"""North-star benchmark (BASELINE.json): tiles/sec for 512x512 uint16
PNG tiles served from a large pyramidal OME-TIFF under concurrent load.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

- value: tiles/sec of the batched TPU pipeline (coalesced batches,
  device byteswap+filter, threaded host deflate) over 1024 requests.
- vs_baseline: speedup over the reference-architecture path measured
  in-process — one request at a time, single-threaded, host-only
  (read -> numpy filter -> zlib), i.e. the shape of the reference's
  per-request Java worker (TileRequestHandler.java:80-139). The Java
  service itself is not runnable in this environment (BASELINE.md:
  baseline must be measured); this stand-in preserves its execution
  structure on identical inputs.
- extra keys: http_tiles_per_sec + p50_ms/p99_ms measured through the
  FULL stack (aiohttp client over a real socket -> session middleware
  -> event bus -> batcher -> pipeline), and a `device` object with the
  accelerator-engine sub-run (recorded even when the tunneled link
  makes it slower; `engine: auto` rightly picks host then).

Robustness contract: this script must NEVER exit non-zero because a
TPU runtime failed to initialize — every jax touchpoint is guarded and
degrades to the host engine, which needs no jax at all
(VERDICT r2 item 1: BENCH_r02 died at an unguarded
jax.default_backend()).

All progress chatter goes to stderr; stdout carries only the JSON line.
"""

import asyncio
import json
import os
import sys
import tempfile
import time
import zlib

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def jax_backend_info() -> dict:
    """Bounded backend probe (a wedged TPU tunnel HANGS PJRT init, so
    this must not touch jax in-process); never raises."""
    from omero_ms_pixel_buffer_tpu.runtime.device_probe import probe

    return dict(probe())


def build_fixture(root: str, size: int = 8192):
    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff

    path = os.path.join(root, f"bench_{size}.ome.tiff")
    if os.path.exists(path):
        return path
    log(f"writing {size}x{size} uint16 fixture...")
    rng = np.random.default_rng(42)
    # smooth-ish synthetic microscopy-like data (compresses realistically,
    # unlike white noise)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    base = (
        2000
        + 1500 * np.sin(xx / 97.0)
        + 1500 * np.cos(yy / 131.0)
    )
    data = (base + rng.normal(0, 120, (size, size))).clip(0, 65535)
    data = data.astype(np.uint16)[None, None, None]
    write_ome_tiff(path, data, tile_size=(512, 512), compression="zlib")
    return path


def make_ctxs(n, size, tile=512, fmt="png", seed=7):
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

    rng = np.random.default_rng(seed)
    ctxs = []
    for _ in range(n):
        x = int(rng.integers(0, (size - tile) // 64)) * 64
        y = int(rng.integers(0, (size - tile) // 64)) * 64
        ctxs.append(
            TileCtx(
                image_id=1, z=0, c=0, t=0,
                region=RegionDef(x, y, tile, tile),
                format=fmt, omero_session_key="bench",
            )
        )
    return ctxs


def run_batched(pipe, ctxs, batch):
    """Drive handle_batch over all ctxs; returns tiles/s."""
    t0 = time.perf_counter()
    done = 0
    for i in range(0, len(ctxs), batch):
        chunk = ctxs[i : i + batch]
        results = pipe.handle_batch(chunk)
        assert all(r is not None for r in results), "bench tile failed"
        done += len(chunk)
    return done / (time.perf_counter() - t0)


def bench_http(
    path: str, n_requests: int, concurrency: int, engine: str = "auto"
) -> dict:
    """Full-stack latency: a lean hand-rolled HTTP client over a real
    localhost socket -> tracing middleware -> session middleware ->
    bus.request -> BatchingTileWorker -> TilePipeline. The reference's
    hot path
    (TileRequestHandler.java:80-139) ran per-request on a worker
    thread behind Vert.x; this measures our complete analog.

    ``engine`` must be the probe-gated value computed in main(), NOT
    re-read from the environment: BENCH_ENGINE=device on a wedged TPU
    would otherwise hang this section at in-process PJRT init before
    the bounded device child ever runs.

    The client is hand-rolled over raw asyncio streams (keep-alive,
    minimal HTTP/1.1 parsing): the client shares the server's core(s)
    in this in-process measurement, so a heavyweight client library
    would bill its own parsing against the server's throughput."""
    from aiohttp import web

    from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
    from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    registry = ImageRegistry()
    registry.add(1, path)
    config = Config.from_dict(
        {
            "session-store": {"type": "memory"},
            "backend": {"engine": engine},
        }
    )
    service = PixelsService(registry)
    app_obj = PixelBufferApp(
        config,
        pixels_service=service,
        session_store=MemorySessionStore({"bench-cookie": "bench-key"}),
    )
    size = int(os.environ.get("BENCH_IMAGE_SIZE", "8192"))
    rng = np.random.default_rng(11)
    urls = []
    for _ in range(n_requests):
        x = int(rng.integers(0, (size - 512) // 64)) * 64
        y = int(rng.integers(0, (size - 512) // 64)) * 64
        urls.append(
            f"/tile/1/0/0/0?x={x}&y={y}&w=512&h=512&format=png"
        )
    # warmup covers every storage chunk once (chunk-aligned sweep):
    # the pipeline-direct headline amortizes first-touch decode over
    # 2x the requests, so a random warmup would bill the HTTP section
    # asymmetrically for cache misses instead of serving
    warm_urls = [
        f"/tile/1/0/0/0?x={x}&y={y}&w=512&h=512&format=png"
        for y in range(0, size - 511, 512)
        for x in range(0, size - 511, 512)
    ]

    async def run() -> dict:
        runner = web.AppRunner(app_obj.make_app(), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        latencies = []

        async def drive(request_urls):
            """``concurrency`` keep-alive connections, each a worker
            draining the shared URL queue."""
            queue: asyncio.Queue = asyncio.Queue()
            for u in request_urls:
                queue.put_nowait(u)
            for _ in range(concurrency):
                queue.put_nowait(None)

            async def worker():
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port
                )
                try:
                    while True:
                        url = await queue.get()
                        if url is None:
                            return
                        t0 = time.perf_counter()
                        writer.write(
                            f"GET {url} HTTP/1.1\r\n"
                            "Host: bench\r\n"
                            "Cookie: sessionid=bench-cookie\r\n"
                            "\r\n".encode()
                        )
                        await writer.drain()
                        status_line = await reader.readline()
                        status = int(status_line.split()[1])
                        clen = 0
                        while True:
                            line = await reader.readline()
                            if line in (b"\r\n", b""):
                                break
                            if line.lower().startswith(b"content-length:"):
                                clen = int(line.split(b":", 1)[1])
                        body = await reader.readexactly(clen)
                        assert status == 200, (status, body[:200])
                        latencies.append(time.perf_counter() - t0)
                finally:
                    writer.close()

            await asyncio.gather(*(worker() for _ in range(concurrency)))

        try:
            # warmup: engine resolution, jit, native build, and one
            # full chunk-coverage sweep so the timed phase measures
            # steady-state serving
            await drive(warm_urls)
            latencies.clear()
            t0 = time.perf_counter()
            await drive(urls)
            elapsed = time.perf_counter() - t0
        finally:
            await runner.cleanup()
            service.close()  # idempotent (app cleanup also closes it)
        lat_ms = np.array(latencies) * 1000.0
        return {
            "http_tiles_per_sec": round(len(urls) / elapsed, 2),
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
            "concurrency": concurrency,
            "engine": app_obj.pipeline.engine,
        }

    return asyncio.run(run())


def bench_cache(
    path: str, n_tiles: int = 192, concurrency: int = 1,
    engine: str = "host",
) -> dict:
    """Cache warm-pass mode: the same full HTTP stack as bench_http
    but with the tiered tile-result cache enabled. Pass 1 (cold)
    renders and memoizes a unique tile set; pass 2 (warm) replays the
    identical URLs. Records the hit ratio and the p50/p99 delta — the
    repeated-tile serving story — and verifies every warm body is
    byte-identical to its cold twin (the correctness bar: a cache that
    alters bytes is worse than no cache).

    Default concurrency is 1: this section is a LATENCY probe (what
    one viewer feels per tile, cold vs hit), so it must not run at
    saturation — at high concurrency both passes measure queueing on
    the shared loop, not the path under test. bench_http carries the
    throughput story; BENCH_CACHE_CONCURRENCY overrides."""
    import hashlib

    from aiohttp import web

    from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
    from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    registry = ImageRegistry()
    registry.add(1, path)
    config = Config.from_dict(
        {
            "session-store": {"type": "memory"},
            "backend": {"engine": engine},
            "cache": {"memory-mb": 512,
                      # the bench replays exact URLs; speculative
                      # neighbors would blur the hit-ratio reading
                      "prefetch": {"enabled": False}},
        }
    )
    service = PixelsService(registry)
    app_obj = PixelBufferApp(
        config,
        pixels_service=service,
        session_store=MemorySessionStore({"bench-cookie": "bench-key"}),
    )
    size = int(os.environ.get("BENCH_IMAGE_SIZE", "8192"))
    rng = np.random.default_rng(29)
    urls = []
    seen = set()
    while len(urls) < n_tiles:
        x = int(rng.integers(0, (size - 512) // 64)) * 64
        y = int(rng.integers(0, (size - 512) // 64)) * 64
        if (x, y) not in seen:  # unique tiles: pass 1 is all misses
            seen.add((x, y))
            urls.append(
                f"/tile/1/0/0/0?x={x}&y={y}&w=512&h=512&format=png"
            )

    async def run() -> dict:
        runner = web.AppRunner(app_obj.make_app(), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]

        async def drive(request_urls):
            latencies, digests = [], {}
            queue: asyncio.Queue = asyncio.Queue()
            for u in request_urls:
                queue.put_nowait(u)
            for _ in range(concurrency):
                queue.put_nowait(None)

            async def worker():
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port
                )
                try:
                    while True:
                        url = await queue.get()
                        if url is None:
                            return
                        t0 = time.perf_counter()
                        writer.write(
                            f"GET {url} HTTP/1.1\r\n"
                            "Host: bench\r\n"
                            "Cookie: sessionid=bench-cookie\r\n"
                            "\r\n".encode()
                        )
                        await writer.drain()
                        status_line = await reader.readline()
                        status = int(status_line.split()[1])
                        clen = 0
                        while True:
                            line = await reader.readline()
                            if line in (b"\r\n", b""):
                                break
                            if line.lower().startswith(
                                b"content-length:"
                            ):
                                clen = int(line.split(b":", 1)[1])
                        body = await reader.readexactly(clen)
                        assert status == 200, (status, body[:200])
                        latencies.append(time.perf_counter() - t0)
                        digests[url] = hashlib.sha1(body).hexdigest()
                finally:
                    writer.close()

            await asyncio.gather(
                *(worker() for _ in range(concurrency))
            )
            return latencies, digests

        try:
            # engine/jit/native warmup outside the timed passes
            await drive(urls[:concurrency])
            cold_lat, cold_digests = await drive(urls)
            # hit ratio reads the WARM pass only
            app_obj.result_cache.memory.hits = 0
            app_obj.result_cache.memory.misses = 0
            warm_lat, warm_digests = await drive(urls)
        finally:
            await runner.cleanup()
            service.close()
        mem = app_obj.result_cache.memory.snapshot()
        cold = np.array(cold_lat) * 1000.0
        warm = np.array(warm_lat) * 1000.0
        identical = cold_digests == warm_digests
        p50_cold = float(np.percentile(cold, 50))
        p50_warm = float(np.percentile(warm, 50))
        return {
            "tiles": len(urls),
            "hit_ratio": round(
                mem["hits"] / max(1, mem["hits"] + mem["misses"]), 4
            ),
            "p50_cold_ms": round(p50_cold, 3),
            "p99_cold_ms": round(float(np.percentile(cold, 99)), 3),
            "p50_warm_ms": round(p50_warm, 3),
            "p99_warm_ms": round(float(np.percentile(warm, 99)), 3),
            "p50_speedup": round(p50_cold / max(p50_warm, 1e-6), 2),
            "identical_bytes": identical,
        }

    return asyncio.run(run())


def bench_cache_plane(path: str, cache_dir: str) -> dict:
    """Cache plane (r11) section — three pins:

    - ``warm_restart``: fill a disk-spilling result cache, close it,
      reopen, and measure the hit rate of the first 100 requests with
      the manifest journal vs the legacy sweep (which is 0 by
      construction);
    - ``l2``: round-trip p50/p99 against the in-memory RESP stub
      (the protocol + framing cost floor — a real Redis adds wire
      latency on top);
    - ``two_replica``: TWO in-process app replicas with a shared ring
      + L2 serve a shared unique-tile workload; pins the render-once
      acceptance number (total renders across both processes ==
      unique tiles) and that both replicas answered with one ETag per
      tile.
    """
    import hashlib  # noqa: F401  (parity with bench_cache imports)
    import socket

    from aiohttp import ClientSession, web

    from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
    from omero_ms_pixel_buffer_tpu.cache.plane.l2 import RedisL2Tier
    from omero_ms_pixel_buffer_tpu.cache.plane.resp_stub import (
        InMemoryRespServer,
    )
    from omero_ms_pixel_buffer_tpu.cache.result_cache import (
        CachedTile,
        TileResultCache,
    )
    from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    out: dict = {}

    # -- warm restart (manifest on vs off) -----------------------------
    def restart_hit_rate(manifest: bool, tag: str) -> float:
        spill = os.path.join(cache_dir, f"plane_spill_{tag}")
        body = os.urandom(4096)
        cache = TileResultCache(
            memory_bytes=64 << 10, disk_dir=spill,
            disk_bytes=64 << 20, manifest=manifest,
        )

        async def fill():
            for i in range(150):
                await cache.put(
                    f"img=1|z=0|c=0|t=0|x={i}|q=bench",
                    CachedTile(body, filename="b.png"),
                )

        asyncio.run(fill())
        cache._io.submit(lambda: None).result()  # drain spills
        cache.close()
        reborn = TileResultCache(
            memory_bytes=64 << 10, disk_dir=spill,
            disk_bytes=64 << 20, manifest=manifest,
        )

        async def probe() -> int:
            hits = 0
            for i in range(100):
                key = f"img=1|z=0|c=0|t=0|x={i}|q=bench"
                if await reborn.get(key) is not None:
                    hits += 1
            return hits

        hits = asyncio.run(probe())
        reborn.close()
        return hits / 100.0

    out["warm_restart"] = {
        "first_100_hit_rate_manifest": restart_hit_rate(True, "on"),
        "first_100_hit_rate_sweep": restart_hit_rate(False, "off"),
    }

    # -- L2 round trip -------------------------------------------------
    async def l2_round_trip() -> dict:
        server = InMemoryRespServer()
        await server.start()
        tier = RedisL2Tier(server.uri)
        body = os.urandom(32 << 10)  # a typical encoded-tile size
        entry = CachedTile(body, filename="b.png")
        lat = []
        try:
            for i in range(50):
                await tier.put(f"img=9|x={i}|q=bench", entry)
            for _ in range(4):  # warm
                await tier.get("img=9|x=0|q=bench")
            for i in range(200):
                t0 = time.perf_counter()
                got = await tier.get(f"img=9|x={i % 50}|q=bench")
                lat.append(time.perf_counter() - t0)
                assert got is not None and got.body == body
        finally:
            await tier.close()
            await server.close()
        ms = np.array(lat) * 1000.0
        return {
            "round_trips": len(lat),
            "p50_ms": round(float(np.percentile(ms, 50)), 3),
            "p99_ms": round(float(np.percentile(ms, 99)), 3),
        }

    out["l2"] = asyncio.run(l2_round_trip())

    # -- two-replica render-once ---------------------------------------
    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    async def two_replica() -> dict:
        resp = InMemoryRespServer()
        await resp.start()
        ports = [free_port(), free_port()]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        replicas, runners, renders = [], [], []
        for i, port in enumerate(ports):
            registry = ImageRegistry()
            registry.add(1, path)
            config = Config.from_dict({
                "session-store": {"type": "memory"},
                "backend": {"engine": "host",
                            "batching": {"coalesce-window-ms": 1.0}},
                "cache": {"prefetch": {"enabled": False}},
                "cluster": {
                    "members": members, "self": members[i],
                    "peer-timeout-ms": 5000,
                    "l2": {"uri": resp.uri},
                },
            })
            app_obj = PixelBufferApp(
                config,
                pixels_service=PixelsService(registry),
                session_store=MemorySessionStore(
                    {"bench-cookie": "bench-key"}
                ),
            )
            counter: list = []

            def wrap(app=app_obj, counter=counter):
                inner_h, inner_b = (
                    app.pipeline.handle, app.pipeline.handle_batch
                )
                app.pipeline.handle = lambda c: (
                    counter.append(1), inner_h(c)
                )[1]
                app.pipeline.handle_batch = lambda cs: (
                    counter.extend([1] * len(cs)), inner_b(cs)
                )[1]

            wrap()
            renders.append(counter)
            runner = web.AppRunner(app_obj.make_app(), access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", port)
            await site.start()
            replicas.append(app_obj)
            runners.append(runner)
        size = int(os.environ.get("BENCH_IMAGE_SIZE", "8192"))
        n_tiles = 24
        urls = [
            f"/tile/1/0/0/0?x={(i % 8) * 512}&y={(i // 8) * 512}"
            "&w=512&h=512&format=png"
            for i in range(n_tiles)
        ]
        assert (max(8, n_tiles // 8) * 512) <= size
        etags: dict = {}
        identical = True
        headers = {"Cookie": "sessionid=bench-cookie"}
        try:
            async with ClientSession() as http:
                for i, url in enumerate(urls):
                    first = members[i % 2]
                    second = members[(i + 1) % 2]
                    async with http.get(
                        first + url, headers=headers
                    ) as r1:
                        assert r1.status == 200, await r1.text()
                        etag1 = r1.headers["ETag"]
                    async with http.get(
                        second + url, headers=headers
                    ) as r2:
                        assert r2.status == 200
                        etag2 = r2.headers["ETag"]
                    identical = identical and (etag1 == etag2)
                    etags[url] = etag1
        finally:
            for runner in runners:
                await runner.cleanup()
            await resp.close()
        total = sum(len(c) for c in renders)
        return {
            "unique_tiles": n_tiles,
            "total_renders": total,
            "render_once": total == n_tiles,
            "identical_etags": identical,
        }

    out["two_replica"] = asyncio.run(two_replica())
    return out


def bench_cluster(cache_dir: str) -> dict:
    """Cluster coordination plane (r17) section — three measurements,
    two hard pins:

    - ``failover``: a three-replica cluster (leases + replication
      factor 2) serves a hot set twice, the owner of part of it is
      KILLED and the shared L2 flushed (so only pushed replicas can
      answer); the ring rebuild maps each orphaned key to exactly the
      successor holding its replica. Pin ``cluster_ok_failover_hits``:
      >= 0.8 post-crash hit rate on the replicated hot set (the
      replication-factor-1 control records the ~0 baseline).
    - ``join``: a cold replica joins a warm cluster; seconds until its
      local cache holds >= 90% of the hot set via the one-round
      warm-up transfer (pinned <= 5 s — one transfer round, not an
      organic re-render).
    - ``hedge``: cold misses against a wedged owner, hedged vs
      unhedged p99. Pin ``cluster_ok_hedge_p99``: hedging must cut
      the wedged-owner p99 to < 70% of the unhedged tail.
    """
    import socket

    from aiohttp import ClientSession, web

    from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
    from omero_ms_pixel_buffer_tpu.cache.plane.resp_stub import (
        InMemoryRespServer,
    )
    from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.tile_ctx import TileCtx
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    out: dict = {}
    headers = {"Cookie": "sessionid=bench-cookie"}
    img_path = os.path.join(cache_dir, "cluster_fixture.ome.tiff")
    if not os.path.exists(img_path):
        rng_local = np.random.default_rng(23)
        img = rng_local.integers(
            0, 60000, (1, 1, 1, 512, 512), dtype=np.uint16
        )
        write_ome_tiff(
            img_path, img, tile_size=(64, 64), pyramid_levels=2
        )

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def tile_paths(n):
        return [
            f"/tile/1/0/0/0?x={64 * (i % 8)}&y={64 * (i // 8)}"
            "&w=64&h=64&format=png"
            for i in range(n)
        ]

    async def boot(members, self_url, port, resp_uri, extra):
        registry = ImageRegistry()
        registry.add(1, img_path)
        cluster_block = {
            "members": members, "self": self_url,
            "peer-timeout-ms": 3000, **(extra or {}),
        }
        if resp_uri:
            cluster_block["l2"] = {"uri": resp_uri}
        config = Config.from_dict({
            "session-store": {"type": "memory"},
            "backend": {"batching": {"coalesce-window-ms": 1.0}},
            "cache": {"prefetch": {"enabled": False}},
            "cluster": cluster_block,
        })
        app_obj = PixelBufferApp(
            config,
            pixels_service=PixelsService(registry),
            session_store=MemorySessionStore(
                {"bench-cookie": "bench-key"}
            ),
        )
        runner = web.AppRunner(app_obj.make_app(), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", port)
        await site.start()
        return app_obj, runner

    def key_for(app_obj, path):
        query = dict(
            kv.split("=") for kv in path.split("?", 1)[1].split("&")
        )
        _, _, image_id, z, c, t = path.split("?", 1)[0].split("/")
        ctx = TileCtx.from_params(
            {"imageId": image_id, "z": z, "c": c, "t": t, **query},
            None,
        )
        return ctx.cache_key(app_obj.pipeline.encode_signature())

    n_hot = 24

    async def failover(replication_factor: int) -> dict:
        resp = InMemoryRespServer()
        await resp.start()
        ports = [free_port() for _ in range(3)]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        nodes = []
        for i, port in enumerate(ports):
            nodes.append(await boot(
                members, members[i], port, resp.uri,
                {"lease-ttl-s": 0.5,
                 "replication-factor": replication_factor},
            ))
        try:
            await asyncio.sleep(0.4)  # leases discovered
            paths = tile_paths(n_hot)
            async with ClientSession() as http:
                for path in paths:
                    key = key_for(nodes[0][0], path)
                    owner_url = nodes[0][0].cache_plane.ring.owner(key)
                    owner = next(
                        a for a, _r in nodes
                        if a.cache_plane.self_url == owner_url
                    )
                    base = owner.cache_plane.self_url
                    for _ in range(2):  # second touch crosses hot bar
                        async with http.get(
                            base + path, headers=headers
                        ) as r:
                            assert r.status == 200, await r.text()
                await asyncio.sleep(0.6)  # pushes drain
                victim_app, victim_runner = nodes[0]
                victim_url = victim_app.cache_plane.self_url
                survivors = nodes[1:]
                victim_paths = [
                    p for p in paths
                    if survivors[0][0].cache_plane.ring.owner(
                        key_for(survivors[0][0], p)
                    ) == victim_url
                ]
                await victim_runner.cleanup()
                for key in [
                    k for k in resp.data
                    if k.startswith(b"ompb:tile:")
                ]:
                    del resp.data[key]  # L2 cold: replicas or nothing
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if all(
                        len(a.cache_plane.membership.members) == 2
                        for a, _r in survivors
                    ):
                        break
                    await asyncio.sleep(0.1)
                hits = 0
                for path in victim_paths:
                    key = key_for(survivors[0][0], path)
                    new_owner_url = (
                        survivors[0][0].cache_plane.ring.owner(key)
                    )
                    new_owner = next(
                        a for a, _r in survivors
                        if a.cache_plane.self_url == new_owner_url
                    )
                    async with http.get(
                        new_owner.cache_plane.self_url + path,
                        headers=headers,
                    ) as r:
                        assert r.status == 200
                        if r.headers.get("X-Cache") == "hit":
                            hits += 1
            return {
                "orphaned_keys": len(victim_paths),
                "post_crash_hits": hits,
                "hit_rate": round(
                    hits / max(1, len(victim_paths)), 3
                ),
            }
        finally:
            for _a, runner in nodes[1:]:
                await runner.cleanup()
            await resp.close()

    out["failover"] = {
        "replicated": asyncio.run(failover(2)),
        "unreplicated": asyncio.run(failover(1)),
    }

    async def join_warm() -> dict:
        resp = InMemoryRespServer()
        await resp.start()
        ports = [free_port() for _ in range(2)]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        nodes = []
        for i, port in enumerate(ports):
            nodes.append(await boot(
                members, members[i], port, resp.uri,
                {"lease-ttl-s": 0.5, "replication-factor": 2},
            ))
        joiner = None
        try:
            await asyncio.sleep(0.4)
            paths = tile_paths(n_hot)
            async with ClientSession() as http:
                for i, path in enumerate(paths):
                    base = nodes[i % 2][0].cache_plane.self_url
                    async with http.get(
                        base + path, headers=headers
                    ) as r:
                        assert r.status == 200
            port = free_port()
            t0 = time.monotonic()
            joiner = await boot(
                [f"http://127.0.0.1:{port}"],
                f"http://127.0.0.1:{port}", port, resp.uri,
                {"lease-ttl-s": 0.5, "replication-factor": 2},
            )
            target = int(0.9 * n_hot)
            warm_s = None
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if len(joiner[0].result_cache.memory) >= target:
                    warm_s = time.monotonic() - t0
                    break
                await asyncio.sleep(0.05)
            return {
                "hot_set": n_hot,
                "target_entries": target,
                "warm_entries": len(joiner[0].result_cache.memory),
                "join_to_90pct_warm_s": (
                    round(warm_s, 3) if warm_s is not None else None
                ),
            }
        finally:
            if joiner is not None:
                await joiner[1].cleanup()
            for _a, runner in nodes:
                await runner.cleanup()
            await resp.close()

    out["join"] = asyncio.run(join_warm())

    async def hedge_run(enabled: bool) -> dict:
        ports = [free_port() for _ in range(2)]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        extra = {"hedge": {
            "enabled": enabled, "min-ms": 10, "max-ms": 40,
            "fallback-ms": 20,
        }}
        nodes = [
            await boot(members, members[i], ports[i], None, extra)
            for i in range(2)
        ]
        try:
            a_app = nodes[0][0]
            paths = [
                p for p in tile_paths(64)
                if a_app.cache_plane.ring.owner(key_for(a_app, p))
                == members[0]
            ][:24]
            # wedge the owner: every render pays 150 ms
            wedged = nodes[0][0]
            inner_h = wedged.pipeline.handle
            inner_b = wedged.pipeline.handle_batch
            wedged.pipeline.handle = lambda c: (
                time.sleep(0.15), inner_h(c)
            )[1]
            wedged.pipeline.handle_batch = lambda cs: (
                time.sleep(0.15), inner_b(cs)
            )[1]
            lat = []
            async with ClientSession() as http:
                for path in paths:
                    t0 = time.perf_counter()
                    async with http.get(
                        members[1] + path, headers=headers
                    ) as r:
                        assert r.status == 200
                    lat.append(time.perf_counter() - t0)
            ms = np.array(lat) * 1000.0
            return {
                "requests": len(lat),
                "p50_ms": round(float(np.percentile(ms, 50)), 1),
                "p99_ms": round(float(np.percentile(ms, 99)), 1),
            }
        finally:
            for _a, runner in nodes:
                await runner.cleanup()

    # unhedged FIRST: its peer-stage observations are what the hedge
    # policy's p99 then clamps against, mirroring production order
    unhedged = asyncio.run(hedge_run(False))
    hedged = asyncio.run(hedge_run(True))
    out["hedge"] = {"unhedged": unhedged, "hedged": hedged}

    rep_rate = out["failover"]["replicated"]["hit_rate"]
    out["cluster_ok_failover_hits"] = rep_rate >= 0.8
    join_s = out["join"]["join_to_90pct_warm_s"]
    out["cluster_ok_join_warm"] = (
        join_s is not None and join_s <= 5.0
    )
    out["cluster_ok_hedge_p99"] = (
        hedged["p99_ms"] < unhedged["p99_ms"] * 0.7
    )
    return out


def bench_lifecycle(cache_dir: str) -> dict:
    """Fleet lifecycle plane (r18) section — two drives, two pins:

    - ``rolling_restart``: a three-replica cluster (leases +
      replication + graceful drain) is restarted one replica at a
      time under live traffic: each replica drains (lease marker,
      full-RAM handoff, quiesce, lease release), is killed, the
      shared L2's tile keys are FLUSHED (so the handed-off RAM
      copies are the only warm source), and a replacement boots on
      the same identity and warms via the join transfer. Pin
      ``cluster_ok_drain_zero_errors``: ZERO serving 5xx across the
      whole drive AND warm-hit rate >= 0.95 — a planned leave rides
      the warm path, not the crash path (the crash-path bench above
      pins only >= 0.8).
    - ``repair``: a hot entry whose replica push is deliberately
      dropped is healed by the anti-entropy digest exchange. Pin
      ``cluster_ok_repair_convergence``: repaired within ONE
      rotation over the peers (<= 2 rounds in a 3-replica fleet).
    """
    import socket

    from aiohttp import ClientSession, web

    from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
    from omero_ms_pixel_buffer_tpu.cache.plane.resp_stub import (
        InMemoryRespServer,
    )
    from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.tile_ctx import TileCtx
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    out: dict = {}
    headers = {"Cookie": "sessionid=bench-cookie"}
    peer_headers = {**headers, "X-OMPB-Peer": "bench-ops"}
    img_path = os.path.join(cache_dir, "cluster_fixture.ome.tiff")
    if not os.path.exists(img_path):
        rng_local = np.random.default_rng(23)
        img = rng_local.integers(
            0, 60000, (1, 1, 1, 512, 512), dtype=np.uint16
        )
        write_ome_tiff(
            img_path, img, tile_size=(64, 64), pyramid_levels=2
        )

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def tile_paths(n):
        return [
            f"/tile/1/0/0/0?x={64 * (i % 8)}&y={64 * (i // 8)}"
            "&w=64&h=64&format=png"
            for i in range(n)
        ]

    def key_for(app_obj, path):
        query = dict(
            kv.split("=") for kv in path.split("?", 1)[1].split("&")
        )
        _, _, image_id, z, c, t = path.split("?", 1)[0].split("/")
        ctx = TileCtx.from_params(
            {"imageId": image_id, "z": z, "c": c, "t": t, **query},
            None,
        )
        return ctx.cache_key(app_obj.pipeline.encode_signature())

    def lifecycle_block(extra=None):
        return {
            "lease-ttl-s": 0.5, "replication-factor": 2,
            "drain": {"deadline-s": 5, "signal": False},
            **(extra or {}),
        }

    async def boot(members, self_url, port, resp_uri, extra):
        registry = ImageRegistry()
        registry.add(1, img_path)
        cluster_block = {
            "members": members, "self": self_url,
            "peer-timeout-ms": 3000, **(extra or {}),
        }
        if resp_uri:
            cluster_block["l2"] = {"uri": resp_uri}
        config = Config.from_dict({
            "session-store": {"type": "memory"},
            "backend": {"batching": {"coalesce-window-ms": 1.0}},
            "cache": {"prefetch": {"enabled": False}},
            "cluster": cluster_block,
        })
        app_obj = PixelBufferApp(
            config,
            pixels_service=PixelsService(registry),
            session_store=MemorySessionStore(
                {"bench-cookie": "bench-key"}
            ),
        )
        runner = web.AppRunner(app_obj.make_app(), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", port)
        await site.start()
        return app_obj, runner

    n_hot = 16
    warm_sources = ("hit", "l2-hit", "peer-hit")

    async def rolling_restart() -> dict:
        resp = InMemoryRespServer()
        await resp.start()
        ports = [free_port() for _ in range(3)]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        nodes = []
        for i, port in enumerate(ports):
            nodes.append(await boot(
                members, members[i], port, resp.uri,
                lifecycle_block(),
            ))
        statuses: list = []
        sources: list = []
        try:
            await asyncio.sleep(0.4)  # leases discovered
            paths = tile_paths(n_hot)
            async with ClientSession() as http:
                for path in paths:  # warm every replica
                    for app_obj, _r in nodes:
                        async with http.get(
                            app_obj.cache_plane.self_url + path,
                            headers=headers,
                        ) as r:
                            assert r.status == 200, await r.text()

                async def traffic_round(live):
                    for path in paths:
                        for app_obj, _r in live:
                            async with http.get(
                                app_obj.cache_plane.self_url + path,
                                headers=headers,
                            ) as r:
                                await r.read()
                                statuses.append(r.status)
                                sources.append(
                                    r.headers.get("X-Cache")
                                )

                handoff_pushed = 0
                for i in range(3):
                    victim_app, victim_runner = nodes[i]
                    victim_url = victim_app.cache_plane.self_url
                    survivors = [
                        n for j, n in enumerate(nodes) if j != i
                    ]

                    async def _drain():
                        async with http.post(
                            victim_url + "/internal/drain?wait=1",
                            headers=peer_headers,
                        ) as r:
                            return r.status, await r.json()

                    drain_task = asyncio.ensure_future(_drain())
                    while not drain_task.done():
                        await traffic_round(survivors)
                        await asyncio.sleep(0.02)
                    status, drained = await drain_task
                    assert status == 200, drained
                    handoff_pushed += drained["stats"]["handoff"][
                        "pushed"
                    ]
                    await victim_runner.cleanup()
                    for key in [
                        k for k in resp.data
                        if k.startswith(b"ompb:tile:")
                    ]:
                        del resp.data[key]
                    for _ in range(2):
                        await traffic_round(survivors)
                    nodes[i] = await boot(
                        members, victim_url, ports[i], resp.uri,
                        lifecycle_block(),
                    )
                    deadline = time.monotonic() + 6.0
                    while time.monotonic() < deadline:
                        if all(
                            len(a.cache_plane.membership.members) == 3
                            for a, _r in nodes
                        ):
                            break
                        await traffic_round(survivors)
                        await asyncio.sleep(0.1)
            errors = sum(1 for s in statuses if s >= 500)
            warm = sum(1 for s in sources if s in warm_sources)
            return {
                "requests": len(statuses),
                "serving_errors": errors,
                "warm_hits": warm,
                "warm_hit_rate": round(warm / max(1, len(sources)), 3),
                "handoff_pushed": handoff_pushed,
            }
        finally:
            for _a, runner in nodes:
                try:
                    await runner.cleanup()
                except Exception:
                    pass
            await resp.close()

    out["rolling_restart"] = asyncio.run(rolling_restart())

    async def repair_drive() -> dict:
        resp = InMemoryRespServer()
        await resp.start()
        ports = [free_port() for _ in range(3)]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        nodes = []
        for i, port in enumerate(ports):
            nodes.append(await boot(
                members, members[i], port, resp.uri,
                lifecycle_block({"repair": {"interval-s": 60}}),
            ))
        try:
            await asyncio.sleep(0.4)
            apps = {
                a.cache_plane.self_url: a for a, _r in nodes
            }
            plane0 = nodes[0][0].cache_plane
            target = None
            for path in tile_paths(n_hot):
                key = key_for(nodes[0][0], path)
                owners = plane0.ring.owners(key, 2)
                if len(owners) == 2:
                    target = (path, key, owners[0], owners[1])
                    break
            path, key, owner_url, succ_url = target
            owner, succ = apps[owner_url], apps[succ_url]

            async def lost_push(*a, **k):
                return None

            owner.cache_plane._push_replicas = lost_push
            async with ClientSession() as http:
                for _ in range(2):  # second touch crosses the hot bar
                    async with http.get(
                        owner_url + path, headers=headers
                    ) as r:
                        assert r.status == 200
            rounds = 0
            repaired = False
            for _ in range(2):  # one rotation over the peers
                rounds += 1
                await succ.cache_plane.repair_round()
                if succ.result_cache.contains(key):
                    repaired = True
                    break
            return {
                "repaired": repaired,
                "rounds_to_converge": rounds if repaired else None,
                "round_bound": 2,
                "repairer": succ.cache_plane.repairer.snapshot(),
            }
        finally:
            for _a, runner in nodes:
                await runner.cleanup()
            await resp.close()

    out["repair"] = asyncio.run(repair_drive())

    rr = out["rolling_restart"]
    out["cluster_ok_drain_zero_errors"] = (
        rr["serving_errors"] == 0
        and rr["warm_hit_rate"] >= 0.95
        and rr["requests"] > 0
    )
    out["cluster_ok_repair_convergence"] = (
        out["repair"]["repaired"]
        and out["repair"]["rounds_to_converge"]
        <= out["repair"]["round_bound"]
    )
    return out


def bench_decentralized(cache_dir: str) -> dict:
    """Decentralized control plane (r20) section — two drives, two
    pins:

    - ``redisless``: a three-replica GOSSIP cluster (Redis demoted to
      L2 + join hint) is warmed, then the RESP stub is killed
      mid-traffic and the same hot set is driven again. Pin
      ``cluster_ok_redisless_convergence``: every replica's
      membership view stays fully converged through the outage, the
      post-outage warm-hit rate holds >= 0.8, and the whole drive
      serves ZERO 5xx — "Redis down" degrades the shared cache,
      never coordination.
    - ``integrity``: one replica of a gossip+suspicion fleet serves
      bit-flipped bodies under intact ETags (the wrong-but-200 bad-
      RAM failure). Every transfer is discarded at the content-hash
      gate and the strikes feed the suspicion quorum. Pin
      ``cluster_ok_integrity_demotion``: zero wrong bytes reach any
      client, and the corrupt replica is demoted within <= 2 brain
      rounds of the verdict landing (one round to publish the
      verdict over gossip, one for the peers to apply it).
    """
    import socket

    from aiohttp import ClientSession, web

    from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
    from omero_ms_pixel_buffer_tpu.cache.plane.resp_stub import (
        InMemoryRespServer,
    )
    from omero_ms_pixel_buffer_tpu.cache.result_cache import CachedTile
    from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.tile_ctx import TileCtx
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    out: dict = {}
    headers = {"Cookie": "sessionid=bench-cookie"}
    img_path = os.path.join(cache_dir, "cluster_fixture.ome.tiff")
    if not os.path.exists(img_path):
        rng_local = np.random.default_rng(23)
        img = rng_local.integers(
            0, 60000, (1, 1, 1, 512, 512), dtype=np.uint16
        )
        write_ome_tiff(
            img_path, img, tile_size=(64, 64), pyramid_levels=2
        )

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def tile_paths(n):
        return [
            f"/tile/1/0/0/0?x={64 * (i % 8)}&y={64 * (i // 8)}"
            "&w=64&h=64&format=png"
            for i in range(n)
        ]

    def key_for(app_obj, path):
        query = dict(
            kv.split("=") for kv in path.split("?", 1)[1].split("&")
        )
        _, _, image_id, z, c, t = path.split("?", 1)[0].split("/")
        ctx = TileCtx.from_params(
            {"imageId": image_id, "z": z, "c": c, "t": t, **query},
            None,
        )
        return ctx.cache_key(app_obj.pipeline.encode_signature())

    gossip_block = {
        "gossip": {
            "enabled": True, "interval-s": 0.15, "fail-after-s": 1.2,
        },
    }

    async def boot(members, self_url, port, resp_uri, extra):
        registry = ImageRegistry()
        registry.add(1, img_path)
        cluster_block = {
            "members": members, "self": self_url,
            "peer-timeout-ms": 3000, **(extra or {}),
        }
        if resp_uri:
            cluster_block["l2"] = {"uri": resp_uri}
        config = Config.from_dict({
            "session-store": {"type": "memory"},
            "backend": {"batching": {"coalesce-window-ms": 1.0}},
            "cache": {"prefetch": {"enabled": False}},
            "cluster": cluster_block,
        })
        app_obj = PixelBufferApp(
            config,
            pixels_service=PixelsService(registry),
            session_store=MemorySessionStore(
                {"bench-cookie": "bench-key"}
            ),
        )
        runner = web.AppRunner(app_obj.make_app(), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", port)
        await site.start()
        return app_obj, runner

    n_hot = 16
    warm_sources = ("hit", "l2-hit", "peer-hit")

    async def redisless_drive() -> dict:
        resp = InMemoryRespServer()
        await resp.start()
        ports = [free_port() for _ in range(3)]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        nodes = []
        for i, port in enumerate(ports):
            nodes.append(await boot(
                members, members[i], port, resp.uri, gossip_block,
            ))
        statuses: list = []
        post_sources: list = []
        try:
            await asyncio.sleep(0.6)  # gossip rounds seed the view
            paths = tile_paths(n_hot)
            async with ClientSession() as http:
                for path in paths:  # warm every replica
                    for app_obj, _r in nodes:
                        async with http.get(
                            app_obj.cache_plane.self_url + path,
                            headers=headers,
                        ) as r:
                            await r.read()
                            statuses.append(r.status)
                # the coordinator dies mid-traffic
                await resp.close()
                await asyncio.sleep(0.6)  # gossip keeps ticking
                for path in paths:
                    for app_obj, _r in nodes:
                        async with http.get(
                            app_obj.cache_plane.self_url + path,
                            headers=headers,
                        ) as r:
                            await r.read()
                            statuses.append(r.status)
                            post_sources.append(
                                r.headers.get("X-Cache")
                            )
            converged = all(
                len(a.cache_plane.membership.members) == 3
                for a, _r in nodes
            )
            errors = sum(1 for s in statuses if s >= 500)
            warm = sum(1 for s in post_sources if s in warm_sources)
            return {
                "requests": len(statuses),
                "serving_errors": errors,
                "ring_converged_after_outage": converged,
                "post_outage_warm_hit_rate": round(
                    warm / max(1, len(post_sources)), 3
                ),
            }
        finally:
            for _a, runner in nodes:
                try:
                    await runner.cleanup()
                except Exception:
                    pass
            await resp.close()

    out["redisless"] = asyncio.run(redisless_drive())

    async def integrity_drive() -> dict:
        ports = [free_port() for _ in range(3)]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        nodes = []
        for i, port in enumerate(ports):
            nodes.append(await boot(
                members, members[i], port, None,
                {**gossip_block, "suspect": {"enabled": True}},
            ))
        victim_app = nodes[2][0]
        victim_url = victim_app.cache_plane.self_url
        healthy = [a for a, _r in nodes[:2]]
        try:
            await asyncio.sleep(0.6)
            paths = tile_paths(n_hot)
            baseline = {}
            wrong_bytes = 0
            async with ClientSession() as http:
                # baseline through the honest victim: it caches its
                # owned keys, the healthy replicas only their own
                for path in paths:
                    async with http.get(
                        victim_url + path, headers=headers
                    ) as r:
                        baseline[path] = await r.read()
                # bad-RAM lever: victim serves flipped bytes under
                # the ORIGINAL ETag from here on
                cache = victim_app.result_cache
                inner = cache.get

                async def bad_get(key):
                    entry = await inner(key)
                    if entry is None:
                        return None
                    flipped = (
                        bytes([entry.body[0] ^ 0xFF]) + entry.body[1:]
                    )
                    return CachedTile(
                        flipped, etag=entry.etag,
                        filename=entry.filename,
                        stored_at=entry.stored_at,
                    )

                cache.get = bad_get
                for a in healthy:
                    for path in paths:
                        async with http.get(
                            a.cache_plane.self_url + path,
                            headers=headers,
                        ) as r:
                            if await r.read() != baseline[path]:
                                wrong_bytes += 1

                async def _verdicts():
                    while not all(
                        victim_url in a.cache_plane.brains.my_verdicts
                        for a in healthy
                    ):
                        await asyncio.sleep(0.02)

                await asyncio.wait_for(_verdicts(), 10.0)
                base_rounds = {
                    a: a.cache_plane.membership.refreshes
                    for a in healthy
                }
                demote_rounds: dict = {}

                async def _demoted():
                    while len(demote_rounds) < len(healthy):
                        for a in healthy:
                            if a in demote_rounds:
                                continue
                            if victim_url in a.cache_plane.brains.demoted:
                                demote_rounds[a] = (
                                    a.cache_plane.membership.refreshes
                                    - base_rounds[a]
                                )
                        await asyncio.sleep(0.02)

                await asyncio.wait_for(_demoted(), 10.0)
                # the re-homed keys still serve correct bytes
                for a in healthy:
                    for path in paths[:4]:
                        async with http.get(
                            a.cache_plane.self_url + path,
                            headers=headers,
                        ) as r:
                            if await r.read() != baseline[path]:
                                wrong_bytes += 1
            strikes = {
                a.cache_plane.self_url:
                    a.cache_plane.corruption.counts().get(victim_url, 0)
                for a in healthy
            }
            return {
                "wrong_bytes_served": wrong_bytes,
                "demoted": True,
                "rounds_to_demote": max(demote_rounds.values()),
                "round_bound": 2,
                "integrity_strikes": strikes,
            }
        finally:
            for _a, runner in nodes:
                try:
                    await runner.cleanup()
                except Exception:
                    pass

    out["integrity"] = asyncio.run(integrity_drive())

    rl = out["redisless"]
    out["cluster_ok_redisless_convergence"] = (
        rl["serving_errors"] == 0
        and rl["ring_converged_after_outage"]
        and rl["post_outage_warm_hit_rate"] >= 0.8
        and rl["requests"] > 0
    )
    it = out["integrity"]
    out["cluster_ok_integrity_demotion"] = (
        it["wrong_bytes_served"] == 0
        and it["demoted"]
        and it["rounds_to_demote"] <= it["round_bound"]
    )
    return out


def bench_session(cache_dir: str) -> dict:
    """Interactive session plane (r22) section — two drives, two pins:

    - ``push``: a two-replica pair; a WebSocket channel subscribed on
      replica B while annotation writes land on replica A. Each
      write's invalidation rides the purge fan-out to B and is pushed
      down the channel — the measured write->frame latency is the
      delta path end to end, cross-replica. Pin
      ``session_ok_push_latency``: every delta arrives, p99 under
      1000 ms (a TTL-polling viewer would wait a cache TTL — tens of
      seconds — to learn the same fact).
    - ``drain``: replica A drains while holding 10 live channels and
      serving tile traffic. Every channel must receive an explicit
      ``{"reconnect": successor}`` frame before its close, the
      successor must absorb the subscription summary, and the tile
      traffic must see zero 5xx. Pin ``session_ok_drain_zero_drops``:
      reconnect frames == channels, absorbed == channels, zero 5xx.
    """
    import socket

    from aiohttp import ClientSession, WSMsgType, web

    from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
    from omero_ms_pixel_buffer_tpu.cache.plane.resp_stub import (
        InMemoryRespServer,
    )
    from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    out: dict = {}
    headers = {"Cookie": "sessionid=bench-cookie"}
    peer_headers = {**headers, "X-OMPB-Peer": "bench-ops"}
    img_path = os.path.join(cache_dir, "session_fixture.ome.tiff")
    if not os.path.exists(img_path):
        rng_local = np.random.default_rng(29)
        img = rng_local.integers(
            0, 60000, (1, 1, 1, 256, 256), dtype=np.uint16
        )
        write_ome_tiff(img_path, img, tile_size=(64, 64))

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    async def boot(members, self_url, port, resp_uri=None, extra=None):
        registry = ImageRegistry()
        registry.add(1, img_path)
        cluster_block = {
            "members": members, "self": self_url,
            "peer-timeout-ms": 3000, **(extra or {}),
        }
        if resp_uri:
            cluster_block["l2"] = {"uri": resp_uri}
        config = Config.from_dict({
            "session-store": {"type": "memory"},
            "backend": {"batching": {"coalesce-window-ms": 1.0}},
            "cache": {"prefetch": {"enabled": False}},
            "cluster": cluster_block,
        })
        app_obj = PixelBufferApp(
            config,
            pixels_service=PixelsService(registry),
            session_store=MemorySessionStore(
                {"bench-cookie": "bench-key"}
            ),
        )
        runner = web.AppRunner(app_obj.make_app(), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", port)
        await site.start()
        return app_obj, runner

    async def recv_frame(ws, timeout=10.0):
        msg = await asyncio.wait_for(ws.receive(), timeout)
        if msg.type != WSMsgType.TEXT:
            return None
        return json.loads(msg.data)

    n_writes = 20

    async def push_drive() -> dict:
        ports = [free_port() for _ in range(2)]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        nodes = []
        for i, port in enumerate(ports):
            nodes.append(await boot(members, members[i], port))
        url_a, url_b = members
        latencies: list = []
        delivered = 0
        try:
            async with ClientSession() as http:
                ws = await asyncio.wait_for(
                    http.ws_connect(
                        url_b + "/session/1/live", headers=headers
                    ), 10.0,
                )
                await recv_frame(ws)  # hello
                shape = {"type": "rect", "x": 4, "y": 4,
                         "w": 16, "h": 16}
                for i in range(n_writes):
                    t0 = time.perf_counter()
                    async with http.post(
                        url_a + "/annotations/1", headers=headers,
                        json={"shape": shape, "label": f"w{i}"},
                    ) as r:
                        assert r.status == 201, await r.text()
                    frame = await recv_frame(ws, timeout=5.0)
                    if frame is not None and frame.get("type") in (
                        "invalidate", "annotations"
                    ):
                        latencies.append(
                            (time.perf_counter() - t0) * 1000.0
                        )
                        delivered += 1
                    # drain any second frame from the same write (the
                    # local fan-out can produce both kinds) so the
                    # next measurement starts on an empty queue
                    while True:
                        try:
                            msg = await asyncio.wait_for(
                                ws.receive(), 0.05
                            )
                        except asyncio.TimeoutError:
                            break
                        if msg.type != WSMsgType.TEXT:
                            break
                await ws.close()
        finally:
            for _a, runner in nodes:
                await runner.cleanup()
        latencies.sort()
        return {
            "writes": n_writes,
            "delivered": delivered,
            "p50_ms": round(
                latencies[len(latencies) // 2], 2
            ) if latencies else None,
            "p99_ms": round(
                latencies[min(len(latencies) - 1,
                              int(len(latencies) * 0.99))], 2
            ) if latencies else None,
        }

    out["push"] = asyncio.run(push_drive())

    n_channels = 10

    async def drain_drive() -> dict:
        resp = InMemoryRespServer()
        await resp.start()
        ports = [free_port() for _ in range(2)]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        extra = {
            "lease-ttl-s": 0.5,
            "drain": {"deadline-s": 5, "signal": False},
        }
        nodes = []
        for i, port in enumerate(ports):
            nodes.append(await boot(
                members, members[i], port, resp.uri, extra,
            ))
        url_a, url_b = members
        statuses: list = []
        reconnects = 0
        try:
            await asyncio.sleep(0.4)  # leases discovered
            async with ClientSession() as http:
                sockets = []
                for _ in range(n_channels):
                    ws = await asyncio.wait_for(
                        http.ws_connect(
                            url_a + "/session/1/live", headers=headers
                        ), 10.0,
                    )
                    await recv_frame(ws)  # hello
                    sockets.append(ws)

                async def tile_round():
                    for url in (url_a, url_b):
                        async with http.get(
                            url + "/tile/1/0/0/0?w=64&h=64&format=png",
                            headers=headers,
                        ) as r:
                            await r.read()
                            statuses.append(r.status)

                async def _drain():
                    async with http.post(
                        url_a + "/internal/drain?wait=1",
                        headers=peer_headers,
                    ) as r:
                        return r.status, await r.json()

                drain_task = asyncio.ensure_future(_drain())
                while not drain_task.done():
                    await tile_round()
                    await asyncio.sleep(0.02)
                status, drained = await drain_task
                assert status == 200, drained
                for ws in sockets:
                    frame = await recv_frame(ws, timeout=10.0)
                    if frame is not None and \
                            frame.get("type") == "reconnect" and \
                            frame.get("reconnect") == url_b:
                        reconnects += 1
                    await ws.close()
                absorbed = nodes[1][0].session_channels.snapshot()[
                    "handoff_in"
                ]
            return {
                "channels": n_channels,
                "reconnect_frames": reconnects,
                "absorbed_by_successor": absorbed,
                "requests": len(statuses),
                "serving_errors": sum(
                    1 for s in statuses if s >= 500
                ),
                "drain_sessions": drained["stats"]["sessions"],
            }
        finally:
            for _a, runner in nodes:
                try:
                    await runner.cleanup()
                except Exception:
                    pass
            await resp.close()

    out["drain"] = asyncio.run(drain_drive())

    push = out["push"]
    out["session_ok_push_latency"] = (
        push["delivered"] == push["writes"]
        and push["p99_ms"] is not None
        and push["p99_ms"] < 1000.0
    )
    dr = out["drain"]
    out["session_ok_drain_zero_drops"] = (
        dr["reconnect_frames"] == dr["channels"]
        and dr["absorbed_by_successor"] == dr["channels"]
        and dr["serving_errors"] == 0
        and dr["requests"] > 0
    )
    return out


def bench_ingest(cache_dir: str) -> dict:
    """Ingest plane (r24) section — write-while-serve, two pins:

    - ``read_p99``: one node serving a tile read loop, first alone
      (baseline), then with a writer PUTting tiles through
      ``/image/{id}/tile`` the whole time. Every read must succeed and
      the concurrent read p99 must stay within 1.5x of the read-only
      baseline (with a small absolute floor so a sub-millisecond
      warm-cache baseline doesn't turn the ratio into noise). Pin
      ``ingest_ok_read_p99``.
    - ``invalidation``: after each committed write, the FIRST read of
      the written region must return the new bytes — the epoch bump
      and purge ride the commit response, so staleness is bounded by
      one epoch round, not a cache TTL. Pin
      ``ingest_ok_invalidation``: zero stale first-reads.
    """
    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
    from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.io.zarr import write_ngff
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    headers = {"Cookie": "sessionid=bench-cookie"}
    img_path = os.path.join(cache_dir, "ingest_fixture.zarr")
    rng_local = np.random.default_rng(31)
    img = rng_local.integers(
        0, 4096, (1, 1, 1, 256, 256), dtype=np.uint16
    )
    if not os.path.exists(img_path):
        write_ngff(
            img_path, img, chunks=(64, 64), levels=1,
            zarr_format=3, shards=(128, 128),
        )

    n_reads = int(os.environ.get("BENCH_INGEST_READS", "300"))
    n_writes = int(os.environ.get("BENCH_INGEST_WRITES", "40"))

    async def drive() -> dict:
        registry = ImageRegistry()
        registry.add(1, img_path)
        config = Config.from_dict({
            "session-store": {"type": "memory"},
            "backend": {"batching": {"coalesce-window-ms": 1.0}},
            "ingest": {"enabled": True},
        })
        app_obj = PixelBufferApp(
            config,
            pixels_service=PixelsService(registry),
            session_store=MemorySessionStore(
                {"bench-cookie": "bench-key"}
            ),
        )
        client = TestClient(
            TestServer(app_obj.make_app()),
            loop=asyncio.get_running_loop(),
        )
        await client.start_server()
        tiles = [(x, y) for x in (0, 64, 128) for y in (0, 64, 128)]
        try:
            async def read_loop(n, latencies, statuses):
                for i in range(n):
                    x, y = tiles[i % len(tiles)]
                    t0 = time.perf_counter()
                    r = await client.get(
                        f"/tile/1/0/0/0?x={x}&y={y}&w=64&h=64",
                        headers=headers,
                    )
                    await r.read()
                    statuses.append(r.status)
                    latencies.append(
                        (time.perf_counter() - t0) * 1000.0
                    )

            # baseline: the read loop alone
            base_lat: list = []
            base_status: list = []
            await read_loop(n_reads, base_lat, base_status)

            # concurrent: same loop with a writer alongside
            write_status: list = []

            async def write_loop():
                tile = np.full((64, 64), 7, dtype=np.uint16)
                for i in range(n_writes):
                    tile[...] = i
                    r = await client.put(
                        f"/image/1/tile/0/0/0"
                        f"?x={(i % 3) * 64}&y=64&w=64&h=64",
                        data=tile.astype(">u2").tobytes(),
                        headers=headers,
                    )
                    await r.read()
                    write_status.append(r.status)
                    await asyncio.sleep(0)

            conc_lat: list = []
            conc_status: list = []
            writer = asyncio.ensure_future(write_loop())
            await read_loop(n_reads, conc_lat, conc_status)
            await writer

            # invalidation: first read after each commit must be fresh
            stale = 0
            for i in range(n_writes):
                tile = np.full((64, 64), 100 + i, dtype=np.uint16)
                wire = tile.astype(">u2").tobytes()
                r = await client.put(
                    "/image/1/tile/0/0/0?x=128&y=128&w=64&h=64",
                    data=wire, headers=headers,
                )
                await r.read()
                assert r.status == 200
                r = await client.get(
                    "/tile/1/0/0/0?x=128&y=128&w=64&h=64",
                    headers=headers,
                )
                if await r.read() != wire:
                    stale += 1

            def p99(lat):
                lat = sorted(lat)
                return round(
                    lat[min(len(lat) - 1, int(len(lat) * 0.99))], 2
                )

            return {
                "reads": n_reads,
                "writes": n_writes,
                "baseline_read_p99_ms": p99(base_lat),
                "concurrent_read_p99_ms": p99(conc_lat),
                "read_errors": sum(
                    1 for s in base_status + conc_status if s >= 500
                ),
                "write_errors": sum(
                    1 for s in write_status if s != 200
                ),
                "stale_first_reads": stale,
            }
        finally:
            await client.close()

    out = asyncio.run(drive())
    out["ingest_ok_read_p99"] = (
        out["read_errors"] == 0
        and out["write_errors"] == 0
        and out["concurrent_read_p99_ms"] <= max(
            1.5 * out["baseline_read_p99_ms"], 25.0
        )
    )
    out["ingest_ok_invalidation"] = out["stale_first_reads"] == 0
    return out


def bench_overload(
    cache_dir: str,
    duration_s: float = 4.0,
    capacity: int = 2,
    queue_size: int = 6,
    service_ms: float = 25.0,
    budget_ms: float = 300.0,
    degrade_factor: float = 6.0,
    interactive_p99_bound_ms: float = 0.0,
) -> dict:
    """Sustained-overload SLO scenario (r13): mixed-class closed-loop
    load at ~2x admission capacity against the deadline-ordered
    scheduler, asserting *SLO outcomes* — interactive p99 and
    degraded-fraction per class — instead of throughput alone.

    Shape: a pyramidal NGFF image behind the full app (cache OFF so
    every request exercises the scheduler + pipeline; the pipeline is
    slowed a deterministic ``service_ms`` per tile so capacity is a
    controlled constant). 10 closed-loop clients — 5 interactive,
    3 prefetch-labelled, 2 bulk-labelled — sustain well past 2x the
    admission capacity (5x the executing slots, 1.25x what slots +
    wait queue absorb), so the queue is genuinely full for the whole
    window and the shed policy is continuously exercised.
    ``queue_size`` deliberately exceeds the interactive client count:
    an interactive arrival can then always evict a lower-class waiter,
    so any interactive 503 is a scheduler bug, not a sizing artifact
    (and lower classes still shed, because slots + queue < total
    clients).

    The three pins (recorded as slo_ok_* booleans; the CI smoke fails
    on them):
    - zero interactive 503s while lower classes still had sheddable
      work (the scheduler's core promise);
    - interactive p99 within ``interactive_p99_bound_ms`` (default:
      the request budget — an interactive request either makes its
      deadline or degrades, it never blows through it);
    - degradation engaged (degraded fraction > 0 for interactive)
      and every degraded response is tagged.
    """
    from aiohttp import web

    from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
    from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.io.zarr import write_ngff
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    if not interactive_p99_bound_ms:
        interactive_p99_bound_ms = budget_ms
    size = 1024
    path = os.path.join(cache_dir, "overload_1024.zarr")
    if not os.path.exists(path):
        rng = np.random.default_rng(29)
        img = rng.integers(
            0, 60000, (1, 1, 1, size, size), dtype=np.uint16
        )
        write_ngff(path, img, chunks=(256, 256), levels=3)
    registry = ImageRegistry()
    registry.add(1, path, type="zarr")
    config = Config.from_dict(
        {
            "session-store": {"type": "memory"},
            "worker_pool_size": capacity,
            "backend": {"batching": {"max-batch": 1,
                                     "coalesce-window-ms": 0.0}},
            "cache": {"enabled": False},
            "resilience": {
                "admission": {"max-inflight": capacity},
                "request-budget-ms": budget_ms,
            },
            "slo": {
                "queue-size": queue_size,
                "degrade-factor": degrade_factor,
            },
        }
    )
    service = PixelsService(registry)
    app_obj = PixelBufferApp(
        config,
        pixels_service=service,
        session_store=MemorySessionStore({"bench-cookie": "bench-key"}),
    )
    inner = app_obj.pipeline.handle
    service_s = service_ms / 1000.0

    def slowed(ctx):
        time.sleep(service_s)
        return inner(ctx)

    app_obj.pipeline.handle = slowed

    classes = (
        [("interactive", {})] * 5
        + [("prefetch", {"Sec-Purpose": "prefetch"})] * 3
        + [("bulk", {"X-OMPB-Priority": "bulk"})] * 2
    )
    samples: list = []  # (class, status, latency_s, degraded)

    async def run() -> dict:
        runner = web.AppRunner(app_obj.make_app(), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]

        import aiohttp

        async def worker(idx, cls, extra_headers, warm_only=False):
            # stable per-worker seed: hash() is PYTHONHASHSEED-
            # randomized (a CI flake here would be unreproducible) and
            # a per-class seed would run same-class workers in lockstep
            rng = np.random.default_rng(
                zlib.crc32(f"{cls}-{idx}".encode())
            )
            headers = {"Cookie": "sessionid=bench-cookie"}
            headers.update(extra_headers)
            deadline = time.perf_counter() + duration_s
            async with aiohttp.ClientSession() as sess:
                while time.perf_counter() < deadline:
                    x = int(rng.integers(0, size // 256)) * 256
                    y = int(rng.integers(0, size // 256)) * 256
                    url = (
                        f"http://127.0.0.1:{port}/tile/1/0/0/0"
                        f"?x={x}&y={y}&w=256&h=256&format=png"
                    )
                    t0 = time.perf_counter()
                    async with sess.get(url, headers=headers) as r:
                        await r.read()
                        samples.append((
                            cls, r.status,
                            time.perf_counter() - t0,
                            int(r.headers.get("X-OMPB-Degraded", 0)),
                        ))
                    if warm_only:
                        return

        try:
            # warm: one uncontended request trains the service EWMA
            await worker(0, "interactive", {}, warm_only=True)
            samples.clear()
            await asyncio.gather(*(
                worker(i, cls, hdrs)
                for i, (cls, hdrs) in enumerate(classes)
            ))
        finally:
            await runner.cleanup()
            service.close()

        out: dict = {
            "offered_classes": {"interactive": 5, "prefetch": 3,
                                "bulk": 2},
            "capacity": capacity,
            "queue_size": queue_size,
            "service_ms": service_ms,
            "budget_ms": budget_ms,
            "duration_s": duration_s,
        }
        for cls in ("interactive", "prefetch", "bulk"):
            rows = [s for s in samples if s[0] == cls]
            ok = [s for s in rows if s[1] == 200]
            lat = np.array([s[2] for s in ok]) * 1000.0
            degraded = sum(1 for s in ok if s[3])
            out[cls] = {
                "requests": len(rows),
                "status_200": len(ok),
                "status_503": sum(1 for s in rows if s[1] == 503),
                "status_504": sum(1 for s in rows if s[1] == 504),
                "degraded": degraded,
                "degraded_fraction": (
                    round(degraded / len(ok), 3) if ok else None
                ),
                "p50_ms": (
                    round(float(np.percentile(lat, 50)), 2)
                    if len(lat) else None
                ),
                "p99_ms": (
                    round(float(np.percentile(lat, 99)), 2)
                    if len(lat) else None
                ),
            }
        out["scheduler"] = app_obj.scheduler.snapshot()
        lower_shed = (
            out["prefetch"]["status_503"] + out["bulk"]["status_503"]
        )
        # the three SLO pins (explicit if/record — never bare asserts,
        # python -O would strip them)
        out["slo_ok_no_interactive_503"] = (
            out["interactive"]["status_503"] == 0 and lower_shed > 0
        )
        p99 = out["interactive"]["p99_ms"]
        out["interactive_p99_bound_ms"] = interactive_p99_bound_ms
        out["slo_ok_interactive_p99"] = (
            p99 is not None and p99 <= interactive_p99_bound_ms
        )
        out["slo_ok_degradation_engaged"] = (
            (out["interactive"]["degraded"] or 0) > 0
        )
        return out

    return asyncio.run(run())


def bench_io(cache_dir: str) -> dict:
    """Cold-remote read plane (r14): a loopback HTTP object store with
    per-request latency serving a multi-chunk NGFF image (16 chunks
    per 256px tile) both unsharded and Zarr-v3-sharded.

    Pins (io_ok_*): batch dedupe + range coalescing spend < 1.0 store
    requests per tile on the sharded fixture (sequential was >= 16);
    the parallel+coalesced plane is >= 2x the sequential path's
    tiles/s on identical inputs; and sharded tile bytes are identical
    to the unsharded ground truth."""
    import functools
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from omero_ms_pixel_buffer_tpu.io import fetch
    from omero_ms_pixel_buffer_tpu.io.zarr import (
        ZarrPixelBuffer,
        write_ngff,
    )

    rng = np.random.default_rng(23)
    img = rng.integers(0, 60000, (1, 1, 1, 1024, 1024), dtype=np.uint16)
    plain = os.path.join(cache_dir, "io_plain.zarr")
    sharded = os.path.join(cache_dir, "io_sharded.zarr")
    if not os.path.exists(plain):
        write_ngff(plain, img, chunks=(64, 64), levels=1,
                   zarr_format=3, compressor="zlib")
    if not os.path.exists(sharded):
        write_ngff(sharded, img, chunks=(64, 64), levels=1,
                   zarr_format=3, compressor="zlib", shards=(512, 512))

    class Handler(BaseHTTPRequestHandler):
        """Range-capable static handler with a 2 ms per-request floor
        — the round-trip a remote object store charges."""

        protocol_version = "HTTP/1.1"
        counts = {"n": 0}
        lock = threading.Lock()

        def __init__(self, root, *args, **kwargs):
            self.root = root
            super().__init__(*args, **kwargs)

        def log_message(self, *a):
            pass

        def _reply(self, code, body=b""):
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            import urllib.parse

            with self.lock:
                self.counts["n"] += 1
            time.sleep(0.002)
            rel = urllib.parse.unquote(self.path.lstrip("/"))
            path = os.path.join(self.root, rel)
            if ".." in rel or not os.path.isfile(path):
                return self._reply(404)
            with open(path, "rb") as f:
                data = f.read()
            rng_h = self.headers.get("Range")
            if rng_h is None:
                return self._reply(200, data)
            spec = rng_h.split("=", 1)[1]
            if spec.startswith("-"):
                n = int(spec[1:])
                body = data[-n:] if n <= len(data) else data
                self.send_response(206)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            lo_s, _, hi_s = spec.partition("-")
            lo = int(lo_s)
            if lo >= len(data):
                return self._reply(416)
            hi = int(hi_s) + 1 if hi_s else len(data)
            body = data[lo:min(hi, len(data))]
            self.send_response(206)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = ThreadingHTTPServer(
        ("127.0.0.1", 0), functools.partial(Handler, cache_dir)
    )
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    tiles16 = [
        (0, 0, 0, x * 256, y * 256, 256, 256)
        for y in range(4) for x in range(4)
    ]
    ground = ZarrPixelBuffer(plain).read_tiles(tiles16, level=0)

    out: dict = {"fixture": {
        "plane": "1024x1024 uint16", "chunks": 64, "shards": 512,
        "tile": 256, "chunks_per_tile": 16,
    }}
    try:
        # -- sequential escape path, cold (the pre-r14 shape) ----------
        fetch.CONFIG.parallel = False
        Handler.counts["n"] = 0
        buf = ZarrPixelBuffer(f"{base}/io_sharded.zarr")
        meta_reqs = Handler.counts["n"]
        t0 = time.perf_counter()
        seq_tiles = []
        for i in range(0, 16, 8):
            seq_tiles += buf.read_tiles(tiles16[i:i + 8], level=0)
        seq_s = time.perf_counter() - t0
        seq_reqs = Handler.counts["n"] - meta_reqs
        out["sequential"] = {
            "tiles_per_sec": round(16 / seq_s, 2),
            "requests_per_tile": round(seq_reqs / 16, 2),
        }

        # -- parallel + coalesced, cold --------------------------------
        fetch.CONFIG.parallel = True
        stats0 = fetch.IO_STATS.snapshot()
        Handler.counts["n"] = 0
        buf = ZarrPixelBuffer(f"{base}/io_sharded.zarr")
        meta_reqs = Handler.counts["n"]
        t0 = time.perf_counter()
        par_tiles = []
        for i in range(0, 16, 8):
            par_tiles += buf.read_tiles(tiles16[i:i + 8], level=0)
        par_s = time.perf_counter() - t0
        par_reqs = Handler.counts["n"] - meta_reqs
        stats1 = fetch.IO_STATS.snapshot()
        planned = stats1["planned"] - stats0["planned"]
        saved = stats1["coalesced_saved"] - stats0["coalesced_saved"]

        # per-tile fetch latency distribution: 16 cold single-tile
        # reads on a fresh buffer (each is one planned batch)
        lat_ms = []
        buf = ZarrPixelBuffer(f"{base}/io_sharded.zarr")
        for co in tiles16:
            t0 = time.perf_counter()
            buf.read_tiles([co], level=0)
            lat_ms.append((time.perf_counter() - t0) * 1000.0)
        lat = np.array(sorted(lat_ms))

        out["parallel"] = {
            "tiles_per_sec": round(16 / par_s, 2),
            "requests_per_tile": round(par_reqs / 16, 3),
            "coalesced_ratio": (
                round(saved / planned, 3) if planned else 0.0
            ),
            "fetch_p50_ms": round(float(np.percentile(lat, 50)), 2),
            "fetch_p99_ms": round(float(np.percentile(lat, 99)), 2),
        }
        out["speedup_parallel_vs_sequential"] = round(seq_s / par_s, 2)
        identical = all(
            a.tobytes() == b.tobytes()
            for a, b in zip(ground, par_tiles)
        ) and all(
            a.tobytes() == b.tobytes()
            for a, b in zip(ground, seq_tiles)
        )
        # the three acceptance pins — explicit booleans in BENCH json
        out["io_ok_requests_per_tile"] = (
            out["parallel"]["requests_per_tile"] < 1.0
        )
        out["io_ok_parallel_speedup"] = (
            out["speedup_parallel_vs_sequential"] >= 2.0
        )
        out["io_ok_sharded_identical"] = identical
    finally:
        fetch.CONFIG.parallel = True
        server.shutdown()
    return out


def bench_obs(cache_dir: str, n: int = 240) -> dict:
    """Observability plane (r16) section — two pins:

    - ``obs_ok_overhead``: the flight recorder's warm-path cost. The
      same warm (cache-hit) URL set is replayed through two identical
      apps, obs on vs off, A/B interleaved over several rounds with
      the per-arm MIN p50 compared (min-of-rounds discards scheduler
      noise on a shared CI box). Pin: p50 penalty <= 3%, with a
      0.3 ms absolute floor — a sub-ms warm hit jitters by more than
      the recorder's ~30 us cost, and the floor keeps timer noise
      from failing a pin the recorder didn't earn.
    - ``obs_ok_tail_capture``: a forced-slow request (slow-threshold
      0 ms makes every cold render "slow") appears in the
      /debug/requests ring with full attribution — pipeline stages
      stamped and the stage sum within the observed total.
    """
    import hashlib  # noqa: F401 - parity with bench_cache imports

    from aiohttp import web

    from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
    from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    size = 2048
    path = os.path.join(cache_dir, "obs_fixture.ome.tiff")
    if not os.path.exists(path):
        rng = np.random.default_rng(61)
        img = rng.integers(
            0, 60000, (1, 1, 1, size, size), dtype=np.uint16
        )
        write_ome_tiff(path, img, tile_size=(256, 256))

    def make_app(obs_enabled: bool, slow_ms: float = 10_000.0):
        registry = ImageRegistry()
        registry.add(1, path)
        config = Config.from_dict({
            "session-store": {"type": "memory"},
            "backend": {"engine": "host"},
            "cache": {"prefetch": {"enabled": False}},
            "obs": {
                "enabled": obs_enabled,
                # overhead arms: nothing kept (pure recording cost);
                # the tail arm flips slow-threshold to 0 instead
                "head-sample-rate": 0.0,
                "slow-threshold-ms": slow_ms,
            },
        })
        service = PixelsService(registry)
        return PixelBufferApp(
            config,
            pixels_service=service,
            session_store=MemorySessionStore({"bench": "bench-key"}),
        ), service

    # 512-px tiles (the bench_cache latency-probe shape): the warm
    # baseline includes a realistic body transfer, so the pin reads
    # the recorder against what a viewer actually feels per hit
    urls = [
        f"/tile/1/0/0/0?x={512 * (i % 3)}&y={512 * (i // 3 % 3)}"
        "&w=512&h=512&format=png"
        for i in range(9)
    ]

    async def drive(port, request_urls, expect_status=200):
        latencies = []
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            for url in request_urls:
                t0 = time.perf_counter()
                writer.write(
                    f"GET {url} HTTP/1.1\r\n"
                    "Host: bench\r\n"
                    "Cookie: sessionid=bench\r\n"
                    "\r\n".encode()
                )
                await writer.drain()
                status = int((await reader.readline()).split()[1])
                clen = 0
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b""):
                        break
                    if line.lower().startswith(b"content-length:"):
                        clen = int(line.split(b":", 1)[1])
                body = await reader.readexactly(clen)
                assert status == expect_status, (status, body[:200])
                latencies.append(time.perf_counter() - t0)
        finally:
            writer.close()
        return latencies, body

    async def warm_p50(app_obj, service, rounds: int = 3) -> float:
        runner = web.AppRunner(app_obj.make_app(), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        try:
            await drive(port, urls)  # cold fill + warmup
            p50s = []
            for _ in range(rounds):
                lat, _ = await drive(
                    port, (urls * (n // len(urls) + 1))[:n]
                )
                p50s.append(
                    float(np.percentile(np.array(lat) * 1e3, 50))
                )
            return min(p50s)
        finally:
            await runner.cleanup()
            service.close()

    async def run() -> dict:
        out: dict = {"warm_requests_per_arm": n}
        app_on, svc_on = make_app(True)
        app_off, svc_off = make_app(False)
        out["warm_p50_on_ms"] = round(await warm_p50(app_on, svc_on), 3)
        out["warm_p50_off_ms"] = round(
            await warm_p50(app_off, svc_off), 3
        )
        penalty = (
            out["warm_p50_on_ms"] - out["warm_p50_off_ms"]
        ) / max(out["warm_p50_off_ms"], 1e-9)
        out["warm_p50_penalty"] = round(penalty, 4)
        out["obs_ok_overhead"] = bool(
            penalty <= 0.03
            or out["warm_p50_on_ms"] - out["warm_p50_off_ms"] <= 0.3
        )

        # forced-slow tail capture: slow-threshold 0 -> every serve is
        # "slow" and must be kept with full attribution
        app_slow, svc_slow = make_app(True, slow_ms=0.0)
        runner = web.AppRunner(app_slow.make_app(), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        try:
            await drive(port, urls[:1])
            events = app_slow.recorder.events()
            captured = bool(events)
            event = events[0] if events else {}
            stages = event.get("stages_ms", {})
            attributed = sum(stages.values())
            out["tail_event_stages"] = sorted(stages)
            out["tail_event_total_ms"] = event.get("total_ms")
            out["obs_ok_tail_capture"] = bool(
                captured
                and event.get("kept_reason") == "slow"
                and {"resolve", "read", "encode"} <= set(stages)
                and attributed <= (event.get("total_ms") or 0) + 1.0
            )
        finally:
            await runner.cleanup()
            svc_slow.close()
        return out

    return asyncio.run(run())


def build_render_fixture(root: str, size: int = 2048, depth: int = 1):
    """3-channel uint16 fixture for the rendered-tile section;
    ``depth`` > 1 writes a z-stack (shifted copies of the base
    pattern) for projection-burst sections."""
    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff

    path = os.path.join(
        root,
        f"bench_render_{size}.ome.tiff" if depth == 1
        else f"bench_render_{size}_z{depth}.ome.tiff",
    )
    if os.path.exists(path):
        return path
    log(f"writing {size}x{size} 3-channel z={depth} render fixture...")
    rng = np.random.default_rng(31)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    chans = []
    for phase in (0.0, 1.1, 2.3):
        base = (
            1800
            + 1200 * np.sin(xx / 89.0 + phase)
            + 1200 * np.cos(yy / 127.0 + phase)
        )
        chans.append(
            (base + rng.normal(0, 90, (size, size))).clip(0, 4095)
        )
    data = np.stack(chans).astype(np.uint16)[None, :, None]
    if depth > 1:
        data = np.concatenate(
            [np.roll(data, 17 * z, axis=-1) for z in range(depth)],
            axis=2,
        )
    write_ome_tiff(path, data, tile_size=(512, 512), compression="zlib")
    return path


def bench_render(
    cache_dir: str, engine: str, size: int = 2048, n: int = 96
) -> dict:
    """Rendered-tile serving (render/): 3 channels window/leveled,
    colored, and composited per tile — p50/p99 per-tile latency plus
    coalesced tiles/s, host engine vs the headline engine (identical
    bytes by the engine contract, so only the clock differs)."""
    import time as _t

    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
    from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

    path = build_render_fixture(cache_dir, size)
    registry = ImageRegistry()
    registry.add(1, path)
    spec = RenderSpec.from_params({
        "c": "1|0:4095$FF0000,2|0:4095$00FF00,3|0:4095$0000FF",
    })
    rng = np.random.default_rng(37)
    ctxs = []
    for _ in range(n):
        x = int(rng.integers(0, (size - 512) // 64)) * 64
        y = int(rng.integers(0, (size - 512) // 64)) * 64
        ctxs.append(TileCtx(
            image_id=1, z=0, c=0, t=0,
            region=RegionDef(x, y, 512, 512), format="png",
            omero_session_key="bench", render=spec,
        ))
    out = {}
    engines = ["host"] if engine == "host" else ["host", engine]
    for label in engines:
        service = PixelsService(registry)
        try:
            pipe = TilePipeline(service, engine=label, buckets=(512,))
            pipe.handle_batch(ctxs[:16])  # warm reads + tables + jit
            lat = []
            for ctx in ctxs[:32]:
                t0 = _t.perf_counter()
                assert pipe.handle(ctx) is not None
                lat.append(_t.perf_counter() - t0)
            tps = run_batched(pipe, ctxs, 16)
            lat_ms = np.array(lat) * 1000.0
            out[label] = {
                "tiles_per_sec": round(tps, 2),
                "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
                "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
            }
            log(f"[render] {label}: {out[label]}")
            pipe.close()
        except Exception as e:
            out[label] = {"error": f"{type(e).__name__}: {e}"}
            log(f"[render] {label} failed: {e!r}")
        finally:
            service.close()
    return out


def bench_supertile(
    cache_dir: str, engine: str, size: int = 1024, tile: int = 64,
    grid: int = 4, rounds: int = 3, depth: int = 4,
) -> dict:
    """Super-tile plane (r19) section — a 4x4 DZI-row burst (one
    spec, one resolution, grid-adjacent tiles; a 3-channel intmax
    z-projection over ``depth`` planes, the viewer burst shape where
    the shared plane gather is largest — every independent tile
    re-gathers and re-projects the whole z-range) rendered two ways:

    - ``independent``: every tile through its own ``handle()`` call —
      the literal "independently rendered tile" the byte-identity
      contract is pinned against (each pays its own gather,
      projection, composite, and dispatch);
    - ``fused``: the same tiles stamped by the batcher's adjacency
      bucketing and served through one ``handle_batch`` — ONE plane
      gather over the bounding rectangle, ONE projection + composite,
      carved per-tile encodes.

    Two pins (recorded per engine; the CI smoke fails on either):
    ``supertile_ok_speedup`` — the fused burst serves >= 2x the
    independent tiles/s on the headline engine; and
    ``supertile_ok_identical`` — fused bytes == independent bytes on
    EVERY engine that ran (the contract that lets fused tiles share
    ETags and cache entries).

    Default operating point: 64px tiles over a z=4 stack — the
    regime where the per-tile gather/projection/dispatch the fusion
    eliminates dominates. At 256px+ tiles on the CPU backend the
    per-tile deflate floor (untouched by fusion) dominates instead
    and the ratio compresses toward 1; KNOWN_GAPS records that
    honestly."""
    import time as _t

    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
    from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
    from omero_ms_pixel_buffer_tpu.render.supertile import (
        BurstHint,
        assign_supertiles,
    )
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

    path = build_render_fixture(cache_dir, size, depth=depth)
    registry = ImageRegistry()
    registry.add(1, path)
    params = {
        "c": "1|0:4095$FF0000,2|0:4095$00FF00,3|0:4095$0000FF",
    }
    if depth > 1:
        params["p"] = f"intmax|0:{depth - 1}"
    spec = RenderSpec.from_params(params)
    hint = BurstHint(tile, tile)

    def burst_ctxs():
        return [
            TileCtx(
                image_id=1, z=0, c=0, t=0,
                region=RegionDef(col * tile, row * tile, tile, tile),
                format="png", omero_session_key="bench", render=spec,
                burst=hint,
            )
            for row in range(grid) for col in range(grid)
        ]

    out: dict = {}
    identical = True
    engines = ["host"] if engine == "host" else ["host", engine]
    for label in engines:
        service = PixelsService(registry)
        try:
            pipe = TilePipeline(
                service, engine=label, buckets=(tile,),
                device_deflate=(label != "host"),
            )
            pipe.mesh = None  # the fused composite is single-device
            # warm both shapes: per-tile jit/native paths AND the
            # fused super-tile program
            warm_ind = [pipe.handle(c) for c in burst_ctxs()]
            assert all(b is not None for b in warm_ind)
            warm_ctxs = burst_ctxs()
            assign_supertiles(warm_ctxs, max_pixels=(grid * tile) ** 2)
            warm_fused = pipe.handle_batch(warm_ctxs)
            if warm_fused != warm_ind:
                identical = False
                log(f"[supertile] {label}: FUSED BYTES DIVERGED")
            t0 = _t.perf_counter()
            for _ in range(rounds):
                for ctx in burst_ctxs():
                    assert pipe.handle(ctx) is not None
            ind_tps = rounds * grid * grid / (_t.perf_counter() - t0)
            t0 = _t.perf_counter()
            for _ in range(rounds):
                ctxs = burst_ctxs()
                assign_supertiles(
                    ctxs, max_pixels=(grid * tile) ** 2
                )
                res = pipe.handle_batch(ctxs)
                assert all(b is not None for b in res)
            fused_tps = rounds * grid * grid / (_t.perf_counter() - t0)
            out[label] = {
                "independent_tiles_per_sec": round(ind_tps, 2),
                "fused_tiles_per_sec": round(fused_tps, 2),
                "speedup": round(fused_tps / max(ind_tps, 1e-9), 3),
            }
            log(f"[supertile] {label}: {out[label]}")
            pipe.close()
        except Exception as e:
            out[label] = {"error": f"{type(e).__name__}: {e}"}
            identical = False
            log(f"[supertile] {label} failed: {e!r}")
        finally:
            service.close()
    headline = engines[-1]
    speedup = (out.get(headline) or {}).get("speedup")
    out["supertile_ok_speedup"] = bool(speedup and speedup >= 2.0)
    out["supertile_ok_identical"] = identical
    return out


def _mesh_fusion_child():
    """Subprocess body for the mesh half of ``bench_mesh_fusion`` —
    runs on a virtual n-device CPU platform (the parent pins XLA_FLAGS
    before jax init, same self-provisioning dance as
    ``__graft_entry__.dryrun_multichip``). Prints ONE marker line
    ``MESH_FUSION_CHILD {json}`` on stdout for the parent to parse."""
    import time as _t

    args = json.loads(os.environ["_OMPB_MESH_FUSION_ARGS"])
    cache_dir = args["cache_dir"]
    size, tile, grid = args["size"], args["tile"], args["grid"]
    rounds, depth, n_devices = args["rounds"], args["depth"], args["n"]

    import jax

    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
    from omero_ms_pixel_buffer_tpu.parallel.mesh import make_mesh
    from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
    from omero_ms_pixel_buffer_tpu.render.supertile import (
        BurstHint,
        assign_supertiles,
    )
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

    assert len(jax.devices()) >= n_devices, (
        f"child got {len(jax.devices())} devices, wanted {n_devices}"
    )
    path = build_render_fixture(cache_dir, size, depth=depth)
    registry = ImageRegistry()
    registry.add(1, path)
    params = {
        "c": "1|0:4095$FF0000,2|0:4095$00FF00,3|0:4095$0000FF",
    }
    if depth > 1:
        params["p"] = f"intmax|0:{depth - 1}"
    spec = RenderSpec.from_params(params)
    hint = BurstHint(tile, tile)
    max_pixels = (grid * tile) ** 2

    def burst_ctxs():
        return [
            TileCtx(
                image_id=1, z=0, c=0, t=0,
                region=RegionDef(col * tile, row * tile, tile, tile),
                format="png", omero_session_key="bench", render=spec,
                burst=hint,
            )
            for row in range(grid) for col in range(grid)
        ]

    def stamped():
        ctxs = burst_ctxs()
        assign_supertiles(ctxs, max_pixels=max_pixels)
        return ctxs

    service = PixelsService(registry)
    result = {"n_devices": n_devices}
    try:
        def make_pipe(supertile_mesh, width):
            pipe = TilePipeline(
                service, engine="device", device_deflate=True,
                buckets=(tile,), supertile_mesh=supertile_mesh,
            )
            if width is None:
                pipe.mesh = None
            else:
                pipe.mesh = make_mesh(
                    ("data",), devices=jax.devices()[:width]
                )
            return pipe

        # single-device reference: independent tiles AND the fused
        # single-device program — the two identity anchors
        p_single = make_pipe(True, None)
        ref_ind = [p_single.handle(c) for c in burst_ctxs()]
        ref_fused = p_single.handle_batch(stamped())

        # fused over the mesh: ONE sharded gather+project+composite+
        # carve+deflate program per super-tile group
        p_fused = make_pipe(True, n_devices)
        fused_out = p_fused.handle_batch(stamped())
        st_dispatch = p_fused.last_mesh_dispatch or {}
        result["identical"] = bool(
            fused_out == ref_fused == ref_ind
            and st_dispatch.get("tag") == "supertile"
            and st_dispatch.get("executed")
        )

        # comparator: same mesh, fusion off — each tile rides the
        # per-lane sharded render path (the pre-fusion decision-table
        # row this PR deletes: "serving mesh active -> no fusion")
        p_lane = make_pipe(False, n_devices)
        lane_out = p_lane.handle_batch(stamped())
        if lane_out != ref_ind:
            result["identical"] = False

        n_tiles = grid * grid
        t0 = _t.perf_counter()
        for _ in range(rounds):
            assert all(
                b is not None for b in p_lane.handle_batch(stamped())
            )
        lane_tps = rounds * n_tiles / (_t.perf_counter() - t0)
        t0 = _t.perf_counter()
        for _ in range(rounds):
            assert all(
                b is not None for b in p_fused.handle_batch(stamped())
            )
        fused_tps = rounds * n_tiles / (_t.perf_counter() - t0)
        result.update({
            "fused_mesh_tiles_per_sec": round(fused_tps, 2),
            "per_lane_sharded_tiles_per_sec": round(lane_tps, 2),
            "speedup": round(fused_tps / max(lane_tps, 1e-9), 3),
        })
        for p in (p_single, p_fused, p_lane):
            p.close()
    finally:
        service.close()
    print("MESH_FUSION_CHILD " + json.dumps(result), flush=True)


def _bench_burst_programs(
    n_tiles: int = 100, stagger_ms: float = 3.0
) -> dict:
    """100-tile zoom burst through the REAL batcher (no jax): lanes
    arrive staggered past the 2ms coalesce window, so without
    continuation nearly every lane is its own device program; with the
    burst-continuation key the windows chain. handle_batch call count
    is the device-program proxy."""
    from omero_ms_pixel_buffer_tpu.auth.omero_session import (
        AllowListValidator,
    )
    from omero_ms_pixel_buffer_tpu.dispatch.batcher import (
        BatchingTileWorker,
    )
    from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
    from omero_ms_pixel_buffer_tpu.render.supertile import BurstHint
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx
    from omero_ms_pixel_buffer_tpu.utils.config import (
        BurstContinuationConfig,
    )

    spec = RenderSpec.from_params({"c": "1|0:4095$FF0000"})
    hint = BurstHint(64, 64)

    class _Counting:
        def __init__(self):
            self.programs = 0

        def handle(self, ctx):
            return b"x"

        def handle_batch(self, ctxs):
            self.programs += 1
            return [b"x"] * len(ctxs)

    def run(bc) -> int:
        counting = _Counting()
        worker = BatchingTileWorker(
            counting, AllowListValidator(), max_batch=32,
            coalesce_window_ms=2.0, workers=1, burst_continuation=bc,
        )

        async def go():
            await worker.start()
            sends = []
            for i in range(n_tiles):
                sends.append(asyncio.ensure_future(worker.handle(
                    TileCtx(
                        image_id=1, z=0, c=0, t=0,
                        region=RegionDef(
                            64 * (i % 10), 64 * (i // 10), 64, 64
                        ),
                        format="png", omero_session_key="bench",
                        render=spec, burst=hint,
                    )
                )))
                await asyncio.sleep(stagger_ms / 1000.0)
            out = await asyncio.gather(*sends)
            await worker.close()
            assert all(t == b"x" for t, _ in out)

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(go())
        finally:
            loop.close()
        return counting.programs

    on = run(BurstContinuationConfig(enabled=True, window_ms=50.0))
    off = run(None)
    return {
        "tiles": n_tiles,
        "continuation_on_programs": on,
        "continuation_off_programs": off,
    }


def bench_mesh_fusion(
    cache_dir: str, engine: str, size: int = 1024, tile: int = 64,
    grid: int = 4, rounds: int = 3, depth: int = 4, n_devices: int = 8,
) -> dict:
    """Mesh-fusion plane (r23) section, two halves:

    - **mesh**: the bench_supertile burst (4x4 adjacent 64px tiles,
      3-channel intmax z-projection) over an 8-chip mesh, fused
      (``supertile_mesh=True`` — one sharded
      gather+project+composite+carve+deflate program) vs the per-lane
      sharded path the mesh used before this PR
      (``supertile_mesh=False`` — every tile its own gather/projection,
      only the encode sharded). The driver env pins exactly one real
      chip and tests alone force virtual devices, so this half re-execs
      a subprocess on a virtual 8-device CPU platform (the
      ``dryrun_multichip`` self-provisioning pattern) — ratios on
      virtual chips are work-count ratios, which is what the pin
      guards.
    - **burst**: programs-per-100-tile-zoom through the real batcher
      with burst continuation on vs off (in-process, no jax).

    Pins (CI smoke fails on any):
    ``mesh_ok_fusion_identity`` — fused-mesh bytes == single-device
    fused == independent tiles, with the dispatch tagged "supertile";
    ``mesh_ok_fusion_speedup`` — fused >= 2x per-lane-sharded tiles/s;
    ``mesh_ok_burst_programs`` — continuation serves the zoom in
    <= 1/4 the programs."""
    import re
    import subprocess

    out: dict = {}
    try:
        out["burst"] = _bench_burst_programs()
        on = out["burst"]["continuation_on_programs"]
        off = out["burst"]["continuation_off_programs"]
        out["mesh_ok_burst_programs"] = bool(on * 4 <= off)
        log(f"[mesh_fusion] burst: {out['burst']}")
    except Exception as e:
        out["burst"] = {"error": f"{type(e).__name__}: {e}"}
        out["mesh_ok_burst_programs"] = False
        log(f"[mesh_fusion] burst failed: {e!r}")

    try:
        env = dict(os.environ)
        env["_OMPB_MESH_FUSION_ARGS"] = json.dumps({
            "cache_dir": cache_dir, "size": size, "tile": tile,
            "grid": grid, "rounds": rounds, "depth": depth,
            "n": n_devices,
        })
        # replace (not merely add) any ambient device-count flag, and
        # pin the cpu platform BEFORE jax init — the axon TPU plugin
        # ignores a bare JAX_PLATFORMS (dryrun_multichip's dance)
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            env.get("XLA_FLAGS", ""),
        )
        env["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
        env["JAX_PLATFORMS"] = "cpu"
        repo = os.path.dirname(os.path.abspath(__file__))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import bench; bench._mesh_fusion_child()"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, cwd=repo,
            capture_output=True, text=True, timeout=1200,
        )
        if proc.stderr:
            log(proc.stderr.rstrip())
        marker = next(
            (
                line[len("MESH_FUSION_CHILD "):]
                for line in proc.stdout.splitlines()
                if line.startswith("MESH_FUSION_CHILD ")
            ),
            None,
        )
        if proc.returncode != 0 or marker is None:
            raise RuntimeError(
                f"mesh child rc={proc.returncode}, no result marker"
            )
        out["mesh"] = json.loads(marker)
        out["mesh_ok_fusion_identity"] = bool(
            out["mesh"].get("identical")
        )
        out["mesh_ok_fusion_speedup"] = bool(
            (out["mesh"].get("speedup") or 0) >= 2.0
        )
        log(f"[mesh_fusion] mesh: {out['mesh']}")
    except Exception as e:
        out["mesh"] = {"error": f"{type(e).__name__}: {e}"}
        out["mesh_ok_fusion_identity"] = False
        out["mesh_ok_fusion_speedup"] = False
        log(f"[mesh_fusion] mesh failed: {e!r}")
    return out


def bench_analysis(
    cache_dir: str, engine: str, size: int = 2048, n: int = 64
) -> dict:
    """Analysis plane (render/analysis + render/masks): histogram
    tiles/s host vs the headline engine — with the integer-identity
    pin ``analysis_ok_hist_identical`` (same ctx, byte-identical JSON
    across engines) — and the masked-render overhead ratio
    (``analysis_ok_masked_overhead``: ROI compositing must stay a
    small multiple of the plain render, since rasters are cached per
    (shape-set, region))."""
    import time as _t

    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
    from omero_ms_pixel_buffer_tpu.render.analysis import HistogramSpec
    from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

    path = build_render_fixture(cache_dir, size)
    registry = ImageRegistry()
    registry.add(1, path)
    hspec = HistogramSpec.from_params({"bins": "256", "c": "1,2,3"})
    rng = np.random.default_rng(41)
    ctxs = []
    for _ in range(n):
        x = int(rng.integers(0, (size - 512) // 64)) * 64
        y = int(rng.integers(0, (size - 512) // 64)) * 64
        ctxs.append(TileCtx(
            image_id=1, z=0, c=0, t=0,
            region=RegionDef(x, y, 512, 512), format="json",
            omero_session_key="bench", analysis=hspec,
        ))
    out: dict = {}
    bodies: dict = {}
    engines = ["host"] if engine == "host" else ["host", engine]
    for label in engines:
        service = PixelsService(registry)
        try:
            pipe = TilePipeline(service, engine=label, buckets=(512,))
            warm = pipe.handle_batch(ctxs[:8])
            assert all(w is not None for w in warm)
            bodies[label] = pipe.handle_batch([ctxs[0]])[0]
            tps = run_batched(pipe, ctxs, 16)
            out[label] = {"hist_tiles_per_sec": round(tps, 2)}
            log(f"[analysis] {label}: {out[label]}")
            pipe.close()
        except Exception as e:
            out[label] = {"error": f"{type(e).__name__}: {e}"}
            log(f"[analysis] {label} failed: {e!r}")
        finally:
            service.close()
    vals = [b for b in bodies.values() if b is not None]
    out["analysis_ok_hist_identical"] = (
        len(vals) == len(engines) and all(v == vals[0] for v in vals)
    )

    # masked-render overhead: the same tile set rendered plain vs
    # with a 3-shape ROI union (host engine — masked lanes serve
    # through the host mirror), warm raster cache
    roi = (
        '[{"type":"rect","x":64,"y":64,"w":320,"h":320},'
        '{"type":"ellipse","cx":256,"cy":256,"rx":200,"ry":140},'
        '{"type":"polygon","points":[[0,0],[500,40],[260,500]]}]'
    )
    plain = RenderSpec.from_params({"c": "1|0:4095$FF0000"})
    masked = RenderSpec.from_params(
        {"c": "1|0:4095$FF0000", "roi": roi}
    )
    service = PixelsService(registry)
    try:
        pipe = TilePipeline(service, engine="host", buckets=(512,))

        def render_ctxs(spec):
            return [TileCtx(
                image_id=1, z=0, c=0, t=0,
                region=RegionDef(c.region.x, c.region.y, 512, 512),
                format="png", omero_session_key="bench", render=spec,
            ) for c in ctxs[:24]]

        for spec in (plain, masked):  # warm reads + tables + rasters
            assert all(
                r is not None
                for r in pipe.handle_batch(render_ctxs(spec)[:8])
            )
        times = {}
        for key, spec in (("plain", plain), ("masked", masked)):
            rcs = render_ctxs(spec)
            t0 = _t.perf_counter()
            res = pipe.handle_batch(rcs)
            assert all(r is not None for r in res)
            times[key] = _t.perf_counter() - t0
        ratio = times["masked"] / max(times["plain"], 1e-9)
        out["masked_overhead_ratio"] = round(ratio, 3)
        out["analysis_ok_masked_overhead"] = ratio <= 3.0
        log(
            f"[analysis] masked overhead {ratio:.2f}x "
            f"(plain {times['plain']*1000:.0f}ms, "
            f"masked {times['masked']*1000:.0f}ms)"
        )
        pipe.close()
    except Exception as e:
        out["masked_error"] = f"{type(e).__name__}: {e}"
        out["analysis_ok_masked_overhead"] = False
        log(f"[analysis] masked bench failed: {e!r}")
    finally:
        service.close()
    return out


def bench_device(path: str, size: int, probe_info: dict) -> dict:
    """Accelerator-engine sub-run, recorded even when slower than host
    (over a tunneled chip the link dominates; BENCH tail carries the
    probed MB/s so the co-located-chip story is quantified separately
    for the HBM plane-cache path and the host-staged bucket path).

    Runs in a bounded CHILD process: the tunnel can wedge mid-transfer
    and hang jax calls, and the headline record must survive that."""
    from omero_ms_pixel_buffer_tpu.runtime.device_probe import run_bounded

    out = dict(probe_info)
    if out.get("backend") != "tpu":
        # no accelerator (probe error, or CPU-only jax): record why,
        # skip the sub-run — engine='device' on the CPU backend would
        # mislabel CPU-JAX numbers as the accelerator story
        return out
    env = dict(os.environ)
    env["BENCH_FIXTURE"] = path
    env["BENCH_IMAGE_SIZE"] = str(size)
    if out.get("link_mbps"):
        # the child folds the measured link into its compute-vs-link
        # throughput projections (runtime/microbench.project_throughput)
        env["BENCH_LINK_MBPS"] = str(out["link_mbps"])
    timeout_s = float(os.environ.get("BENCH_DEVICE_TIMEOUT_S", "600"))
    child = run_bounded(
        [sys.executable, os.path.abspath(__file__), "--device-sub"],
        timeout_s, env=env,
    )
    out.update(child)
    return out


def device_sub_main():
    """Child-process entry for the device sub-run (see bench_device)."""
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline

    path = os.environ["BENCH_FIXTURE"]
    size = int(os.environ["BENCH_IMAGE_SIZE"])
    n = int(os.environ.get("BENCH_DEVICE_REQUESTS", "64"))
    registry = ImageRegistry()
    registry.add(1, path)
    service = PixelsService(registry)
    out = {}
    for label, plane_cache, dev_deflate in (
        ("plane_cache", True, False),
        ("bucket", False, False),
        # on-device deflate: only compressed bytes cross the link back
        ("bucket_devdeflate", False, True),
        # plane staged once + compressed return: the minimal-transfer
        # configuration for a tunnel-attached chip
        ("plane_devdeflate", True, True),
    ):
        try:
            pipe = TilePipeline(
                service, engine="device", buckets=(512,),
                use_plane_cache=plane_cache, device_deflate=dev_deflate,
            )
            if plane_cache:
                # the plane cache is the single-device HBM path; with
                # >1 chip the auto-mesh would supersede it and this
                # label would silently duplicate the bucket number
                pipe.mesh = None
            ctxs = make_ctxs(n, size, seed=23)
            # warm with the RUN's batch size: device jit programs are
            # per-(batch, shape), and a mismatched warmup would leave a
            # tens-of-seconds compile inside the timed region
            pipe.handle_batch(ctxs[:32])
            tps = run_batched(pipe, ctxs, 32)
            out[f"tiles_per_sec_{label}"] = round(tps, 2)
            log(f"[device] {label} path: {tps:.1f} tiles/s")
            if dev_deflate:
                # steady-state queue health: cross-batch overlap is
                # proven when the inter-group idle gap stays below one
                # group's compute time (overlapped_fraction high)
                queue = pipe.device_queue_snapshot()
                if queue:
                    out.setdefault("queue", {})[label] = queue
                    log(f"[device] {label} queue: {queue}")
        except Exception as e:
            out[f"error_{label}"] = f"{type(e).__name__}: {e}"
            log(f"[device] {label} path failed: {e!r}")
    # rendered-tile lanes: the fused render->filter->deflate chain as
    # ONE device dispatch per bucket group
    try:
        from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
        from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

        spec = RenderSpec.from_params({"c": "1|0:65535$FF0000"})
        pipe = TilePipeline(
            service, engine="device", buckets=(512,),
            use_plane_cache=False, device_deflate=True,
        )
        pipe.mesh = None
        rctxs = []
        rng = np.random.default_rng(41)
        for _ in range(n):
            x = int(rng.integers(0, (size - 512) // 64)) * 64
            y = int(rng.integers(0, (size - 512) // 64)) * 64
            rctxs.append(TileCtx(
                image_id=1, z=0, c=0, t=0,
                region=RegionDef(x, y, 512, 512), format="png",
                omero_session_key="bench", render=spec,
            ))
        pipe.handle_batch(rctxs[:32])
        tps = run_batched(pipe, rctxs, 32)
        out["tiles_per_sec_render"] = round(tps, 2)
        log(f"[device] render path: {tps:.1f} tiles/s")
    except Exception as e:
        out["error_render"] = f"{type(e).__name__}: {e}"
        log(f"[device] render path failed: {e!r}")
    service.close()
    # kernel-only compute metrics: over the tunneled chip the serving
    # numbers above measure the LINK; these measure the TPU itself
    # (device-resident inputs, compiles excluded) so the device design
    # is judgeable without a co-located chip. Shapes match the serving
    # runs, so the jit cache warmed above is reused.
    if os.environ.get("BENCH_MICRO", "1") != "0":
        from omero_ms_pixel_buffer_tpu.runtime.microbench import (
            project_throughput,
            run_microbench,
        )

        micro = None
        try:
            micro = run_microbench()
            link = float(os.environ.get("BENCH_LINK_MBPS", "0") or 0)
            micro.update(project_throughput(micro, link or None))
            out["micro"] = micro
            log(f"[device] microbench: {micro}")
        except Exception as e:
            out["micro"] = {"error": f"{type(e).__name__}: {e}"}
            log(f"[device] microbench failed: {e!r}")
        # the dynamic-Huffman ratio claim is PINNED, not prose: a
        # regression past the acceptance bound is recorded as
        # error_ratio (the headline record survives). An explicit
        # check, not assert — python -O must not strip the gate.
        ratio = (micro or {}).get("deflate_ratio_vs_host_dynamic")
        if ratio is not None and ratio > 1.10:
            msg = (
                f"dynamic-Huffman deflate ratio regressed: {ratio} "
                "(bound 1.10x host bytes on the rendered-RGB fixture)"
            )
            out["error_ratio"] = msg
            log(f"[device] RATIO REGRESSION: {msg}")
    print(json.dumps(out))


def main():
    t_setup = time.perf_counter()
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline

    cache_dir = os.environ.get(
        "BENCH_CACHE", os.path.join(tempfile.gettempdir(), "ompb_bench")
    )
    os.makedirs(cache_dir, exist_ok=True)
    size = int(os.environ.get("BENCH_IMAGE_SIZE", "8192"))
    n_requests = int(os.environ.get("BENCH_REQUESTS", "1024"))
    batch = int(os.environ.get("BENCH_BATCH", "32"))
    path = build_fixture(cache_dir, size)

    registry = ImageRegistry()
    registry.add(1, path)

    # --- baseline: reference-architecture path (sequential, host) -----
    # Separate service with the decoded-block cache OFF: the reference
    # re-opens and re-decodes per request (TileRequestHandler.java:86),
    # so its stand-in must too. Python (not native) encode, one at a
    # time, single worker — the Java worker-thread shape.
    base_service = PixelsService(registry, block_cache_bytes=0)
    base_pipe = TilePipeline(
        base_service, use_device=False, encode_workers=1,
        png_level=6, png_strategy="default",  # Java Deflater defaults
    )
    base_ctxs = make_ctxs(64, size)
    for ctx in base_ctxs[:4]:  # warm page cache + code paths
        assert base_pipe.handle(ctx) is not None
    t0 = time.perf_counter()
    for ctx in base_ctxs:
        out = base_pipe.handle(ctx)
        assert out is not None
    host_tps = len(base_ctxs) / (time.perf_counter() - t0)
    log(f"baseline (sequential host path): {host_tps:.1f} tiles/s")

    # --- framework batched path (auto engine) -------------------------
    probe_info = jax_backend_info()
    log(f"jax: {probe_info}")
    service = PixelsService(registry)
    engine = os.environ.get("BENCH_ENGINE", "auto")
    if engine in ("device", "tpu") and probe_info.get("backend") != "tpu":
        # an explicit device request on a wedged/absent TPU would HANG
        # at in-process PJRT init (not raise), so gate on the bounded
        # probe before any jax touchpoint
        log(
            f"engine '{engine}' requested but probe says "
            f"{probe_info}; falling back to host"
        )
        engine = "host"
    ctxs = make_ctxs(n_requests, size, seed=9)
    try:
        pipe = TilePipeline(service, engine=engine, buckets=(512,))
        # warmup: resolve auto engine, trigger jit/native build
        warm = pipe.handle_batch(ctxs[:batch])
        assert all(w is not None for w in warm)
    except Exception as e:
        # an explicitly-requested device engine on a wedged TPU must
        # still produce a headline number — re-run on the host engine
        log(f"engine '{engine}' failed ({e!r}); falling back to host")
        engine = "host"
        pipe = TilePipeline(service, engine="host", buckets=(512,))
        warm = pipe.handle_batch(ctxs[:batch])
        assert all(w is not None for w in warm)
    log(f"engine: {pipe.engine}")
    tpu_tps = run_batched(pipe, ctxs, batch)
    log(
        f"batched path ({pipe.engine}): {tpu_tps:.1f} tiles/s over "
        f"{len(ctxs)} tiles (setup+warmup "
        f"{time.perf_counter() - t_setup:.1f}s total elapsed)"
    )

    # --- full-stack HTTP latency --------------------------------------
    http_stats: dict = {}
    if os.environ.get("BENCH_HTTP", "1") != "0":
        try:
            http_stats = bench_http(
                path,
                int(os.environ.get("BENCH_HTTP_REQUESTS", "512")),
                int(os.environ.get("BENCH_HTTP_CONCURRENCY", "64")),
                engine=pipe.engine,  # probe-gated, never re-read from env
            )
            log(f"full-stack http: {http_stats}")
        except Exception as e:
            # namespaced: a top-level "error" key means total failure
            http_stats = {"http_error": f"{type(e).__name__}: {e}"}
            log(f"http bench failed: {e!r}")

    # --- cache warm-pass: repeated-tile serving (hit ratio + p50/p99
    # delta; identical bytes is the correctness bar) -------------------
    cache_stats: dict = {}
    if os.environ.get("BENCH_CACHE_PASS", "1") != "0":
        try:
            cache_stats = bench_cache(
                path,
                int(os.environ.get("BENCH_CACHE_TILES", "192")),
                int(os.environ.get("BENCH_CACHE_CONCURRENCY", "1")),
                engine=pipe.engine,  # probe-gated, never re-read
            )
            log(f"cache warm pass: {cache_stats}")
        except Exception as e:
            cache_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"cache bench failed: {e!r}")

    # --- cache plane (r11): warm-restart hit rate, L2 round trip,
    # two-replica render-once ------------------------------------------
    plane_stats: dict = {}
    if os.environ.get("BENCH_CACHE_PLANE", "1") != "0":
        try:
            plane_stats = bench_cache_plane(path, cache_dir)
            log(f"cache plane: {plane_stats}")
        except Exception as e:
            plane_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"cache plane bench failed: {e!r}")

    # --- sustained-overload SLO scenario (r13): mixed-class closed-
    # loop load at ~2x admission capacity against the deadline-ordered
    # scheduler; asserts SLO outcomes (slo_ok_* pins), not throughput
    overload_stats: dict = {}
    if os.environ.get("BENCH_OVERLOAD", "1") != "0":
        try:
            overload_stats = bench_overload(
                cache_dir,
                duration_s=float(
                    os.environ.get("BENCH_OVERLOAD_S", "4")
                ),
            )
            log(f"overload: {overload_stats}")
        except Exception as e:
            overload_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"overload bench failed: {e!r}")

    # --- cluster coordination plane (r17): owner-kill failover on the
    # replicated hot set, join-time warm-up, hedged vs unhedged peer
    # p99 (cluster_ok_* pins)
    cluster_stats: dict = {}
    if os.environ.get("BENCH_CLUSTER", "1") != "0":
        try:
            cluster_stats = bench_cluster(cache_dir)
            log(f"cluster: {cluster_stats}")
        except Exception as e:
            cluster_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"cluster bench failed: {e!r}")

    # --- fleet lifecycle plane (r18): rolling restart under traffic
    # (graceful drain + handoff + join warm-up) and anti-entropy
    # repair convergence (cluster_ok_drain_zero_errors /
    # cluster_ok_repair_convergence pins)
    lifecycle_stats: dict = {}
    if os.environ.get("BENCH_LIFECYCLE", "1") != "0":
        try:
            lifecycle_stats = bench_lifecycle(cache_dir)
            log(f"lifecycle: {lifecycle_stats}")
        except Exception as e:
            lifecycle_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"lifecycle bench failed: {e!r}")

    # --- decentralized control plane (r20): gossip membership through
    # a Redis outage + corrupt-replica demotion via integrity verdicts
    # (cluster_ok_redisless_convergence /
    # cluster_ok_integrity_demotion pins)
    decentralized_stats: dict = {}
    if os.environ.get("BENCH_DECENTRALIZED", "1") != "0":
        try:
            decentralized_stats = bench_decentralized(cache_dir)
            log(f"decentralized: {decentralized_stats}")
        except Exception as e:
            decentralized_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"decentralized bench failed: {e!r}")

    # --- interactive session plane (r22): cross-replica delta push
    # latency over a live channel + rolling drain with channel handoff
    # (session_ok_push_latency / session_ok_drain_zero_drops pins)
    session_stats: dict = {}
    if os.environ.get("BENCH_SESSION", "1") != "0":
        try:
            session_stats = bench_session(cache_dir)
            log(f"session: {session_stats}")
        except Exception as e:
            session_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"session bench failed: {e!r}")

    # --- ingest plane (r24): read p99 under concurrent writes +
    # write-to-fresh-read staleness (ingest_ok_* pins) -----------------
    ingest_stats: dict = {}
    if os.environ.get("BENCH_INGEST", "1") != "0":
        try:
            ingest_stats = bench_ingest(cache_dir)
            log(f"ingest: {ingest_stats}")
        except Exception as e:
            ingest_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"ingest bench failed: {e!r}")

    # --- batched read plane (r14): cold remote reads over a loopback
    # HTTP object store — sequential vs parallel+coalesced, sharded
    # byte identity, requests-per-tile (io_ok_* pins)
    io_stats: dict = {}
    if os.environ.get("BENCH_IO", "1") != "0":
        try:
            io_stats = bench_io(cache_dir)
            log(f"io read plane: {io_stats}")
        except Exception as e:
            io_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"io bench failed: {e!r}")

    # --- observability plane (r16): flight-recorder warm-path
    # overhead A/B + forced-slow tail capture (obs_ok_* pins) ----------
    obs_stats: dict = {}
    if os.environ.get("BENCH_OBS", "1") != "0":
        try:
            obs_stats = bench_obs(cache_dir)
            log(f"obs: {obs_stats}")
        except Exception as e:
            obs_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"obs bench failed: {e!r}")

    # --- rendered-tile serving (render/): host vs headline engine ----
    render_stats: dict = {}
    if os.environ.get("BENCH_RENDER", "1") != "0":
        try:
            render_stats = bench_render(cache_dir, pipe.engine)
        except Exception as e:
            render_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"render bench failed: {e!r}")

    # --- analysis plane (r15): histogram throughput host vs engine +
    # masked-render overhead (analysis_ok_* pins) ----------------------
    analysis_stats: dict = {}
    if os.environ.get("BENCH_ANALYSIS", "1") != "0":
        try:
            analysis_stats = bench_analysis(cache_dir, pipe.engine)
        except Exception as e:
            analysis_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"analysis bench failed: {e!r}")

    # --- super-tile plane (r19): 4x4 DZI-row projection burst fused
    # vs independent (supertile_ok_speedup >= 2x +
    # supertile_ok_identical pins) -------------------------------------
    supertile_stats: dict = {}
    if os.environ.get("BENCH_SUPERTILE", "1") != "0":
        try:
            supertile_stats = bench_supertile(cache_dir, pipe.engine)
            log(f"supertile: {supertile_stats}")
        except Exception as e:
            supertile_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"supertile bench failed: {e!r}")

    # --- mesh-fusion plane (r23): fused-mesh vs per-lane-sharded
    # super-tile burst + programs-per-zoom with burst continuation
    # (mesh_ok_* pins) -------------------------------------------------
    mesh_fusion_stats: dict = {}
    if os.environ.get("BENCH_MESH_FUSION", "1") != "0":
        try:
            mesh_fusion_stats = bench_mesh_fusion(cache_dir, pipe.engine)
            log(f"mesh_fusion: {mesh_fusion_stats}")
        except Exception as e:
            mesh_fusion_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"mesh_fusion bench failed: {e!r}")

    if os.environ.get("BENCH_SUBS", "1") != "0":
        try:
            sub_benches(pipe, service, size, cache_dir)
        except Exception as e:
            log(f"sub-benches failed: {e!r}")

    # --- accelerator-engine sub-run (bounded child; last so a wedged
    # tunnel can't cost anything already measured) ---------------------
    device_stats: dict = {}
    if os.environ.get("BENCH_DEVICE", "1") != "0":
        try:
            device_stats = bench_device(path, size, probe_info)
        except Exception as e:
            device_stats = {"error": f"{type(e).__name__}: {e}"}
            log(f"device bench failed: {e!r}")

    record = {
        "metric": "tiles_per_sec_512x512_uint16_png",
        "value": round(tpu_tps, 2),
        "unit": "tiles/s",
        "vs_baseline": round(tpu_tps / host_tps, 3),
        "engine": pipe.engine,
        "baseline_tiles_per_sec": round(host_tps, 2),
    }
    record.update(
        {k: v for k, v in http_stats.items() if k != "engine"}
    )
    if cache_stats:
        record["cache"] = cache_stats
    if plane_stats:
        record["cache_plane"] = plane_stats
    if cluster_stats:
        record["cluster"] = cluster_stats
    if lifecycle_stats:
        record["lifecycle"] = lifecycle_stats
    if decentralized_stats:
        record["decentralized"] = decentralized_stats
    if session_stats:
        record["session"] = session_stats
    if ingest_stats:
        record["ingest"] = ingest_stats
    if overload_stats:
        record["overload"] = overload_stats
    if io_stats:
        record["io"] = io_stats
    if obs_stats:
        record["obs"] = obs_stats
    if render_stats:
        record["render"] = render_stats
    if analysis_stats:
        record["analysis"] = analysis_stats
    if supertile_stats:
        record["supertile"] = supertile_stats
    if mesh_fusion_stats:
        record["mesh_fusion"] = mesh_fusion_stats
    if device_stats:
        record["device"] = device_stats
    # explicit host-vs-device table so the next round can read WHICH
    # engine/stage moved without diffing nested sections
    comparison = {
        "sequential_host": round(host_tps, 2),
        f"batched_{pipe.engine}": round(tpu_tps, 2),
    }
    for k, v in device_stats.items():
        if k.startswith("tiles_per_sec_"):
            comparison["device_" + k[len("tiles_per_sec_"):]] = v
    for label, stats in render_stats.items():
        if isinstance(stats, dict) and "tiles_per_sec" in stats:
            comparison[f"render_{label}"] = stats["tiles_per_sec"]
    for label, stats in analysis_stats.items():
        if isinstance(stats, dict) and "hist_tiles_per_sec" in stats:
            comparison[f"hist_{label}"] = stats["hist_tiles_per_sec"]
    if "masked_overhead_ratio" in analysis_stats:
        comparison["masked_overhead_ratio"] = (
            analysis_stats["masked_overhead_ratio"]
        )
    for label, stats in supertile_stats.items():
        if isinstance(stats, dict) and "fused_tiles_per_sec" in stats:
            comparison[f"supertile_fused_{label}"] = (
                stats["fused_tiles_per_sec"]
            )
            comparison[f"supertile_independent_{label}"] = (
                stats["independent_tiles_per_sec"]
            )
    mesh_half = mesh_fusion_stats.get("mesh") or {}
    if "fused_mesh_tiles_per_sec" in mesh_half:
        comparison["mesh_fused_tiles_per_sec"] = (
            mesh_half["fused_mesh_tiles_per_sec"]
        )
        comparison["mesh_per_lane_sharded_tiles_per_sec"] = (
            mesh_half["per_lane_sharded_tiles_per_sec"]
        )
    burst_half = mesh_fusion_stats.get("burst") or {}
    if "continuation_on_programs" in burst_half:
        comparison["burst_programs_continuation_on"] = (
            burst_half["continuation_on_programs"]
        )
        comparison["burst_programs_continuation_off"] = (
            burst_half["continuation_off_programs"]
        )
    micro = device_stats.get("micro") or {}
    for k in (
        "deflate_gbps", "pack_gbps", "pack_speedup_vs_gather",
        "deflate_ratio_vs_host_dynamic", "deflate_ratio_vs_host_rle_rgb",
        "deflate_dynamic_gbps",
    ):
        if k in micro:
            comparison[k] = micro[k]
    if "emit_ops_per_token" in micro:
        comparison["emit_ops_per_token"] = micro["emit_ops_per_token"]
    if "stage_breakdown" in micro:
        comparison["device_stage_breakdown"] = micro["stage_breakdown"]
    if "queue" in device_stats:
        comparison["device_queue"] = device_stats["queue"]
    if io_stats and "parallel" in io_stats:
        comparison["io_cold_sequential_tiles_per_sec"] = (
            io_stats["sequential"]["tiles_per_sec"]
        )
        comparison["io_cold_parallel_tiles_per_sec"] = (
            io_stats["parallel"]["tiles_per_sec"]
        )
        comparison["io_requests_per_tile"] = (
            io_stats["parallel"]["requests_per_tile"]
        )
        comparison["io_coalesced_ratio"] = (
            io_stats["parallel"]["coalesced_ratio"]
        )
    if overload_stats and "interactive" in overload_stats:
        comparison["slo_interactive_p99_ms"] = (
            overload_stats["interactive"]["p99_ms"]
        )
        comparison["slo_interactive_degraded_fraction"] = (
            overload_stats["interactive"]["degraded_fraction"]
        )
    if obs_stats and "warm_p50_penalty" in obs_stats:
        comparison["obs_warm_p50_penalty"] = (
            obs_stats["warm_p50_penalty"]
        )
    if cluster_stats and "failover" in cluster_stats:
        comparison["cluster_failover_hit_rate"] = (
            cluster_stats["failover"]["replicated"]["hit_rate"]
        )
        comparison["cluster_failover_hit_rate_unreplicated"] = (
            cluster_stats["failover"]["unreplicated"]["hit_rate"]
        )
        comparison["cluster_join_warm_s"] = (
            cluster_stats["join"]["join_to_90pct_warm_s"]
        )
        comparison["cluster_hedged_peer_p99_ms"] = (
            cluster_stats["hedge"]["hedged"]["p99_ms"]
        )
        comparison["cluster_unhedged_peer_p99_ms"] = (
            cluster_stats["hedge"]["unhedged"]["p99_ms"]
        )
    if lifecycle_stats and "rolling_restart" in lifecycle_stats:
        comparison["cluster_drain_serving_errors"] = (
            lifecycle_stats["rolling_restart"]["serving_errors"]
        )
        comparison["cluster_drain_warm_hit_rate"] = (
            lifecycle_stats["rolling_restart"]["warm_hit_rate"]
        )
        comparison["cluster_repair_rounds_to_converge"] = (
            lifecycle_stats["repair"]["rounds_to_converge"]
        )
    if decentralized_stats and "redisless" in decentralized_stats:
        comparison["cluster_redisless_warm_hit_rate"] = (
            decentralized_stats["redisless"][
                "post_outage_warm_hit_rate"
            ]
        )
        comparison["cluster_integrity_rounds_to_demote"] = (
            decentralized_stats["integrity"]["rounds_to_demote"]
        )
    if session_stats and "push" in session_stats:
        comparison["session_push_p99_ms"] = (
            session_stats["push"]["p99_ms"]
        )
        comparison["session_drain_reconnects"] = (
            session_stats["drain"]["reconnect_frames"]
        )
        comparison["session_drain_serving_errors"] = (
            session_stats["drain"]["serving_errors"]
        )
    if ingest_stats and "concurrent_read_p99_ms" in ingest_stats:
        comparison["ingest_read_p99_ms"] = (
            ingest_stats["concurrent_read_p99_ms"]
        )
        comparison["ingest_baseline_read_p99_ms"] = (
            ingest_stats["baseline_read_p99_ms"]
        )
        comparison["ingest_stale_first_reads"] = (
            ingest_stats["stale_first_reads"]
        )
    record["engine_comparison"] = comparison
    print(json.dumps(record))


def sub_benches(pipe, service, size, cache_dir):
    """The remaining BASELINE.md measurement-matrix configs, scaled to
    bench-friendly sizes; stderr only (the driver consumes stdout)."""
    import time as _t

    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
    from omero_ms_pixel_buffer_tpu.runtime.native import get_engine

    rng = np.random.default_rng(3)

    # -- config 2: random 256x256 replay, format=raw -------------------
    ctxs = make_ctxs(256, size, tile=256, fmt=None, seed=13)
    pipe.handle_batch(ctxs[:32])
    t0 = _t.perf_counter()
    for i in range(0, len(ctxs), 32):
        results = pipe.handle_batch(ctxs[i : i + 32])
        assert all(r is not None for r in results)
    log(f"[sub] raw 256x256 replay: "
        f"{len(ctxs) / (_t.perf_counter() - t0):.1f} tiles/s")

    # -- config 3: multi-Z stack, PNG coalesced across Z ---------------
    zpath = os.path.join(cache_dir, "bench_z8.ome.tiff")
    if not os.path.exists(zpath):
        zdata = rng.integers(
            0, 60000, (1, 1, 8, 1024, 1024), dtype=np.uint16
        )
        write_ome_tiff(zpath, zdata, tile_size=(512, 512),
                       compression="zlib")
    registry = ImageRegistry()
    registry.add(2, zpath)
    zservice = PixelsService(registry)
    zpipe = TilePipeline(zservice, engine=pipe.engine)
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

    zctxs = [
        TileCtx(image_id=2, z=z, c=0, t=0,
                region=RegionDef(256, 256, 512, 512), format="png",
                omero_session_key="bench")
        for z in range(8)
    ] * 8  # 64 requests coalescing across the Z axis
    zpipe.handle_batch(zctxs[:16])
    t0 = _t.perf_counter()
    for i in range(0, len(zctxs), 32):
        results = zpipe.handle_batch(zctxs[i : i + 32])
        assert all(r is not None for r in results)
    log(f"[sub] multi-Z 512x512 png (coalesced): "
        f"{len(zctxs) / (_t.perf_counter() - t0):.1f} tiles/s")
    zservice.close()

    # -- config 4 (scaled): RGB8 256x256 encode sweep ------------------
    engine = get_engine()
    if engine is not None:
        rgb = [
            rng.integers(0, 255, (256, 256, 3), dtype=np.uint8)
            for _ in range(64)
        ]
        engine.png_encode_batch(rgb[:8], "up", 6, strategy="fast")
        t0 = _t.perf_counter()
        out = engine.png_encode_batch(rgb, "up", 6, strategy="fast")
        assert all(o is not None for o in out)
        log(f"[sub] rgb8 256x256 png encode: "
            f"{len(rgb) / (_t.perf_counter() - t0):.1f} tiles/s")

    # -- config 4b: JPEG whole-slide RGB pyramid, 256x256 png sweep ----
    # (the actual config-4 storage: JPEG-compressed tiled RGB TIFF,
    # read through the in-tree baseline decoder, served as PNG)
    jpath = os.path.join(cache_dir, "bench_rgb_jpeg.ome.tiff")
    if not os.path.exists(jpath):
        yy, xx = np.mgrid[0:2048, 0:2048].astype(np.float32)
        base = (
            128 + 60 * np.sin(xx / 37) + 50 * np.cos(yy / 53)
            + rng.normal(0, 8, (2048, 2048))
        ).clip(0, 255).astype(np.uint8)
        rgbdata = np.stack(
            [base, np.roll(base, 11, 0), np.roll(base, 7, 1)], -1
        )
        write_ome_tiff(
            jpath, rgbdata[None, None, None], tile_size=(256, 256),
            compression="jpeg", pyramid_levels=2,
        )
    jreg = ImageRegistry()
    jreg.add(3, jpath)
    jsvc = PixelsService(jreg)
    jpipe = TilePipeline(jsvc, engine=pipe.engine)
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef as _RD
    from omero_ms_pixel_buffer_tpu.tile_ctx import TileCtx as _TC

    jctxs = []
    for _ in range(128):
        x = int(rng.integers(0, (2048 - 256) // 64)) * 64
        y = int(rng.integers(0, (2048 - 256) // 64)) * 64
        jctxs.append(
            _TC(image_id=3, z=0, c=int(rng.integers(0, 3)), t=0,
                region=_RD(x, y, 256, 256), format="png",
                omero_session_key="bench")
        )
    jpipe.handle_batch(jctxs[:16])
    t0 = _t.perf_counter()
    for i in range(0, len(jctxs), 32):
        results = jpipe.handle_batch(jctxs[i : i + 32])
        assert all(r is not None for r in results)
    log(f"[sub] jpeg-rgb 256x256 png sweep: "
        f"{len(jctxs) / (_t.perf_counter() - t0):.1f} tiles/s")
    jsvc.close()

    # -- config 5 (scaled): concurrent format=tif fan-out --------------
    tctxs = make_ctxs(128, size, tile=512, fmt="tif", seed=17)
    pipe.handle_batch(tctxs[:16])
    t0 = _t.perf_counter()
    for i in range(0, len(tctxs), 32):
        results = pipe.handle_batch(tctxs[i : i + 32])
        assert all(r is not None for r in results)
    log(f"[sub] tif 512x512 fan-out: "
        f"{len(tctxs) / (_t.perf_counter() - t0):.1f} tiles/s")


if __name__ == "__main__":
    if "--device-sub" in sys.argv:
        device_sub_main()
        sys.exit(0)
    try:
        main()
    except Exception as e:
        # last-resort: the driver must always get a parseable record
        log(f"FATAL: {e!r}")
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(
            json.dumps(
                {
                    "metric": "tiles_per_sec_512x512_uint16_png",
                    "value": 0.0,
                    "unit": "tiles/s",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        )
        sys.exit(0)
