"""North-star benchmark (BASELINE.json): tiles/sec for 512x512 uint16
PNG tiles served from a large pyramidal OME-TIFF under concurrent load.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

- value: tiles/sec of the batched TPU pipeline (coalesced batches,
  device byteswap+filter, threaded host deflate) over 1024 requests.
- vs_baseline: speedup over the reference-architecture path measured
  in-process — one request at a time, single-threaded, host-only
  (read -> numpy filter -> zlib), i.e. the shape of the reference's
  per-request Java worker (TileRequestHandler.java:80-139). The Java
  service itself is not runnable in this environment (BASELINE.md:
  baseline must be measured); this stand-in preserves its execution
  structure on identical inputs.

All progress chatter goes to stderr; stdout carries only the JSON line.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build_fixture(root: str, size: int = 8192):
    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff

    path = os.path.join(root, f"bench_{size}.ome.tiff")
    if os.path.exists(path):
        return path
    log(f"writing {size}x{size} uint16 fixture...")
    rng = np.random.default_rng(42)
    # smooth-ish synthetic microscopy-like data (compresses realistically,
    # unlike white noise)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    base = (
        2000
        + 1500 * np.sin(xx / 97.0)
        + 1500 * np.cos(yy / 131.0)
    )
    data = (base + rng.normal(0, 120, (size, size))).clip(0, 65535)
    data = data.astype(np.uint16)[None, None, None]
    write_ome_tiff(path, data, tile_size=(512, 512), compression="zlib")
    return path


def make_ctxs(n, size, tile=512, fmt="png", seed=7):
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

    rng = np.random.default_rng(seed)
    ctxs = []
    for _ in range(n):
        x = int(rng.integers(0, (size - tile) // 64)) * 64
        y = int(rng.integers(0, (size - tile) // 64)) * 64
        ctxs.append(
            TileCtx(
                image_id=1, z=0, c=0, t=0,
                region=RegionDef(x, y, tile, tile),
                format=fmt, omero_session_key="bench",
            )
        )
    return ctxs


def main():
    t_setup = time.perf_counter()
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline

    cache_dir = os.environ.get(
        "BENCH_CACHE", os.path.join(tempfile.gettempdir(), "ompb_bench")
    )
    os.makedirs(cache_dir, exist_ok=True)
    size = int(os.environ.get("BENCH_IMAGE_SIZE", "8192"))
    n_requests = int(os.environ.get("BENCH_REQUESTS", "1024"))
    batch = int(os.environ.get("BENCH_BATCH", "32"))
    path = build_fixture(cache_dir, size)

    registry = ImageRegistry()
    registry.add(1, path)

    # --- baseline: reference-architecture path (sequential, host) -----
    # Separate service with the decoded-block cache OFF: the reference
    # re-opens and re-decodes per request (TileRequestHandler.java:86),
    # so its stand-in must too. Python (not native) encode, one at a
    # time, single worker — the Java worker-thread shape.
    base_service = PixelsService(registry, block_cache_bytes=0)
    base_pipe = TilePipeline(
        base_service, use_device=False, encode_workers=1,
        png_level=6, png_strategy="default",  # Java Deflater defaults
    )
    base_ctxs = make_ctxs(64, size)
    for ctx in base_ctxs[:4]:  # warm page cache + code paths
        assert base_pipe.handle(ctx) is not None
    t0 = time.perf_counter()
    for ctx in base_ctxs:
        out = base_pipe.handle(ctx)
        assert out is not None
    host_tps = len(base_ctxs) / (time.perf_counter() - t0)
    log(f"baseline (sequential host path): {host_tps:.1f} tiles/s")

    # --- framework batched path (auto engine) -------------------------
    import jax

    log(f"jax backend: {jax.default_backend()} devices: {jax.devices()}")
    service = PixelsService(registry)
    engine = os.environ.get("BENCH_ENGINE", "auto")
    pipe = TilePipeline(service, engine=engine, buckets=(512,))
    ctxs = make_ctxs(n_requests, size, seed=9)
    # warmup: resolve auto engine, trigger jit/native build
    warm = pipe.handle_batch(ctxs[:batch])
    assert all(w is not None for w in warm)
    log(f"engine: {pipe.engine}")
    t0 = time.perf_counter()
    done = 0
    for i in range(0, len(ctxs), batch):
        chunk = ctxs[i : i + batch]
        results = pipe.handle_batch(chunk)
        assert all(r is not None for r in results), "bench tile failed"
        done += len(chunk)
    elapsed = time.perf_counter() - t0
    tpu_tps = done / elapsed
    log(
        f"batched path ({pipe.engine}): {tpu_tps:.1f} tiles/s over "
        f"{done} tiles ({elapsed:.2f}s; setup+warmup "
        f"{time.perf_counter() - t_setup - elapsed:.1f}s)"
    )

    if os.environ.get("BENCH_SUBS", "1") != "0":
        sub_benches(pipe, service, size, cache_dir)

    print(
        json.dumps(
            {
                "metric": "tiles_per_sec_512x512_uint16_png",
                "value": round(tpu_tps, 2),
                "unit": "tiles/s",
                "vs_baseline": round(tpu_tps / host_tps, 3),
            }
        )
    )


def sub_benches(pipe, service, size, cache_dir):
    """The remaining BASELINE.md measurement-matrix configs, scaled to
    bench-friendly sizes; stderr only (the driver consumes stdout)."""
    import time as _t

    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
    from omero_ms_pixel_buffer_tpu.runtime.native import get_engine

    rng = np.random.default_rng(3)

    # -- config 2: random 256x256 replay, format=raw -------------------
    ctxs = make_ctxs(256, size, tile=256, fmt=None, seed=13)
    pipe.handle_batch(ctxs[:32])
    t0 = _t.perf_counter()
    for i in range(0, len(ctxs), 32):
        results = pipe.handle_batch(ctxs[i : i + 32])
        assert all(r is not None for r in results)
    log(f"[sub] raw 256x256 replay: "
        f"{len(ctxs) / (_t.perf_counter() - t0):.1f} tiles/s")

    # -- config 3: multi-Z stack, PNG coalesced across Z ---------------
    zpath = os.path.join(cache_dir, "bench_z8.ome.tiff")
    if not os.path.exists(zpath):
        zdata = rng.integers(
            0, 60000, (1, 1, 8, 1024, 1024), dtype=np.uint16
        )
        write_ome_tiff(zpath, zdata, tile_size=(512, 512),
                       compression="zlib")
    registry = ImageRegistry()
    registry.add(2, zpath)
    zservice = PixelsService(registry)
    zpipe = TilePipeline(zservice, engine=pipe.engine)
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

    zctxs = [
        TileCtx(image_id=2, z=z, c=0, t=0,
                region=RegionDef(256, 256, 512, 512), format="png",
                omero_session_key="bench")
        for z in range(8)
    ] * 8  # 64 requests coalescing across the Z axis
    zpipe.handle_batch(zctxs[:16])
    t0 = _t.perf_counter()
    for i in range(0, len(zctxs), 32):
        results = zpipe.handle_batch(zctxs[i : i + 32])
        assert all(r is not None for r in results)
    log(f"[sub] multi-Z 512x512 png (coalesced): "
        f"{len(zctxs) / (_t.perf_counter() - t0):.1f} tiles/s")
    zservice.close()

    # -- config 4 (scaled): RGB8 256x256 encode sweep ------------------
    engine = get_engine()
    if engine is not None:
        rgb = [
            rng.integers(0, 255, (256, 256, 3), dtype=np.uint8)
            for _ in range(64)
        ]
        engine.png_encode_batch(rgb[:8], "up", 6, strategy="fast")
        t0 = _t.perf_counter()
        out = engine.png_encode_batch(rgb, "up", 6, strategy="fast")
        assert all(o is not None for o in out)
        log(f"[sub] rgb8 256x256 png encode: "
            f"{len(rgb) / (_t.perf_counter() - t0):.1f} tiles/s")

    # -- config 5 (scaled): concurrent format=tif fan-out --------------
    tctxs = make_ctxs(128, size, tile=512, fmt="tif", seed=17)
    pipe.handle_batch(tctxs[:16])
    t0 = _t.perf_counter()
    for i in range(0, len(tctxs), 32):
        results = pipe.handle_batch(tctxs[i : i + 32])
        assert all(r is not None for r in results)
    log(f"[sub] tif 512x512 fan-out: "
        f"{len(tctxs) / (_t.perf_counter() - t0):.1f} tiles/s")


if __name__ == "__main__":
    main()
