"""Multi-chip sharding: data-parallel batch filtering and
space-parallel plane filtering with halo exchange, on the 8-virtual-
device CPU mesh (conftest). Results must be bit-identical to the
single-device path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from omero_ms_pixel_buffer_tpu.ops.convert import to_big_endian_bytes
from omero_ms_pixel_buffer_tpu.ops.png import _filter_batch, assemble_png
from omero_ms_pixel_buffer_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    row_sharding,
)
from omero_ms_pixel_buffer_tpu.parallel.sharding import (
    distributed_filter_plane,
    shard_batch,
    shard_rows,
    sharded_batch_filter,
)

rng = np.random.default_rng(11)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest should provide 8 CPU devices"
    return make_mesh(("data",))


class TestDataParallel:
    @pytest.mark.parametrize("dtype", [np.uint8, np.uint16])
    def test_batch_matches_single_device(self, mesh, dtype):
        bpp = np.dtype(dtype).itemsize
        batch = rng.integers(
            0, np.iinfo(dtype).max, (16, 32, 48), dtype=dtype
        )
        sharded = shard_batch(mesh, jnp.asarray(batch))
        out = np.asarray(sharded_batch_filter(mesh, sharded, bpp=bpp))
        ref = np.asarray(
            _filter_batch(to_big_endian_bytes(jnp.asarray(batch)), bpp, "up")
        )
        np.testing.assert_array_equal(out, ref)

    def test_output_stays_sharded(self, mesh):
        batch = rng.integers(0, 60000, (8, 16, 16), dtype=np.uint16)
        sharded = shard_batch(mesh, jnp.asarray(batch))
        out = sharded_batch_filter(mesh, sharded, bpp=2)
        assert out.sharding.is_equivalent_to(
            batch_sharding(mesh), ndim=out.ndim
        )


class TestSpaceParallel:
    def test_plane_matches_single_device(self, mesh):
        plane = rng.integers(0, 60000, (64, 40), dtype=np.uint16)
        rows_sharded = shard_rows(mesh, jnp.asarray(plane))
        out = np.asarray(distributed_filter_plane(mesh, rows_sharded))
        ref = np.asarray(
            _filter_batch(to_big_endian_bytes(jnp.asarray(plane[None])), 2, "up")
        )[0]
        np.testing.assert_array_equal(out, ref)

    def test_distributed_scanlines_make_valid_png(self, mesh):
        from PIL import Image
        import io

        plane = rng.integers(0, 60000, (64, 40), dtype=np.uint16)
        rows_sharded = shard_rows(mesh, jnp.asarray(plane))
        filtered = np.asarray(distributed_filter_plane(mesh, rows_sharded))
        png = assemble_png(filtered.tobytes(), 40, 64, 16, 0)
        decoded = np.array(Image.open(io.BytesIO(png)))
        np.testing.assert_array_equal(decoded.astype(np.uint16), plane)

    def test_sharding_layout(self, mesh):
        plane = rng.integers(0, 200, (32, 16), dtype=np.uint8)
        rows_sharded = shard_rows(mesh, jnp.asarray(plane))
        out = distributed_filter_plane(mesh, rows_sharded)
        assert out.sharding.is_equivalent_to(row_sharding(mesh), ndim=2)


class TestGraftEntry:
    def test_entry_compiles(self):
        import sys

        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (8, 256, 513)

    def test_dryrun_multichip(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)
        g.dryrun_multichip(4)
        g.dryrun_multichip(1)
