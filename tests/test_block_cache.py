"""Decoded-block cache + engine selection (the perf layer added on top
of the readers and the pipeline)."""

import numpy as np
import pytest

from omero_ms_pixel_buffer_tpu.io.ometiff import (
    OmeTiffPixelBuffer,
    write_ome_tiff,
)
from omero_ms_pixel_buffer_tpu.io.pixel_buffer import BlockCache
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.io.zarr import ZarrPixelBuffer, write_ngff
from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
from omero_ms_pixel_buffer_tpu.ops.png import decode_png
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx


class TestBlockCache:
    def test_lru_byte_bound(self):
        cache = BlockCache(max_bytes=100)
        for i in range(5):
            cache[i] = np.zeros(40, np.uint8)
        assert cache.nbytes <= 100
        assert cache.get(0) is None  # evicted
        assert cache.get(4) is not None

    def test_get_refreshes_recency(self):
        cache = BlockCache(max_bytes=100)
        cache["a"] = np.zeros(40, np.uint8)
        cache["b"] = np.zeros(40, np.uint8)
        cache.get("a")  # now "b" is LRU
        cache["c"] = np.zeros(40, np.uint8)
        assert cache.get("a") is not None
        assert cache.get("b") is None

    def test_disabled_and_oversized(self):
        cache = BlockCache(max_bytes=0)
        cache["x"] = np.zeros(8, np.uint8)
        assert cache.get("x") is None
        cache = BlockCache(max_bytes=10)
        cache["big"] = np.zeros(100, np.uint8)  # larger than budget
        assert cache.get("big") is None
        assert cache.nbytes == 0

    def test_none_values_cached(self):
        cache = BlockCache(max_bytes=100)
        sentinel = object()
        cache["absent-chunk"] = None
        assert cache.get("absent-chunk", sentinel) is None
        assert cache.get("other", sentinel) is sentinel


@pytest.fixture
def tiff_image(tmp_path):
    rng = np.random.default_rng(5)
    data = rng.integers(0, 60000, (1, 1, 1, 512, 512), dtype=np.uint16)
    path = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(path, data, tile_size=(128, 128), compression="zlib")
    return path, data[0, 0, 0]


class TestReaderCaches:
    def test_ometiff_cache_hits_and_correctness(self, tiff_image):
        path, truth = tiff_image
        buf = OmeTiffPixelBuffer(path)
        t1 = buf.get_tile_at(0, 0, 0, 0, 32, 32, 200, 200)
        misses = buf.block_cache.misses
        assert len(buf.block_cache) > 0
        t2 = buf.get_tile_at(0, 0, 0, 0, 32, 32, 200, 200)
        assert buf.block_cache.misses == misses  # pure hits second time
        np.testing.assert_array_equal(t1, truth[32:232, 32:232])
        np.testing.assert_array_equal(t2, t1)
        buf.close()

    def test_ometiff_batched_reads_use_cache(self, tiff_image):
        path, truth = tiff_image
        buf = OmeTiffPixelBuffer(path)
        coords = [(0, 0, 0, 0, 0, 256, 256), (0, 0, 0, 64, 64, 256, 256)]
        first = buf.read_tiles(coords)
        second = buf.read_tiles(coords)
        for (z, c, t, x, y, w, h), a, b in zip(coords, first, second):
            np.testing.assert_array_equal(a, truth[y : y + h, x : x + w])
            np.testing.assert_array_equal(a, b)
        buf.close()

    def test_ometiff_disabled_cache_still_correct(self, tiff_image):
        path, truth = tiff_image
        buf = OmeTiffPixelBuffer(path, cache_bytes=0)
        tile = buf.get_tile_at(0, 0, 0, 0, 0, 0, 128, 128)
        np.testing.assert_array_equal(tile, truth[:128, :128])
        assert len(buf.block_cache) == 0
        buf.close()

    def test_zarr_persistent_cache(self, tmp_path):
        rng = np.random.default_rng(6)
        data = rng.integers(0, 255, (1, 1, 1, 256, 256), dtype=np.uint8)
        root = str(tmp_path / "img.zarr")
        write_ngff(root, data, chunks=(64, 64), compressor="zlib")
        buf = ZarrPixelBuffer(root)
        t1 = buf.get_tile_at(0, 0, 0, 0, 10, 10, 100, 100)
        assert len(buf.block_cache) > 0
        misses = buf.block_cache.misses
        t2 = buf.get_tile_at(0, 0, 0, 0, 10, 10, 100, 100)
        assert buf.block_cache.misses == misses
        np.testing.assert_array_equal(t1, data[0, 0, 0, 10:110, 10:110])
        np.testing.assert_array_equal(t1, t2)

    def test_zarr_disabled_cache_still_correct(self, tmp_path):
        rng = np.random.default_rng(6)
        data = rng.integers(0, 255, (1, 1, 1, 128, 128), dtype=np.uint8)
        root = str(tmp_path / "img.zarr")
        write_ngff(root, data, chunks=(64, 64), compressor="zlib")
        buf = ZarrPixelBuffer(root, cache_bytes=0)
        tiles = buf.read_tiles([(0, 0, 0, 0, 0, 128, 128)])
        np.testing.assert_array_equal(tiles[0], data[0, 0, 0])
        assert len(buf.block_cache) == 0

    def test_shared_cache_no_cross_buffer_aliasing(self, tmp_path):
        """Two buffers sharing one BlockCache must never serve each
        other's blocks, even with identical block indices."""
        shared = BlockCache(max_bytes=64 << 20)
        rng = np.random.default_rng(7)
        bufs, truths = [], []
        for k in range(2):
            data = rng.integers(0, 60000, (1, 1, 1, 256, 256), np.uint16)
            path = str(tmp_path / f"img{k}.ome.tiff")
            write_ome_tiff(path, data, tile_size=(128, 128), compression="zlib")
            bufs.append(OmeTiffPixelBuffer(path, block_cache=shared))
            truths.append(data[0, 0, 0])
        for buf, truth in zip(bufs, truths):
            tile = buf.get_tile_at(0, 0, 0, 0, 0, 0, 256, 256)
            np.testing.assert_array_equal(tile, truth)
        # again, now everything is cached — still the right image
        for buf, truth in zip(bufs, truths):
            tile = buf.get_tile_at(0, 0, 0, 0, 64, 64, 128, 128)
            np.testing.assert_array_equal(tile, truth[64:192, 64:192])
        for buf in bufs:
            buf.close()

    def test_pixels_service_shares_one_cache(self, tiff_image, tmp_path):
        path, _ = tiff_image
        registry = ImageRegistry()
        registry.add(1, path)
        service = PixelsService(registry, block_cache_bytes=32 << 20)
        buf = service.get_pixel_buffer(1)
        assert buf.block_cache is service.block_cache
        service.close()


class TestEngineSelection:
    def _service(self, tiff_image):
        path, truth = tiff_image
        registry = ImageRegistry()
        registry.add(1, path)
        return PixelsService(registry), truth

    def _ctx(self, fmt="png"):
        return TileCtx(
            image_id=1, z=0, c=0, t=0,
            region=RegionDef(16, 48, 200, 160),
            format=fmt, omero_session_key="k",
        )

    def test_auto_resolves_to_host_on_cpu(self, tiff_image):
        service, _ = self._service(tiff_image)
        pipe = TilePipeline(service, engine="auto")
        # conftest pins JAX to the CPU backend -> auto must pick host
        assert pipe.engine == "host"
        assert not pipe.use_device

    def test_invalid_engine_rejected(self, tiff_image):
        service, _ = self._service(tiff_image)
        with pytest.raises(ValueError):
            TilePipeline(service, engine="gpu")

    def test_host_and_device_agree(self, tiff_image):
        service, truth = self._service(tiff_image)
        expected = truth[48:208, 16:216]
        host = TilePipeline(service, engine="host")
        device = TilePipeline(service, engine="device", use_pallas=False)
        out_h = host.handle_batch([self._ctx()])[0]
        out_d = device.handle_batch([self._ctx()])[0]
        np.testing.assert_array_equal(decode_png(out_h), expected)
        np.testing.assert_array_equal(decode_png(out_d), expected)

    def test_legacy_use_device_mapping(self, tiff_image):
        service, _ = self._service(tiff_image)
        assert TilePipeline(service, use_device=False).engine == "host"
        assert TilePipeline(service, use_device=True).engine == "device"


class TestMemoizer:
    """Persistent IFD-parse memo (the Bio-Formats Memoizer analog)."""

    def test_memo_roundtrip_and_staleness(self, tiff_image, tmp_path,
                                          monkeypatch):
        path, truth = tiff_image
        memo_dir = str(tmp_path / "memo")
        buf = OmeTiffPixelBuffer(path, memo_dir=memo_dir)
        first = buf.get_tile_at(0, 0, 0, 0, 0, 0, 128, 128)
        buf.close()
        import os

        memos = os.listdir(memo_dir)
        assert len(memos) == 1 and memos[0].endswith(".ifd.json")

        # second open must come from the memo: break the parser to prove
        from omero_ms_pixel_buffer_tpu.io import ometiff as mod

        def boom(data):
            raise AssertionError("memo not used")

        monkeypatch.setattr(mod, "_parse_ifds", boom)
        buf2 = OmeTiffPixelBuffer(path, memo_dir=memo_dir)
        np.testing.assert_array_equal(
            buf2.get_tile_at(0, 0, 0, 0, 0, 0, 128, 128), first
        )
        buf2.close()
        monkeypatch.undo()

        # rewriting the file invalidates the memo (key = mtime+size)
        rng = np.random.default_rng(9)
        data = rng.integers(0, 60000, (1, 1, 1, 256, 256), dtype=np.uint16)
        write_ome_tiff(path, data, tile_size=(128, 128), compression="zlib")
        os.utime(path, (1e9, 1e9))  # force distinct mtime
        buf3 = OmeTiffPixelBuffer(path, memo_dir=memo_dir)
        np.testing.assert_array_equal(
            buf3.get_tile_at(0, 0, 0, 0, 0, 0, 256, 256), data[0, 0, 0]
        )
        buf3.close()

    def test_corrupt_memo_falls_back(self, tiff_image, tmp_path):
        path, truth = tiff_image
        memo_dir = tmp_path / "memo"
        memo_dir.mkdir()
        from omero_ms_pixel_buffer_tpu.io.ometiff import _memo_key

        (memo_dir / (_memo_key(path) + ".ifd.json")).write_bytes(b"garbage")
        buf = OmeTiffPixelBuffer(path, memo_dir=str(memo_dir))
        tile = buf.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)
        np.testing.assert_array_equal(tile, truth[:64, :64])
        buf.close()


def test_memo_rewrite_overwrites_not_orphans(tmp_path):
    """A rewritten image reuses its (path-keyed) memo file instead of
    leaking one orphan per rewrite."""
    import os

    rng = np.random.default_rng(31)
    path = str(tmp_path / "img.ome.tiff")
    memo_dir = str(tmp_path / "memo")
    for round_ in range(3):
        data = rng.integers(0, 60000, (1, 1, 1, 128, 128), dtype=np.uint16)
        write_ome_tiff(path, data, tile_size=(64, 64))
        os.utime(path, (1e9 + round_, 1e9 + round_))
        buf = OmeTiffPixelBuffer(path, memo_dir=memo_dir)
        np.testing.assert_array_equal(
            buf.get_tile_at(0, 0, 0, 0, 0, 0, 128, 128), data[0, 0, 0]
        )
        buf.close()
    assert len(os.listdir(memo_dir)) == 1
