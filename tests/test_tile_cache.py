"""Tiered tile-result cache, single-flight, conditional GET, prefetch.

Covers the cache/ package end to end: SLRU mechanics (budget,
promotion, scan resistance), the key schema, single-flight semantics
(one execution, error fan-out, cancellation isolation), HTTP ETag/304
behavior and byte-identity on hits, invalidation (unit + resolver
listener), batch-level key dedup — plus the chaos contract under
``-m resilience``: a faulted disk tier degrades to pass-through, a
flight-leader failure fans out to every waiter, prefetch sheds under
admission pressure, and per-call network timeouts bound the
Postgres/Redis edges.
"""

import asyncio
import io
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

from omero_ms_pixel_buffer_tpu.auth.omero_session import AllowListValidator
from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.cache.prefetch import ViewportPrefetcher
from omero_ms_pixel_buffer_tpu.cache.result_cache import (
    CachedTile,
    SegmentedLRU,
    TileResultCache,
    etag_matches,
    make_etag,
)
from omero_ms_pixel_buffer_tpu.cache.single_flight import SingleFlight
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.resilience import faultinject
from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
from omero_ms_pixel_buffer_tpu.resilience.faultinject import INJECTOR
from omero_ms_pixel_buffer_tpu.resilience.timeouts import set_io_timeout
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

rng = np.random.default_rng(7)
IMG = rng.integers(0, 60000, (1, 1, 2, 256, 256), dtype=np.uint16)
AUTH = {"Cookie": "sessionid=ck"}


@pytest.fixture(autouse=True)
def _clean_chaos():
    INJECTOR.clear()
    yield
    INJECTOR.clear()
    BOARD.reset()
    set_io_timeout(5.0)


def _entry(body: bytes) -> CachedTile:
    return CachedTile(body, filename="f.png")


def _ctx(image_id=1, z=0, c=0, t=0, x=0, y=0, w=64, h=64,
         resolution=None, fmt="png", session="omero-key"):
    return TileCtx(
        image_id=image_id, z=z, c=c, t=t,
        region=RegionDef(x, y, w, h), resolution=resolution,
        format=fmt, omero_session_key=session,
    )


async def _make_app(tmp_path, cache_config=None, validator=None,
                    session_key="omero-key-1"):
    write_ome_tiff(
        str(tmp_path / "img.ome.tiff"), IMG, tile_size=(64, 64),
        pyramid_levels=2,
    )
    registry = ImageRegistry()
    registry.add(1, str(tmp_path / "img.ome.tiff"))
    config = Config.from_dict({
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 1.0}},
        "cache": cache_config if cache_config is not None else {},
    })
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=MemorySessionStore({"ck": session_key}),
        session_validator=validator,
    )
    client = TestClient(
        TestServer(app_obj.make_app()), loop=asyncio.get_running_loop()
    )
    await client.start_server()
    return app_obj, client


# ---------------------------------------------------------------------------
# memory tier: segmented LRU
# ---------------------------------------------------------------------------

class TestSegmentedLRU:
    def test_byte_budget_evicts_lru(self):
        lru = SegmentedLRU(max_bytes=300)
        for i in range(4):
            lru.put(f"k{i}", _entry(b"x" * 100))
        assert lru.nbytes <= 300
        assert lru.get("k0") is None  # oldest one-touch entry left
        assert lru.get("k3") is not None

    def test_second_touch_promotes(self):
        lru = SegmentedLRU(max_bytes=1000)
        lru.put("a", _entry(b"x" * 10))
        assert lru.get("a") is not None  # promoted to protected
        snap = lru.snapshot()
        assert snap["protected_entries"] == 1

    def test_scan_resistance(self):
        """A one-pass scan of cold keys cannot displace the protected
        working set."""
        lru = SegmentedLRU(max_bytes=500, protected_fraction=0.8)
        for k in ("hot1", "hot2"):
            lru.put(k, _entry(b"h" * 100))
            assert lru.get(k) is not None  # promote
        for i in range(50):  # the scan: 50 one-touch entries
            lru.put(f"scan{i}", _entry(b"s" * 100))
        assert lru.get("hot1") is not None
        assert lru.get("hot2") is not None

    def test_oversized_entry_not_admitted(self):
        lru = SegmentedLRU(max_bytes=100)
        lru.put("big", _entry(b"x" * 1000))
        assert len(lru) == 0

    def test_remove_prefix(self):
        lru = SegmentedLRU(max_bytes=10_000)
        lru.put("img=1|a", _entry(b"x"))
        lru.put("img=1|b", _entry(b"y"))
        lru.put("img=2|a", _entry(b"z"))
        assert lru.remove_prefix("img=1|") == 2
        assert lru.peek("img=2|a") is not None
        assert lru.peek("img=1|a") is None


# ---------------------------------------------------------------------------
# key schema + validators
# ---------------------------------------------------------------------------

class TestKeySchema:
    def test_every_dimension_distinguishes(self):
        base = _ctx()
        variants = [
            _ctx(image_id=2), _ctx(z=1), _ctx(c=1), _ctx(t=1),
            _ctx(x=64), _ctx(y=64), _ctx(w=128), _ctx(h=128),
            _ctx(resolution=1), _ctx(fmt="tif"), _ctx(fmt=None),
        ]
        keys = {v.cache_key("q") for v in variants}
        assert base.cache_key("q") not in keys
        assert len(keys) == len(variants)
        # quality (encode signature) is part of the schema
        assert base.cache_key("q1") != base.cache_key("q2")

    def test_session_scopes_dedupe_not_content(self):
        a, b = _ctx(session="s1"), _ctx(session="s2")
        assert a.cache_key("q") == b.cache_key("q")
        assert a.dedupe_key("q") != b.dedupe_key("q")

    def test_etag_matching(self):
        etag = make_etag(b"bytes")
        assert etag_matches(etag, etag)
        assert etag_matches(f'W/{etag}', etag)
        assert etag_matches(f'"other", {etag}', etag)
        # '*' proves no possession: it must NOT match (it would hand
        # an unauthorized caller a cache-state oracle via the 304
        # precheck)
        assert not etag_matches("*", etag)
        assert not etag_matches('"nope"', etag)
        assert not etag_matches("", etag)


# ---------------------------------------------------------------------------
# single-flight
# ---------------------------------------------------------------------------

class TestSingleFlight:
    async def test_concurrent_misses_one_execution(self):
        flight = SingleFlight()
        calls = []

        async def factory():
            calls.append(1)
            await asyncio.sleep(0.02)
            return "tile"

        results = await asyncio.gather(
            *(flight.do("k", factory) for _ in range(8))
        )
        assert results == ["tile"] * 8
        assert len(calls) == 1
        assert flight.active == 0

    async def test_error_fans_out_to_all_waiters(self):
        flight = SingleFlight()
        calls = []

        async def boom():
            calls.append(1)
            await asyncio.sleep(0.02)
            raise RuntimeError("leader failed")

        results = await asyncio.gather(
            *(flight.do("k", boom) for _ in range(5)),
            return_exceptions=True,
        )
        assert len(calls) == 1
        assert all(isinstance(r, RuntimeError) for r in results)

    async def test_waiter_cancellation_does_not_kill_flight(self):
        flight = SingleFlight()
        done = asyncio.Event()

        async def factory():
            await asyncio.sleep(0.05)
            done.set()
            return "tile"

        w1 = asyncio.ensure_future(flight.do("k", factory))
        await asyncio.sleep(0.01)
        w2 = asyncio.ensure_future(flight.do("k", factory))
        await asyncio.sleep(0.01)
        w1.cancel()
        assert await w2 == "tile"  # survivor gets the result
        assert done.is_set()

    async def test_waiter_timeout_leaves_flight_running(self):
        flight = SingleFlight()

        async def slow():
            await asyncio.sleep(0.08)
            return "tile"

        fast = asyncio.ensure_future(flight.do("k", slow, timeout_s=0.01))
        patient = asyncio.ensure_future(flight.do("k", slow))
        with pytest.raises(asyncio.TimeoutError):
            await fast
        assert await patient == "tile"

    async def test_sequential_calls_rerun(self):
        flight = SingleFlight()
        calls = []

        async def factory():
            calls.append(1)
            return len(calls)

        assert await flight.do("k", factory) == 1
        assert await flight.do("k", factory) == 2  # no stale reuse


# ---------------------------------------------------------------------------
# tiered cache behavior
# ---------------------------------------------------------------------------

class TestTieredCache:
    async def test_disk_spill_and_readmission(self, tmp_path):
        cache = TileResultCache(
            memory_bytes=250, disk_dir=str(tmp_path / "spill"),
            disk_bytes=1 << 20,
        )
        try:
            await cache.put("img=1|a", _entry(b"a" * 100))
            await cache.put("img=1|b", _entry(b"b" * 100))
            await cache.put("img=1|c", _entry(b"c" * 100))  # evicts a
            # wait out the executor hop
            for _ in range(50):
                if len(cache.disk):
                    break
                await asyncio.sleep(0.01)
            assert len(cache.disk) >= 1
            entry = await cache.get("img=1|a")  # disk hit, re-admitted
            assert entry is not None and entry.body == b"a" * 100
            assert cache.contains("img=1|a")
        finally:
            cache.close()

    async def test_contains_any_tier_sees_disk_index(self, tmp_path):
        """The overload door gate's probe: disk-resident entries are
        visible (index peek, no file I/O), memory-only ``contains``
        stays blind to them, and TTL applies to both tiers."""
        cache = TileResultCache(
            memory_bytes=250, disk_dir=str(tmp_path / "spill"),
            disk_bytes=1 << 20, ttl_s=30.0,
        )
        try:
            await cache.put("img=1|a", _entry(b"a" * 100))
            await cache.put("img=1|b", _entry(b"b" * 100))
            await cache.put("img=1|c", _entry(b"c" * 100))  # evicts a
            for _ in range(50):
                if len(cache.disk):
                    break
                await asyncio.sleep(0.01)
            assert not cache.contains("img=1|a")  # RAM-only probe
            assert cache.contains_any_tier("img=1|a")
            assert not cache.contains_any_tier("img=1|zz")
            # a TTL-expired disk entry would miss at get-time: the
            # probe must not pass it through the door either
            with cache.disk._lock:
                path, nb, etag, fn, _ = cache.disk._index["img=1|a"]
                cache.disk._index["img=1|a"] = (
                    path, nb, etag, fn, time.monotonic() - 60.0,
                )
            assert not cache.contains_any_tier("img=1|a")
        finally:
            cache.close()

    async def test_invalidate_image_purges_both_tiers(self, tmp_path):
        cache = TileResultCache(
            memory_bytes=1 << 20, disk_dir=str(tmp_path / "spill"),
        )
        try:
            await cache.put("img=7|x=0", _entry(b"seven"))
            await cache.put("img=8|x=0", _entry(b"eight"))
            cache.invalidate_image(7)
            assert await cache.get("img=7|x=0") is None
            assert (await cache.get("img=8|x=0")).body == b"eight"
        finally:
            cache.close()

    async def test_fill_discarded_when_invalidation_races(self):
        """A render that STARTED before an invalidation must not land
        after the purge (with ttl 0 it would serve stale forever)."""
        cache = TileResultCache(memory_bytes=1 << 20)
        gen = cache.generation()  # captured before the render
        cache.invalidate_image(1)  # the pixels row changes mid-flight
        await cache.put("img=1|k", _entry(b"stale"), generation=gen)
        assert await cache.get("img=1|k") is None  # discarded
        await cache.put(
            "img=1|k", _entry(b"fresh"), generation=cache.generation()
        )
        assert (await cache.get("img=1|k")).body == b"fresh"

    def test_bytes_gauge_is_one_family(self):
        """Multiple cache instances (bench, tests, app re-creation)
        must not duplicate the tile_cache_bytes metric family or pin
        closed caches' contents."""
        from omero_ms_pixel_buffer_tpu.utils.metrics import REGISTRY

        c1 = TileResultCache(memory_bytes=4096)
        c2 = TileResultCache(memory_bytes=4096)
        try:
            text = REGISTRY.exposition()
            assert text.count("# TYPE tile_cache_bytes gauge") == 1
        finally:
            c1.close()
            c2.close()

    async def test_ttl_expiry(self):
        cache = TileResultCache(memory_bytes=1 << 20, ttl_s=0.02)
        await cache.put("k", _entry(b"v"))
        assert (await cache.get("k")) is not None
        time.sleep(0.03)
        assert await cache.get("k") is None

    @pytest.mark.resilience
    async def test_hung_disk_reads_as_miss_within_io_timeout(
        self, tmp_path
    ):
        """A disk that HANGS (no error, NFS D-state) must not park
        the request: the loop-side wait is bounded by the per-call
        io-timeout, the hang feeds the breaker, and the lookup reads
        as a miss (pass-through)."""
        cache = TileResultCache(
            memory_bytes=1 << 20, disk_dir=str(tmp_path / "spill"),
        )
        try:
            set_io_timeout(0.05)
            cache._disk_get = lambda key: time.sleep(5)  # the hang
            t0 = time.monotonic()
            assert await cache.get("img=1|k") is None
            assert time.monotonic() - t0 < 1.0  # never the 5 s
            assert cache._disk_breaker.snapshot()[
                "consecutive_failures"
            ] >= 1
        finally:
            cache.close()

    @pytest.mark.resilience
    async def test_memory_fault_degrades_to_passthrough(self):
        cache = TileResultCache(memory_bytes=1 << 20)
        await cache.put("k", _entry(b"v"))
        INJECTOR.install(
            "cache.memory", faultinject.always(RuntimeError("ram gone"))
        )
        assert await cache.get("k") is None  # pass-through, no raise
        await cache.put("k2", _entry(b"w"))  # swallowed
        INJECTOR.clear()
        assert (await cache.get("k")).body == b"v"  # tier intact

    @pytest.mark.resilience
    async def test_disk_fault_opens_breaker_memory_survives(
        self, tmp_path
    ):
        cache = TileResultCache(
            memory_bytes=300, disk_dir=str(tmp_path / "spill"),
        )
        try:
            INJECTOR.install(
                "cache.disk", faultinject.always(OSError("disk dead"))
            )
            for i in range(12):  # spills fail -> breaker input
                await cache.put(f"img=1|{i}", _entry(b"x" * 100))
                entry = await cache.get(f"img=1|{i}")
                assert entry is not None  # memory tier still serves
            for _ in range(50):
                if cache._disk_breaker.state == "open":
                    break
                await asyncio.sleep(0.01)
            assert cache._disk_breaker.state == "open"
            # with the breaker open, disk ops are skipped entirely
            before = INJECTOR.calls("cache.disk")
            assert await cache.get("img=1|0") is None  # evicted, lost
            assert INJECTOR.calls("cache.disk") == before
        finally:
            cache.close()


# ---------------------------------------------------------------------------
# HTTP: ETag / 304 / hit semantics
# ---------------------------------------------------------------------------

class TestConditionalGet:
    async def test_miss_then_hit_identical_bytes(self, tmp_path):
        app_obj, client = await _make_app(tmp_path)
        try:
            url = "/tile/1/0/0/0?x=64&y=64&w=64&h=64&format=png"
            r1 = await client.get(url, headers=AUTH)
            assert r1.status == 200
            assert r1.headers["X-Cache"] == "miss"
            etag = r1.headers["ETag"]
            assert etag.startswith('"')
            assert "max-age" in r1.headers["Cache-Control"]
            body1 = await r1.read()

            r2 = await client.get(url, headers=AUTH)
            assert r2.status == 200
            assert r2.headers["X-Cache"] == "hit"
            assert r2.headers["ETag"] == etag
            body2 = await r2.read()
            assert body1 == body2  # byte-identical service
            decoded = np.array(Image.open(io.BytesIO(body2)))
            np.testing.assert_array_equal(
                decoded.astype(np.uint16), IMG[0, 0, 0, 64:128, 64:128]
            )
        finally:
            await client.close()

    async def test_if_none_match_304(self, tmp_path):
        app_obj, client = await _make_app(tmp_path)
        try:
            url = "/tile/1/0/0/0?w=64&h=64&format=png"
            r1 = await client.get(url, headers=AUTH)
            etag = r1.headers["ETag"]
            r2 = await client.get(
                url, headers={**AUTH, "If-None-Match": etag}
            )
            assert r2.status == 304
            assert await r2.read() == b""
            assert r2.headers["ETag"] == etag
            # stale validator still gets the full body
            r3 = await client.get(
                url, headers={**AUTH, "If-None-Match": '"stale"'}
            )
            assert r3.status == 200
            assert len(await r3.read()) > 0
        finally:
            await client.close()

    async def test_etag_precheck_short_circuits_auth(self, tmp_path):
        """With a matching strong ETag cached, revalidation answers 304
        BEFORE the session join; a request without the validator still
        takes the full (denied -> 403) path."""
        app_obj, client = await _make_app(
            tmp_path, validator=AllowListValidator(allowed={"nobody"}),
        )
        try:
            body = b"cached-tile-bytes"
            entry = CachedTile(body, filename="t.png")
            ctx = _ctx(w=64, h=64, session="omero-key-1")
            key = ctx.cache_key(app_obj.pipeline.encode_signature())
            await app_obj.result_cache.put(key, entry)
            url = "/tile/1/0/0/0?w=64&h=64&format=png"
            r1 = await client.get(
                url, headers={**AUTH, "If-None-Match": entry.etag}
            )
            assert r1.status == 304  # validator never consulted
            r2 = await client.get(url, headers=AUTH)
            assert r2.status == 403  # hit not served: not authorized
        finally:
            await client.close()

    async def test_invalidation_serves_fresh_etag(self, tmp_path):
        app_obj, client = await _make_app(tmp_path)
        try:
            url = "/tile/1/0/0/0?w=64&h=64&format=png"
            r1 = await client.get(url, headers=AUTH)
            etag = r1.headers["ETag"]
            app_obj._invalidate_image(1)  # the resolver-listener path
            r2 = await client.get(
                url, headers={**AUTH, "If-None-Match": etag}
            )
            # cache purged: full re-render; identical pixels -> the
            # strong ETag matches again and revalidation still wins
            assert r2.status in (200, 304)
            r3 = await client.get(url, headers=AUTH)
            assert r3.status == 200
            assert r3.headers["ETag"] == etag  # content unchanged
        finally:
            await client.close()

    async def test_cache_disabled_still_serves(self, tmp_path):
        app_obj, client = await _make_app(
            tmp_path, cache_config={"enabled": False}
        )
        try:
            assert app_obj.result_cache is None
            r = await client.get(
                "/tile/1/0/0/0?w=64&h=64&format=png", headers=AUTH
            )
            assert r.status == 200
            assert "ETag" not in r.headers
            assert "X-Cache" not in r.headers
        finally:
            await client.close()


class TestFlightThroughHttp:
    @pytest.mark.resilience
    async def test_leader_failure_fans_out(self, tmp_path):
        """Concurrent identical requests collapse into one pipeline
        execution; when it fails, EVERY waiter sees the failure."""
        app_obj, client = await _make_app(tmp_path)
        calls = []

        def boom(ctx):
            calls.append(1)
            time.sleep(0.05)  # hold the flight open for the joiners
            raise RuntimeError("pipeline down")

        app_obj.pipeline.handle = boom
        try:
            results = await asyncio.gather(*(
                client.get(
                    "/tile/1/0/0/0?w=64&h=64&format=png", headers=AUTH
                )
                for _ in range(6)
            ))
            assert [r.status for r in results] == [500] * 6
            assert len(calls) == 1  # ONE execution for six requests
        finally:
            await client.close()

    async def test_concurrent_misses_coalesce(self, tmp_path):
        app_obj, client = await _make_app(tmp_path)
        executions = []
        inner = app_obj.pipeline.handle

        def counting(ctx):
            executions.append(1)
            time.sleep(0.03)
            return inner(ctx)

        app_obj.pipeline.handle = counting
        try:
            results = await asyncio.gather(*(
                client.get(
                    "/tile/1/0/0/0?x=64&w=64&h=64&format=png",
                    headers=AUTH,
                )
                for _ in range(8)
            ))
            bodies = [await r.read() for r in results]
            assert all(r.status == 200 for r in results)
            assert len(set(bodies)) == 1
            assert len(executions) == 1
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# batcher: identical-lane dedup
# ---------------------------------------------------------------------------

class TestBatchDedup:
    async def test_duplicate_lanes_execute_once(self):
        from omero_ms_pixel_buffer_tpu.dispatch.batcher import (
            BatchingTileWorker,
        )

        seen_batches = []

        class FakePipeline:
            def handle(self, ctx):
                seen_batches.append([ctx])
                return b"one"

            def handle_batch(self, ctxs):
                seen_batches.append(list(ctxs))
                return [f"tile-{c.region.x}".encode() for c in ctxs]

        worker = BatchingTileWorker(
            FakePipeline(), AllowListValidator(),
            max_batch=8, coalesce_window_ms=30.0,
        )
        await worker.start()
        try:
            dup1 = _ctx(x=0)
            dup2 = _ctx(x=0)  # identical lane key
            other = _ctx(x=64)
            r = await asyncio.gather(
                worker.handle(dup1), worker.handle(dup2),
                worker.handle(other),
            )
            executed = [c for batch in seen_batches for c in batch]
            assert len(executed) == 2  # dup collapsed
            assert r[0][0] == r[1][0] == b"tile-0"
            assert r[2][0] == b"tile-64"
        finally:
            await worker.close()


# ---------------------------------------------------------------------------
# prefetch
# ---------------------------------------------------------------------------

class _FakeAdmission:
    def __init__(self, headroom=True):
        self.headroom = headroom

    def has_headroom(self, fraction=0.5):
        return self.headroom


class TestPrefetcher:
    async def test_motion_predicts_and_warms(self):
        fetched = []

        async def fetch(ctx, key):
            fetched.append((ctx.region.x, ctx.region.y, ctx.resolution))

        pre = ViewportPrefetcher(
            fetch, cache=None, admission=_FakeAdmission(), lookahead=2
        )
        pre.start()
        try:
            pre.observe(_ctx(x=0, y=64))
            pre.observe(_ctx(x=64, y=64))  # moving right
            for _ in range(100):
                if len(fetched) >= 4:
                    break
                await asyncio.sleep(0.01)
            # continuation x=128, x=192 plus perpendicular neighbors
            assert (128, 64, None) in fetched
            assert (192, 64, None) in fetched
            assert (128, 0, None) in fetched
            assert (128, 128, None) in fetched
        finally:
            await pre.close()

    async def test_zoom_prediction(self):
        fetched = []

        async def fetch(ctx, key):
            fetched.append((ctx.region.x, ctx.region.y, ctx.resolution))

        pre = ViewportPrefetcher(fetch, None, _FakeAdmission())
        pre.start()
        try:
            pre.observe(_ctx(x=0, y=0, resolution=2))
            pre.observe(_ctx(x=64, y=0, resolution=2))
            for _ in range(100):
                if any(res == 1 for *_xy, res in fetched):
                    break
                await asyncio.sleep(0.01)
            assert any(res == 1 for *_xy, res in fetched)
        finally:
            await pre.close()

    @pytest.mark.resilience
    async def test_sheds_under_admission_pressure(self):
        fetched = []

        async def fetch(ctx, key):
            fetched.append(ctx)

        admission = _FakeAdmission(headroom=False)
        pre = ViewportPrefetcher(fetch, None, admission)
        pre.start()
        try:
            pre.observe(_ctx(x=0))
            pre.observe(_ctx(x=64))
            # y=0 prunes one perpendicular neighbor (negative y):
            # 2 continuation + 1 neighbor predictions, all shed
            for _ in range(100):
                if pre.snapshot()["shed"] >= 3:
                    break
                await asyncio.sleep(0.01)
            assert pre.snapshot()["shed"] >= 3
            assert not fetched  # nothing issued while saturated
            admission.headroom = True  # load drains -> prefetch resumes
            pre.observe(_ctx(x=128))
            for _ in range(100):
                if fetched:
                    break
                await asyncio.sleep(0.01)
            assert fetched
        finally:
            await pre.close()

    async def test_close_survives_swallowed_cancel(self):
        """close() must terminate even when its cancel is eaten by the
        fetch path's bounded wait (wait_for's completion race,
        bpo-42130): the worker checks the closing latch instead of
        sailing back into queue.get() forever."""
        entered = asyncio.Event()

        async def fetch(ctx, key):
            entered.set()
            try:
                await asyncio.Event().wait()  # park until cancelled
            except asyncio.CancelledError:
                return  # the swallowed-cancel shape

        pre = ViewportPrefetcher(fetch, None, _FakeAdmission())
        pre.start()
        pre.observe(_ctx(x=0))
        pre.observe(_ctx(x=64))  # predictions put the worker in fetch
        await asyncio.wait_for(entered.wait(), 5)
        await asyncio.wait_for(pre.close(), 5)
        assert pre._worker is None

    async def test_http_pan_warms_neighbor(self, tmp_path):
        app_obj, client = await _make_app(tmp_path)
        try:
            for x in (0, 64):
                r = await client.get(
                    f"/tile/1/0/0/0?x={x}&y=64&w=64&h=64&format=png",
                    headers=AUTH,
                )
                assert r.status == 200
            neighbor = _ctx(x=128, y=64, session=None)
            key = neighbor.cache_key(app_obj.pipeline.encode_signature())
            cache = app_obj.result_cache
            for _ in range(200):
                if cache.contains(key):
                    break
                await asyncio.sleep(0.01)
            assert cache.contains(key)
            # and the warmed tile now serves as a hit
            r = await client.get(
                "/tile/1/0/0/0?x=128&y=64&w=64&h=64&format=png",
                headers=AUTH,
            )
            assert r.status == 200
            assert r.headers["X-Cache"] == "hit"
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# chaos: disk-tier outage through the full HTTP stack
# ---------------------------------------------------------------------------

class TestDiskChaosHttp:
    @pytest.mark.resilience
    async def test_disk_fault_serves_every_request(self, tmp_path):
        """The acceptance bar: with the disk tier faulted, every
        request still answers correctly via pass-through."""
        app_obj, client = await _make_app(
            tmp_path,
            cache_config={
                "memory-mb": 1,
                "disk-dir": str(tmp_path / "spill"),
            },
        )
        INJECTOR.install(
            "cache.disk", faultinject.always(OSError("disk tier dead"))
        )
        # shrink the RAM tier so evictions actually reach the (dead)
        # disk tier during the run
        app_obj.result_cache.memory.max_bytes = 4096
        app_obj.result_cache.memory.protected_max = 3276
        try:
            for x in (0, 64, 128, 192):
                for repeat in range(2):
                    r = await client.get(
                        f"/tile/1/0/0/0?x={x}&w=64&h=64&format=png",
                        headers=AUTH,
                    )
                    assert r.status == 200
                    body = await r.read()
                    decoded = np.array(Image.open(io.BytesIO(body)))
                    np.testing.assert_array_equal(
                        decoded.astype(np.uint16),
                        IMG[0, 0, 0, 0:64, x:x + 64],
                    )
            assert INJECTOR.calls("cache.disk") > 0  # tier WAS hit
            health = await (await client.get("/healthz")).json()
            assert health["cache"]["enabled"] is True
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# invalidation via the metadata resolver
# ---------------------------------------------------------------------------

ROW_V1 = ("10", "256", "256", "1", "1", "2", "uint16", "img", "2", "3",
          "-120", None, None, None, None)
ROW_V2 = ("10", "512", "512", "1", "1", "2", "uint16", "img", "2", "3",
          "-120", None, None, None, None)


class _FakePgClient:
    def __init__(self):
        self.rows = [ROW_V1]

    async def query(self, sql, params):
        if "FROM pixels" in sql:
            return list(self.rows)
        return []

    async def close(self):
        pass


class TestResolverInvalidation:
    async def test_changed_row_fires_listener(self):
        from omero_ms_pixel_buffer_tpu.db.metadata import (
            OmeroPostgresMetadataResolver,
        )

        resolver = OmeroPostgresMetadataResolver(
            "postgresql://u@localhost/db", cache_ttl_s=0.0
        )
        fake = _FakePgClient()
        resolver._client = fake
        fired = []
        resolver.add_invalidation_listener(fired.append)

        meta = await resolver.get_pixels_async(1)
        assert meta is not None and meta.size_x == 256
        assert fired == []  # unchanged refresh: no invalidation
        await resolver.get_pixels_async(1)
        assert fired == []
        fake.rows = [ROW_V2]  # the pixels row changed
        meta = await resolver.get_pixels_async(1)
        assert meta.size_x == 512
        assert fired == [1]
        fake.rows = []  # the image vanished
        assert await resolver.get_pixels_async(1) is None
        assert fired == [1, 1]

    async def test_manual_invalidate(self):
        from omero_ms_pixel_buffer_tpu.db.metadata import (
            OmeroPostgresMetadataResolver,
        )

        resolver = OmeroPostgresMetadataResolver(
            "postgresql://u@localhost/db"
        )
        resolver._client = _FakePgClient()
        fired = []
        resolver.add_invalidation_listener(fired.append)
        resolver.invalidate(5)
        assert fired == [5]


class TestPipelineInvalidation:
    def test_invalidate_image_drops_buffer(self, tmp_path):
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        write_ome_tiff(str(tmp_path / "i.ome.tiff"), IMG)
        registry = ImageRegistry()
        registry.add(1, str(tmp_path / "i.ome.tiff"))
        service = PixelsService(registry)
        pipe = TilePipeline(service, engine="host")
        assert pipe.handle(_ctx(w=32, h=32, session="k")) is not None
        buf = service.get_pixel_buffer(1)
        assert buf is not None
        pipe.invalidate_image(1)
        assert service.get_pixel_buffer(1) is not buf  # re-opened
        assert pipe.handle(_ctx(w=32, h=32, session="k")) is not None


# ---------------------------------------------------------------------------
# per-call network timeouts (satellite: KNOWN_GAPS closure)
# ---------------------------------------------------------------------------

class TestPerCallTimeouts:
    @pytest.mark.resilience
    async def test_postgres_exchange_bounded(self):
        from omero_ms_pixel_buffer_tpu.db.postgres import (
            PostgresClient,
            PostgresUnavailableError,
        )

        set_io_timeout(0.05)
        INJECTOR.install("db.postgres", faultinject.latency(5.0))
        client = PostgresClient(host="localhost", port=59999)
        t0 = time.monotonic()
        # surfaces as UNAVAILABLE (-> 503), never a raw TimeoutError
        # (which the pipeline's broad catch would turn into 404)
        with pytest.raises(PostgresUnavailableError):
            await client.query("SELECT 1")
        assert time.monotonic() - t0 < 1.0  # never the injected 5 s
        assert client.breaker.snapshot()["consecutive_failures"] >= 1

    @pytest.mark.resilience
    async def test_redis_lookup_bounded(self):
        from omero_ms_pixel_buffer_tpu.auth.stores import (
            RedisSessionStore,
        )

        set_io_timeout(0.05)
        INJECTOR.install("session_store", faultinject.latency(5.0))
        store = RedisSessionStore("redis://localhost:59998/0")
        t0 = time.monotonic()
        with pytest.raises(asyncio.TimeoutError):
            await store.get_omero_session_key("sid")
        assert time.monotonic() - t0 < 1.0
        assert store.breaker.snapshot()["consecutive_failures"] >= 1

    def test_ice_timeout_follows_configuration(self):
        from omero_ms_pixel_buffer_tpu.auth.ice import Glacier2Client

        set_io_timeout(0.25)
        client = Glacier2Client("localhost")
        assert client.timeout_s == 0.25
        set_io_timeout(0.0)  # disabled -> conservative default
        assert client.timeout_s == 10.0
        pinned = Glacier2Client("localhost", timeout_s=3.0)
        assert pinned.timeout_s == 3.0


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------

class TestCacheConfig:
    def _base(self, **cache):
        return Config.from_dict(
            {"session-store": {"type": "memory"}, "cache": cache}
        )

    def test_defaults(self):
        config = self._base()
        assert config.cache.enabled and config.cache.memory_mb == 256
        assert config.cache.disk_dir is None
        assert config.cache.prefetch.enabled
        assert config.resilience.io_timeout_ms == 5000.0

    def test_rejects_bad_fraction(self):
        with pytest.raises(ConfigError):
            self._base(**{"protected-fraction": 1.5})
        with pytest.raises(ConfigError):
            self._base(prefetch={"headroom": 2.0})

    def test_rejects_garbage_numbers(self):
        with pytest.raises(ConfigError):
            self._base(**{"memory-mb": "lots"})
        with pytest.raises(ConfigError):
            Config.from_dict({
                "session-store": {"type": "memory"},
                "resilience": {"io-timeout-ms": -1},
            })

    def test_full_block_parses(self):
        config = Config.from_dict({
            "session-store": {"type": "memory"},
            "cache": {
                "memory-mb": 64, "disk-dir": "/tmp/spill",
                "disk-mb": 128, "ttl-s": 30, "max-age-s": 120,
                "etag-precheck": False,
                "prefetch": {"enabled": False, "lookahead": 3},
            },
            "resilience": {"io-timeout-ms": 1500},
        })
        assert config.cache.disk_dir == "/tmp/spill"
        assert config.cache.ttl_s == 30.0
        assert not config.cache.etag_precheck
        assert not config.cache.prefetch.enabled
        assert config.resilience.io_timeout_ms == 1500.0


# ---------------------------------------------------------------------------
# w/h=0 full-plane normalization: both spellings share ONE cache entry
# ---------------------------------------------------------------------------

class TestFullPlaneNormalization:
    async def test_defaulted_then_explicit_share_one_entry(
        self, tmp_path, loop
    ):
        app_obj, client = await _make_app(tmp_path)
        try:
            r0 = await client.get("/tile/1/0/0/0", headers=AUTH)
            assert r0.status == 200
            assert r0.headers["X-Cache"] == "miss"
            body = await r0.read()
            assert len(app_obj.result_cache.memory) == 1
            # the explicit spelling of the same full plane HITS the
            # defaulted request's entry — no duplicate bytes
            r1 = await client.get(
                "/tile/1/0/0/0?w=256&h=256", headers=AUTH
            )
            assert r1.status == 200
            assert r1.headers["X-Cache"] == "hit"
            assert await r1.read() == body
            assert len(app_obj.result_cache.memory) == 1
        finally:
            await client.close()

    async def test_explicit_then_defaulted_share_one_entry(
        self, tmp_path, loop
    ):
        app_obj, client = await _make_app(tmp_path)
        try:
            r0 = await client.get(
                "/tile/1/1/0/0?w=256&h=256&format=png", headers=AUTH
            )
            assert r0.status == 200
            body0 = await r0.read()
            r1 = await client.get(
                "/tile/1/1/0/0?format=png", headers=AUTH
            )
            assert r1.status == 200
            assert r1.headers["X-Cache"] == "hit"
            assert len(app_obj.result_cache.memory) == 1
            assert await r1.read() == body0
        finally:
            await client.close()

    async def test_pyramid_level_normalizes_to_level_extent(
        self, tmp_path, loop
    ):
        """w/h=0 at resolution=1 must rewrite to the LEVEL's extent
        (128x128 here), not the full-resolution plane's."""
        app_obj, client = await _make_app(tmp_path)
        try:
            r0 = await client.get(
                "/tile/1/0/0/0?resolution=1", headers=AUTH
            )
            assert r0.status == 200
            body0 = await r0.read()
            r1 = await client.get(
                "/tile/1/0/0/0?resolution=1&w=128&h=128", headers=AUTH
            )
            assert r1.status == 200
            assert r1.headers["X-Cache"] == "hit"
            assert await r1.read() == body0
        finally:
            await client.close()

    async def test_unknown_image_leaves_region_untouched(
        self, tmp_path, loop
    ):
        app_obj, client = await _make_app(tmp_path)
        try:
            r = await client.get("/tile/99/0/0/0", headers=AUTH)
            assert r.status == 404  # normalization failure never 500s
        finally:
            await client.close()

    async def test_offset_defaulted_spelling_still_404s(
        self, tmp_path, loop
    ):
        """Regression: w=0 defaults to the FULL sizeX regardless of x
        (the resolve_region contract), so x>0&w=0 is out of bounds —
        normalization must reproduce that 404, not invent a clamped
        remainder tile that only exists when the cache is on."""
        app_obj, client = await _make_app(tmp_path)
        try:
            r = await client.get(
                "/tile/1/0/0/0?x=100&w=0&h=64", headers=AUTH
            )
            assert r.status == 404
            assert len(app_obj.result_cache.memory) == 0
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# prefetch bounds pruning: off-image predictions die in arithmetic,
# not in a pipeline resolve
# ---------------------------------------------------------------------------

class TestPrefetchBoundsPruning:
    async def test_off_image_predictions_never_reach_the_fetcher(self):
        """The fetch hook IS the pipeline-resolve path; with a known
        extent, off-image predictions must never invoke it."""
        fetched = []

        async def fetch(ctx, key):
            fetched.append((ctx.region.x, ctx.region.y))

        extent_calls = []

        def extent_fn(image_id, resolution):
            extent_calls.append((image_id, resolution))
            return (256, 128)

        pre = ViewportPrefetcher(
            fetch, cache=None, admission=_FakeAdmission(),
            lookahead=2, extent_fn=extent_fn,
        )
        pre.start()
        try:
            # pan right along the bottom edge: x=64 -> x=128 (w=64)
            pre.observe(_ctx(x=64, y=64, w=64, h=64))
            pre.observe(_ctx(x=128, y=64, w=64, h=64))
            await asyncio.sleep(0.05)
            # continuation x=192 fits; x=256 is off-image (256+64 >
            # 256); perpendicular y=128 is off-image (128+64 > 128)
            assert (192, 64) in fetched
            assert all(x + 64 <= 256 and y + 64 <= 128
                       for x, y in fetched), fetched
            assert pre.snapshot()["pruned_off_image"] >= 2
            # extent lookups are memoized per (image, level): the
            # second access answered from the prefetcher's own cache
            assert len(extent_calls) == 1
        finally:
            await pre.close()

    async def test_unknown_extent_keeps_pipeline_backstop(self):
        fetched = []

        async def fetch(ctx, key):
            fetched.append((ctx.region.x, ctx.region.y))

        pre = ViewportPrefetcher(
            fetch, cache=None, admission=_FakeAdmission(),
            lookahead=1, extent_fn=lambda image_id, res: None,
        )
        pre.start()
        try:
            pre.observe(_ctx(x=0, y=0, w=64, h=64))
            pre.observe(_ctx(x=64, y=0, w=64, h=64))
            await asyncio.sleep(0.05)
            assert fetched  # predictions still flow without an extent
            assert pre.snapshot()["pruned_off_image"] == 0
        finally:
            await pre.close()

    async def test_peek_extent_answers_only_from_open_buffers(
        self, tmp_path
    ):
        """The extent hook never opens or resolves: before the first
        real tile it answers None; after (buffer cached) it answers
        the level extent without touching the metadata plane."""
        write_ome_tiff(
            str(tmp_path / "img.ome.tiff"), IMG, tile_size=(64, 64),
            pyramid_levels=2,
        )
        registry = ImageRegistry()
        registry.add(1, str(tmp_path / "img.ome.tiff"))

        class CountingRegistry:
            def __init__(self, inner):
                self._inner = inner
                self.resolves = 0

            def get_pixels(self, image_id):
                self.resolves += 1
                return self._inner.get_pixels(image_id)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        counting = CountingRegistry(registry)
        svc = PixelsService(counting)
        try:
            assert svc.peek_extent(1) is None  # nothing open yet
            svc.get_pixel_buffer(1)  # the stream's first real tile
            before = counting.resolves
            assert svc.peek_extent(1) == (256, 256)
            assert svc.peek_extent(1, 1) == (128, 128)
            assert svc.peek_extent(1, 9) is None  # bad level
            assert svc.peek_extent(42) is None  # unknown image
            assert counting.resolves == before  # ZERO resolver calls
        finally:
            svc.close()

    async def test_app_wires_extent_pruning_end_to_end(
        self, tmp_path, loop
    ):
        """Through the real app: pan toward the image edge; the
        prefetcher must record pruned predictions (bounds math), not
        pipeline-resolved 404s."""
        app_obj, client = await _make_app(tmp_path)
        try:
            # 256-wide image: x=128 then x=192 (w=64) — continuation
            # x=256 is off-image
            for x in (128, 192):
                r = await client.get(
                    f"/tile/1/0/0/0?x={x}&y=0&w=64&h=64&format=png",
                    headers=AUTH,
                )
                assert r.status == 200
            for _ in range(100):
                snap = app_obj.prefetcher.snapshot()
                if snap["pruned_off_image"] >= 1:
                    break
                await asyncio.sleep(0.01)
            assert snap["pruned_off_image"] >= 1, snap
        finally:
            await client.close()
