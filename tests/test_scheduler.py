"""SLO-aware scheduling suite (resilience/scheduler, PR r13).

Covers the scheduler unit contract (EDF within class, weighted
round-robin between, lowest-class-latest-deadline shedding, in-queue
expiry, the degradation signal), sweep detection + classification,
the deadline-ordered batcher queue, and the HTTP integration: shed
ordering under injected overload, Retry-After only when the queue is
genuinely full, hybrid-resolution degradation engaging under pressure
and disengaging cleanly after, degraded-vs-full cache/ETag isolation,
the deferred trailing device group, concurrent session lookups, and
the opt-in /healthz dependency probes.
"""

import asyncio
import concurrent.futures
import io
import threading
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_pixel_buffer_tpu.auth.stores import (
    MemorySessionStore,
    OmeroWebSessionStore,
)
from omero_ms_pixel_buffer_tpu.errors import (
    GatewayTimeoutError,
    ServiceUnavailableError,
)
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.io.zarr import write_ngff
from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
    DeferredTile,
    TilePipeline,
)
from omero_ms_pixel_buffer_tpu.resilience import AdmissionController
from omero_ms_pixel_buffer_tpu.resilience.deadline import Deadline
from omero_ms_pixel_buffer_tpu.resilience.scheduler import (
    PRIORITY_BULK,
    PRIORITY_INTERACTIVE,
    PRIORITY_PREFETCH,
    DeadlineQueue,
    SloScheduler,
    SweepDetector,
    classify,
)
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

rng = np.random.default_rng(13)
IMG = rng.integers(0, 60000, (1, 1, 1, 64, 64), dtype=np.uint16)

AUTH = {"Cookie": "sessionid=ck"}


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _deadline(clock, budget_s: float) -> Deadline:
    return Deadline.after(budget_s, clock=clock)


# ---------------------------------------------------------------------------
# sweep detection + classification
# ---------------------------------------------------------------------------


class TestSweepDetector:
    def _walk(self, det, session, n, stride=64, y=0):
        for i in range(n):
            det.observe(session, 1, 0, 0, 0, 0, i * stride, y, 64, 64)

    def test_constant_stride_run_detects(self):
        det = SweepDetector(threshold=4)
        self._walk(det, "robot", 5)
        assert det.is_sweep("robot")
        assert det.snapshot()["detected_total"] == 1

    def test_short_runs_and_direction_changes_do_not(self):
        det = SweepDetector(threshold=4)
        # a human pan: 3 right, wobble down, 3 right
        self._walk(det, "human", 3)
        det.observe("human", 1, 0, 0, 0, 0, 128, 64, 64, 64)
        self._walk(det, "human", 3, y=64)
        assert not det.is_sweep("human")

    def test_refresh_is_not_a_step(self):
        det = SweepDetector(threshold=3)
        for _ in range(10):  # same tile re-requested (viewer refresh)
            det.observe("s", 1, 0, 0, 0, 0, 0, 0, 64, 64)
        assert not det.is_sweep("s")

    def test_demotion_expires_and_refreshes(self):
        clock = FakeClock()
        det = SweepDetector(threshold=3, ttl_s=10.0, clock=clock)
        self._walk(det, "robot", 4)
        assert det.is_sweep("robot")
        clock.advance(11.0)
        assert not det.is_sweep("robot")
        # a resumed sweep re-demotes (run state persists per stream)
        self._walk(det, "robot2", 4)
        clock.advance(9.0)
        det.observe("robot2", 1, 0, 0, 0, 0, 4 * 64, 0, 64, 64)
        clock.advance(5.0)  # 14s after first detection, 5s after refresh
        assert det.is_sweep("robot2")

    def test_full_plane_requests_ignored(self):
        det = SweepDetector(threshold=2)
        for i in range(5):
            det.observe("s", 1, 0, 0, 0, 0, i * 64, 0, 0, 0)
        assert not det.is_sweep("s")


class TestClassify:
    def test_default_is_interactive(self):
        assert classify({}, "s") == PRIORITY_INTERACTIVE

    def test_override_header_wins(self):
        h = {"x-ompb-priority": "bulk"}
        assert classify(h, "s") == PRIORITY_BULK
        h = {"x-ompb-priority": "prefetch"}
        assert classify(h, "s") == PRIORITY_PREFETCH
        # an override even outranks sweep detection
        det = SweepDetector(threshold=2)
        for i in range(4):
            det.observe("s", 1, 0, 0, 0, 0, i * 64, 0, 64, 64)
        assert classify(
            {"x-ompb-priority": "interactive"}, "s", det
        ) == PRIORITY_INTERACTIVE

    def test_unknown_override_value_ignored(self):
        assert classify(
            {"x-ompb-priority": "vip"}, "s"
        ) == PRIORITY_INTERACTIVE

    def test_purpose_headers_mark_prefetch(self):
        assert classify(
            {"Sec-Purpose": "prefetch;anonymous-client-ip"}, "s"
        ) == PRIORITY_PREFETCH
        assert classify({"Purpose": "prefetch"}, "s") == PRIORITY_PREFETCH
        assert classify({"X-OMPB-Prefetch": "1"}, "s") == PRIORITY_PREFETCH

    def test_sweep_session_demotes(self):
        det = SweepDetector(threshold=2)
        for i in range(4):
            det.observe("robot", 1, 0, 0, 0, 0, i * 64, 0, 64, 64)
        assert classify({}, "robot", det) == PRIORITY_BULK
        assert classify({}, "other", det) == PRIORITY_INTERACTIVE


# ---------------------------------------------------------------------------
# the scheduler unit contract
# ---------------------------------------------------------------------------


def _sched(capacity=1, queue_size=4, weights=(8, 2, 1), degrade=True,
           factor=1.5):
    admission = AdmissionController(
        max_inflight=capacity, retry_after_s=2.0
    )
    return SloScheduler(
        admission, queue_size=queue_size, class_weights=weights,
        degrade=degrade, degrade_factor=factor,
    )


class TestSloSchedulerUnit:
    async def test_immediate_grant_under_capacity(self, loop):
        s = _sched(capacity=2)
        p1 = await s.acquire(PRIORITY_INTERACTIVE, None)
        p2 = await s.acquire(PRIORITY_BULK, None)
        assert not p1.degraded and not p2.degraded
        assert s.admission.inflight == 2
        s.release(p1)
        s.release(p2)
        assert s.admission.inflight == 0

    async def test_edf_within_class(self, loop):
        clock = FakeClock()
        s = _sched(capacity=1, queue_size=8)
        p0 = await s.acquire(PRIORITY_INTERACTIVE, None)
        order = []

        async def waiter(tag, budget):
            p = await s.acquire(
                PRIORITY_INTERACTIVE, _deadline(clock, budget)
            )
            order.append(tag)
            s.release(p)

        # enqueue latest-deadline first: EDF must invert the order
        tasks = [
            asyncio.ensure_future(waiter("late", 30.0)),
            asyncio.ensure_future(waiter("mid", 20.0)),
            asyncio.ensure_future(waiter("early", 10.0)),
        ]
        await asyncio.sleep(0.01)
        s.release(p0)  # grants cascade as each waiter releases
        await asyncio.gather(*tasks)
        assert order == ["early", "mid", "late"]

    async def test_wrr_between_classes(self, loop):
        s = _sched(capacity=1, queue_size=16, weights=(2, 1, 1))
        p0 = await s.acquire(PRIORITY_INTERACTIVE, None)
        order = []

        async def waiter(tag, prio):
            p = await s.acquire(prio, None)
            order.append(tag)
            s.release(p)

        tasks = [
            asyncio.ensure_future(waiter(f"i{k}", PRIORITY_INTERACTIVE))
            for k in range(4)
        ]
        await asyncio.sleep(0.01)
        tasks += [
            asyncio.ensure_future(waiter("b0", PRIORITY_BULK)),
            asyncio.ensure_future(waiter("p0", PRIORITY_PREFETCH)),
        ]
        await asyncio.sleep(0.01)
        s.release(p0)
        await asyncio.gather(*tasks)
        # interactive dominates 2:1:1 but the lower classes are NOT
        # starved behind the interactive backlog
        assert order.index("p0") < len(order) - 1
        assert order[:2] == ["i0", "i1"]  # weight-2 head
        assert "b0" in order

    async def test_shed_order_lowest_class_latest_deadline(self, loop):
        clock = FakeClock()
        s = _sched(capacity=1, queue_size=2)
        p0 = await s.acquire(PRIORITY_INTERACTIVE, None)
        results = {}

        async def waiter(tag, prio, budget):
            try:
                p = await s.acquire(prio, _deadline(clock, budget))
                results[tag] = "granted"
                s.release(p)
            except ServiceUnavailableError:
                results[tag] = "shed"
            except GatewayTimeoutError:
                results[tag] = "expired"

        t_bulk = asyncio.ensure_future(
            waiter("bulk", PRIORITY_BULK, 10.0)
        )
        await asyncio.sleep(0.01)
        t_pre = asyncio.ensure_future(
            waiter("prefetch", PRIORITY_PREFETCH, 10.0)
        )
        await asyncio.sleep(0.01)  # queue full: [bulk, prefetch]
        # an incoming bulk with a LATER deadline is the worst work in
        # sight: it sheds, the queue is untouched
        with pytest.raises(ServiceUnavailableError) as ei:
            await s.acquire(PRIORITY_BULK, _deadline(clock, 20.0))
        assert ei.value.retry_after_s == 2.0
        # an incoming interactive evicts the queued BULK entry
        t_int = asyncio.ensure_future(
            waiter("interactive", PRIORITY_INTERACTIVE, 10.0)
        )
        await asyncio.sleep(0.01)
        assert results.get("bulk") == "shed"
        # another interactive evicts the queued PREFETCH entry
        t_int2 = asyncio.ensure_future(
            waiter("interactive2", PRIORITY_INTERACTIVE, 12.0)
        )
        await asyncio.sleep(0.01)
        assert results.get("prefetch") == "shed"
        s.release(p0)
        await asyncio.gather(t_bulk, t_pre, t_int, t_int2)
        assert results["interactive"] == "granted"
        assert results["interactive2"] == "granted"
        snap = s.snapshot()
        assert snap["shed"] == {
            "interactive": 0, "prefetch": 1, "bulk": 2,
        }

    async def test_queue_size_zero_is_binary_gate(self, loop):
        s = _sched(capacity=1, queue_size=0)
        p0 = await s.acquire(PRIORITY_INTERACTIVE, None)
        with pytest.raises(ServiceUnavailableError):
            await s.acquire(PRIORITY_INTERACTIVE, None)
        s.release(p0)
        p1 = await s.acquire(PRIORITY_INTERACTIVE, None)
        s.release(p1)

    async def test_expired_in_queue_is_504_and_slot_moves_on(self, loop):
        clock = FakeClock()
        s = _sched(capacity=1, queue_size=4)
        p0 = await s.acquire(PRIORITY_INTERACTIVE, None)
        doomed = _deadline(clock, 5.0)
        t_doomed = asyncio.ensure_future(
            s.acquire(PRIORITY_INTERACTIVE, doomed)
        )
        await asyncio.sleep(0.01)
        t_live = asyncio.ensure_future(
            s.acquire(PRIORITY_INTERACTIVE, _deadline(clock, 60.0))
        )
        await asyncio.sleep(0.01)
        clock.advance(6.0)  # doomed expires while queued
        s.release(p0)
        with pytest.raises(GatewayTimeoutError):
            await t_doomed
        live = await t_live  # the freed slot moved on to live work
        assert live.priority == PRIORITY_INTERACTIVE
        s.release(live)
        assert s.snapshot()["expired_in_queue"]["interactive"] == 1

    async def test_degrade_signal_engages_and_disengages(self, loop):
        s = _sched(capacity=1, queue_size=4, factor=1.5)
        # train the service-time EWMA: a 100 ms full-res execution
        p = await s.acquire(PRIORITY_INTERACTIVE, None)
        p._t_start = time.monotonic() - 0.1
        s.release(p)
        assert s._service_ewma == pytest.approx(0.1, rel=0.05)
        # uncontended grant with plenty of budget: NOT degraded
        p = await s.acquire(
            PRIORITY_INTERACTIVE, Deadline.after(10.0)
        )
        assert not p.degraded
        # contended grant with remaining < 1.5 x ewma: degraded
        t = asyncio.ensure_future(
            s.acquire(PRIORITY_INTERACTIVE, Deadline.after(0.12))
        )
        await asyncio.sleep(0.01)
        p._t_start = time.monotonic()
        s.release(p)
        granted = await t
        assert granted.degraded
        s.release(granted)
        # pressure gone: an identical tight budget no longer degrades
        p2 = await s.acquire(
            PRIORITY_INTERACTIVE, Deadline.after(0.12)
        )
        assert not p2.degraded
        s.release(p2)
        assert s.snapshot()["degraded"]["interactive"] == 1

    async def test_degraded_durations_do_not_train_ewma(self, loop):
        s = _sched(capacity=1)
        p = await s.acquire(PRIORITY_INTERACTIVE, None)
        p._t_start = time.monotonic() - 0.2
        s.release(p)
        ewma = s._service_ewma
        p = await s.acquire(PRIORITY_INTERACTIVE, None)
        p.degraded = True
        p._t_start = time.monotonic() - 0.001
        s.release(p)
        assert s._service_ewma == ewma  # unchanged

    async def test_failed_requests_do_not_train_ewma(self, loop):
        """release(train=False) — the HTTP layer's path for requests
        that errored: a fast-failing burst (404 loop, open breaker)
        must not collapse the estimate and disarm degradation."""
        s = _sched(capacity=1)
        p = await s.acquire(PRIORITY_INTERACTIVE, None)
        p._t_start = time.monotonic() - 0.2
        s.release(p)
        ewma = s._service_ewma
        for _ in range(20):  # 20 near-instant failures
            p = await s.acquire(PRIORITY_INTERACTIVE, None)
            s.release(p, train=False)
        assert s._service_ewma == ewma  # unchanged

    async def test_non_degradable_grants_never_flagged(self, loop):
        """acquire(degradable=False) — raw/TIFF measurement surfaces:
        the permit is never degraded (slo_degraded_total counts only
        requests that CAN degrade) and its full-res serve still
        trains the EWMA."""
        s = _sched(capacity=1, queue_size=4, factor=1.5)
        p = await s.acquire(PRIORITY_INTERACTIVE, None)
        p._t_start = time.monotonic() - 0.1
        s.release(p)
        p = await s.acquire(PRIORITY_INTERACTIVE, None)
        t = asyncio.ensure_future(s.acquire(
            PRIORITY_INTERACTIVE, Deadline.after(0.12),
            degradable=False,
        ))
        await asyncio.sleep(0.01)
        p._t_start = time.monotonic() - 0.1
        s.release(p)
        granted = await t
        assert not granted.degraded  # would have been flagged
        ewma = s._service_ewma
        granted._t_start = time.monotonic() - 0.1
        s.release(granted)
        assert s._service_ewma != ewma  # full-res serve still trains
        assert s.snapshot()["degraded"]["interactive"] == 0

    async def test_cancelled_waiter_leaves_queue_consistent(self, loop):
        s = _sched(capacity=1, queue_size=4)
        p0 = await s.acquire(PRIORITY_INTERACTIVE, None)
        t = asyncio.ensure_future(
            s.acquire(PRIORITY_INTERACTIVE, None)
        )
        await asyncio.sleep(0.01)
        t.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t
        assert s._waiting_total == 0
        s.release(p0)
        assert s.admission.inflight == 0
        # the scheduler still grants cleanly afterwards
        p = await s.acquire(PRIORITY_INTERACTIVE, None)
        s.release(p)

    async def test_door_preview_matches_victim_class(self, loop):
        """``would_overflow_shed`` reads the per-class live-waiter
        counters (O(1) on the overload hot path), not a heap scan —
        but the answer must match acquire's victim choice: a fresh
        arrival sheds unless a strictly lower class is waiting."""
        s = _sched(capacity=1, queue_size=2)
        p0 = await s.acquire(PRIORITY_BULK, None)
        waiters = [
            asyncio.ensure_future(
                s.acquire(PRIORITY_BULK, Deadline.after(5.0))
            )
            for _ in range(2)
        ]
        await asyncio.sleep(0.01)
        assert s._waiting_total == 2  # queue genuinely full
        # bulk waiters evictable by anything strictly more important
        assert not s.would_overflow_shed(PRIORITY_INTERACTIVE)
        assert not s.would_overflow_shed(PRIORITY_PREFETCH)
        # a fresh bulk arrival holds the latest deadline: it sheds
        assert s.would_overflow_shed(PRIORITY_BULK)
        # a cancelled waiter leaves the preview consistent
        waiters[1].cancel()
        with pytest.raises(asyncio.CancelledError):
            await waiters[1]
        assert not s.would_overflow_shed(PRIORITY_BULK)  # room again
        s.release(p0)
        s.release(await waiters[0])


class TestDeadlineQueue:
    def _item(self, priority=0, budget=None, clock=None):
        ctx = TileCtx(1, 0, 0, 0, RegionDef(0, 0, 1, 1))
        ctx.priority = priority
        if budget is not None:
            ctx.deadline = Deadline.after(
                budget, clock=clock or time.monotonic
            )
        return (ctx, object())

    async def test_pops_deadline_then_class_order(self, loop):
        """Deadline is the primary key (everything queued already
        holds a granted slot — class-first would starve admitted
        lower-class lanes under a steady interactive stream); class
        breaks same-deadline ties interactive-first."""
        clock = FakeClock()
        q = DeadlineQueue()
        a = self._item(PRIORITY_BULK, 1.0, clock)
        b = self._item(PRIORITY_INTERACTIVE, 9.0, clock)
        c = self._item(PRIORITY_INTERACTIVE, 2.0, clock)
        d = self._item(PRIORITY_PREFETCH, 1.0, clock)
        for it in (a, b, c, d):
            q.put_nowait(it)
        assert [q.get_nowait() for _ in range(4)] == [d, a, c, b]

    async def test_admitted_lane_never_starved_by_later_arrivals(
        self, loop
    ):
        """The starvation regression: a queued prefetch lane with the
        earliest deadline pops before interactive lanes that arrived
        after it — its admission slot is never pinned behind an
        endless higher-class stream."""
        clock = FakeClock()
        q = DeadlineQueue()
        prefetch = self._item(PRIORITY_PREFETCH, 5.0, clock)
        q.put_nowait(prefetch)
        clock.advance(1.0)  # later arrivals: later deadlines
        later = [
            self._item(PRIORITY_INTERACTIVE, 5.0, clock)
            for _ in range(4)
        ]
        for it in later:
            q.put_nowait(it)
        assert q.get_nowait() is prefetch

    async def test_fifo_within_equal_keys_and_maxsize(self, loop):
        q = DeadlineQueue(maxsize=2)
        a, b = self._item(), self._item()
        q.put_nowait(a)
        q.put_nowait(b)
        with pytest.raises(asyncio.QueueFull):
            q.put_nowait(self._item())
        assert q.get_nowait() is a and q.get_nowait() is b
        with pytest.raises(asyncio.QueueEmpty):
            q.get_nowait()

    async def test_async_get_wakes_on_put(self, loop):
        q = DeadlineQueue()
        task = asyncio.ensure_future(q.get())
        await asyncio.sleep(0.01)
        item = self._item()
        q.put_nowait(item)
        assert await task is item
        assert q.empty() and q.qsize() == 0


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


class TestSloConfig:
    def _cfg(self, slo):
        return Config.from_dict(
            {"session-store": {"type": "memory"}, "slo": slo}
        )

    def test_defaults(self):
        cfg = Config.from_dict({"session-store": {"type": "memory"}})
        assert cfg.slo.enabled and cfg.slo.queue_size == 512
        assert cfg.slo.class_weights == (8, 2, 1)
        assert cfg.slo.degrade and cfg.slo.sweep_window == 16

    def test_unknown_key_fails(self):
        with pytest.raises(ConfigError):
            self._cfg({"que-size": 3})

    def test_weights_validated(self):
        with pytest.raises(ConfigError):
            self._cfg({"class-weights": [1, 2]})
        with pytest.raises(ConfigError):
            self._cfg({"class-weights": [1, 0, 1]})
        with pytest.raises(ConfigError):
            self._cfg({"class-weights": "high"})

    def test_values_validated(self):
        with pytest.raises(ConfigError):
            self._cfg({"queue-size": -1})
        with pytest.raises(ConfigError):
            self._cfg({"degrade-factor": 0})
        with pytest.raises(ConfigError):
            self._cfg({"sweep-window": 1})
        cfg = self._cfg({"queue-size": 0, "priority-header": None})
        assert cfg.slo.queue_size == 0
        assert cfg.slo.priority_header == ""


class TestCtxKeys:
    def test_degraded_joins_every_key(self):
        a = TileCtx(1, 0, 0, 0, RegionDef(0, 0, 64, 64), format="png")
        b = TileCtx(
            1, 0, 0, 0, RegionDef(0, 0, 64, 64), format="png",
            degraded=1,
        )
        assert a.cache_key("q") != b.cache_key("q")
        assert a.dedupe_key("q") != b.dedupe_key("q")
        assert a.lane_key() != b.lane_key()
        assert "deg=1" in b.cache_key("q")
        assert "deg" not in a.cache_key("q")

    def test_priority_and_degraded_round_trip_json(self):
        ctx = TileCtx(
            1, 0, 0, 0, RegionDef(0, 0, 64, 64), format="png",
            priority=PRIORITY_BULK, degraded=1,
        )
        back = TileCtx.from_json(ctx.to_json())
        assert back.priority == PRIORITY_BULK and back.degraded == 1
        assert back.cache_key("q") == ctx.cache_key("q")

    def test_priority_never_changes_keys(self):
        a = TileCtx(1, 0, 0, 0, RegionDef(0, 0, 64, 64), format="png")
        b = TileCtx(
            1, 0, 0, 0, RegionDef(0, 0, 64, 64), format="png",
            priority=PRIORITY_BULK,
        )
        assert a.cache_key("q") == b.cache_key("q")
        assert a.lane_key() == b.lane_key()


# ---------------------------------------------------------------------------
# HTTP integration
# ---------------------------------------------------------------------------


async def _make_app(
    tmp_path, *, resilience=None, slo=None, config_extra=None,
    slow_s=0.0, workers=4, cache=False, levels=2, size=64,
    session_store=None,
):
    """A served 2-level NGFF image behind the full app with an
    optionally slowed pipeline — the chaos-suite shape, tuned for
    scheduler scenarios."""
    path = str(tmp_path / "img.zarr")
    write_ngff(
        path, IMG[:, :, :, :size, :size], chunks=(32, 32),
        levels=levels,
    )
    registry = ImageRegistry()
    registry.add(1, path, type="zarr")
    raw = {
        "session-store": {"type": "memory"},
        "worker_pool_size": workers,
        "backend": {"batching": {"max-batch": 1,
                                 "coalesce-window-ms": 0.0}},
        "cache": {"enabled": bool(cache)},
    }
    if resilience:
        raw["resilience"] = resilience
    if slo:
        raw["slo"] = slo
    if config_extra:
        raw.update(config_extra)
    config = Config.from_dict(raw)
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=session_store
        or MemorySessionStore({"ck": "key"}),
    )
    if slow_s:
        inner = app_obj.pipeline.handle

        def slowed(ctx):
            time.sleep(slow_s)
            return inner(ctx)

        app_obj.pipeline.handle = slowed
    client = TestClient(
        TestServer(app_obj.make_app()), loop=asyncio.get_running_loop()
    )
    await client.start_server()
    return app_obj, client


def _png_pixels(body: bytes) -> np.ndarray:
    from PIL import Image

    return np.array(Image.open(io.BytesIO(body)))


def _upscaled_reference(x, y, w, h):
    """The expected degraded pixels: level-1 (stride-2) plane of IMG,
    nearest-neighbor mapped back to the requested region — an
    independent spelling of the pipeline's _degrade_plan contract."""
    lvl1 = IMG[0, 0, 0, ::2, ::2]
    ys = np.minimum((y + np.arange(h)) * lvl1.shape[0] // 64,
                    lvl1.shape[0] - 1)
    xs = np.minimum((x + np.arange(w)) * lvl1.shape[1] // 64,
                    lvl1.shape[1] - 1)
    return lvl1[np.ix_(ys, xs)]


@pytest.mark.resilience
class TestShedOrdering:
    """Satellite: under injected overload, prefetch sheds before
    interactive, bulk before prefetch, and Retry-After only appears
    once the queue is genuinely full."""

    async def test_shed_order_and_retry_after(self, tmp_path, loop):
        gate = threading.Event()
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1,
                                      "retry-after-s": 3}},
            slo={"queue-size": 2, "degrade": False},
            workers=4,
        )
        inner = app_obj.pipeline.handle

        def gated(ctx):
            gate.wait(10.0)
            return inner(ctx)

        app_obj.pipeline.handle = gated
        url = "/tile/1/0/0/0?w=32&h=32&format=png"

        async def req(headers=None):
            h = dict(AUTH)
            if headers:
                h.update(headers)
            return await client.get(url, headers=h)

        try:
            occupant = asyncio.ensure_future(req())
            await asyncio.sleep(0.1)  # slot taken, queue empty
            queued_bulk = asyncio.ensure_future(
                req({"X-OMPB-Priority": "bulk"})
            )
            await asyncio.sleep(0.05)
            queued_pre = asyncio.ensure_future(
                req({"Sec-Purpose": "prefetch"})
            )
            await asyncio.sleep(0.05)  # queue now FULL: [bulk, prefetch]

            # an incoming bulk (later deadline) is the worst work in
            # sight: it sheds with Retry-After; the queue is untouched
            r = await req({"X-OMPB-Priority": "bulk"})
            assert r.status == 503
            assert r.headers["Retry-After"] == "3"

            # incoming interactive evicts the queued BULK first ...
            int1 = asyncio.ensure_future(req())
            await asyncio.sleep(0.05)
            r_bulk = await queued_bulk
            assert r_bulk.status == 503
            assert "Retry-After" in r_bulk.headers

            # ... and the next interactive evicts the queued PREFETCH
            int2 = asyncio.ensure_future(req())
            await asyncio.sleep(0.05)
            r_pre = await queued_pre
            assert r_pre.status == 503

            gate.set()
            r0, r1, r2 = await asyncio.gather(occupant, int1, int2)
            # ZERO interactive 503s while lower classes had sheddable
            # work — the acceptance property
            assert (r0.status, r1.status, r2.status) == (200,) * 3
            snap = app_obj.scheduler.snapshot()
            assert snap["shed"]["interactive"] == 0
            assert snap["shed"]["bulk"] == 2
            assert snap["shed"]["prefetch"] == 1
        finally:
            gate.set()
            await client.close()

    async def test_no_retry_after_while_queue_has_room(
        self, tmp_path, loop
    ):
        """Queued-not-shed: with wait room available, overload
        produces zero 503s — requests reorder and ride it out."""
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1}},
            slo={"queue-size": 8, "degrade": False},
            slow_s=0.05, workers=2,
        )
        try:
            rs = await asyncio.gather(*(
                client.get("/tile/1/0/0/0?w=32&h=32&format=png",
                           headers=AUTH)
                for _ in range(6)
            ))
            assert all(r.status == 200 for r in rs)
            assert app_obj.admission.shed_total == 0
        finally:
            await client.close()


@pytest.mark.resilience
class TestDegradation:
    """Chaos pins for the hybrid-resolution fallback: injected
    pressure flips the scheduler into degradation; pressure gone,
    requests serve full resolution again."""

    URL = "/tile/1/0/0/0?format=png&w=16&h=16"

    @staticmethod
    def _tiles():
        # 16 distinct 16x16 tiles of the 64x64 plane
        return [
            (x, y) for y in range(0, 64, 16) for x in range(0, 64, 16)
        ]

    async def test_engage_then_disengage(self, tmp_path, loop):
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1},
                        "request-budget-ms": 1200},
            slo={"queue-size": 16, "degrade-factor": 6.0},
            slow_s=0.15, workers=2,
        )
        try:
            # warm: trains the service-time EWMA, no contention
            r = await client.get(self.URL + "&x=0&y=0", headers=AUTH)
            assert r.status == 200
            assert "X-OMPB-Degraded" not in r.headers

            async def fetch(x, y):
                r = await client.get(
                    self.URL + f"&x={x}&y={y}", headers=AUTH
                )
                body = await r.read()
                return x, y, r, body

            burst = await asyncio.gather(*(
                fetch(x, y) for x, y in self._tiles()[:6]
            ))
            full = [r for _, _, r, _ in burst
                    if "X-OMPB-Degraded" not in r.headers]
            degraded = [
                (x, y, r, body) for x, y, r, body in burst
                if "X-OMPB-Degraded" in r.headers
            ]
            assert all(r.status == 200 for _, _, r, _ in burst)
            assert degraded, "pressure never engaged degradation"
            assert full, "every request degraded (signal too eager)"
            for x, y, r, body in degraded:
                assert r.headers["X-OMPB-Degraded"] == "1"
                assert np.array_equal(
                    _png_pixels(body), _upscaled_reference(x, y, 16, 16)
                ), "degraded body is not the upscaled lower level"

            # pressure gone: the SAME tile serves full-resolution with
            # no degraded tag and full-res pixels
            x, y, _, dbody = degraded[0]
            r = await client.get(
                self.URL + f"&x={x}&y={y}", headers=AUTH
            )
            body = await r.read()
            assert r.status == 200
            assert "X-OMPB-Degraded" not in r.headers
            assert np.array_equal(
                _png_pixels(body),
                IMG[0, 0, 0, y:y + 16, x:x + 16],
            )
            assert body != dbody
            snap = app_obj.scheduler.snapshot()
            assert snap["degraded"]["interactive"] == len(degraded)
            assert snap["shed"] == {
                "interactive": 0, "prefetch": 0, "bulk": 0,
            }
        finally:
            await client.close()

    async def test_no_coarser_level_fills_full_res_key(
        self, tmp_path, loop
    ):
        """A single-level (non-pyramidal) image under a degraded
        permit: the pipeline clears the flag, the response is
        untagged full-resolution — and the cache fill must land
        under the FULL-RES key, never |deg=1 (a full-res body cached
        under the degraded key would serve later degraded-permit
        hits tagged ``X-OMPB-Degraded`` on undegraded bytes)."""
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1},
                        "request-budget-ms": 1200},
            slo={"queue-size": 16, "degrade-factor": 8.0},
            slow_s=0.12, workers=2, cache=True, levels=1,
        )
        try:
            r = await client.get(self.URL + "&x=48&y=48", headers=AUTH)
            assert r.status == 200  # warm: trains the EWMA

            async def fetch(x, y):
                r = await client.get(
                    self.URL + f"&x={x}&y={y}", headers=AUTH
                )
                await r.read()
                return x, y, r

            burst = await asyncio.gather(*(
                fetch(x, y) for x, y in self._tiles()[:6]
            ))
            snap = app_obj.scheduler.snapshot()
            assert snap["degraded"]["interactive"] > 0, (
                "pressure never flagged a permit — scenario too light"
            )
            # no coarser level exists: nothing may be tagged
            for _, _, r in burst:
                assert r.status == 200
                assert "X-OMPB-Degraded" not in r.headers
            # the fill landed under the full-res key: a fresh GET of
            # a bursted tile is a HIT with the same ETag (a |deg=1
            # fill would leave this a miss/re-render)
            x, y, br = burst[0]
            r = await client.get(
                self.URL + f"&x={x}&y={y}", headers=AUTH
            )
            assert r.status == 200
            assert r.headers.get("X-Cache") == "hit"
            assert "X-OMPB-Degraded" not in r.headers
            assert r.headers["ETag"] == br.headers["ETag"]
        finally:
            await client.close()

    async def test_degraded_cache_isolation(self, tmp_path, loop):
        """A degraded body caches under its OWN key/ETag: it never
        overwrites the full-resolution entry, a full-res request
        never serves it, and its ETag never 304s a full-res GET."""
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1},
                        "request-budget-ms": 1200},
            slo={"queue-size": 16, "degrade-factor": 8.0},
            slow_s=0.12, workers=2, cache=True,
        )
        try:
            # warm the EWMA on a tile outside the burst set
            r = await client.get(self.URL + "&x=48&y=48", headers=AUTH)
            assert r.status == 200

            async def fetch(x, y):
                r = await client.get(
                    self.URL + f"&x={x}&y={y}", headers=AUTH
                )
                await r.read()
                return x, y, r

            burst = await asyncio.gather(*(
                fetch(x, y) for x, y in self._tiles()[:6]
            ))
            degraded = [
                (x, y, r) for x, y, r in burst
                if "X-OMPB-Degraded" in r.headers
            ]
            assert degraded, "pressure never engaged degradation"
            x, y, dr = degraded[0]
            detag = dr.headers["ETag"]

            # pressure gone: the full-resolution resource is intact —
            # a fresh GET misses (never served from the degraded
            # entry), carries a DIFFERENT ETag, and the degraded ETag
            # does not revalidate it
            url = self.URL + f"&x={x}&y={y}"
            r = await client.get(url, headers=AUTH)
            assert r.status == 200
            assert "X-OMPB-Degraded" not in r.headers
            fetag = r.headers["ETag"]
            assert fetag != detag
            r304 = await client.get(
                url, headers={**AUTH, "If-None-Match": detag}
            )
            assert r304.status == 200  # degraded ETag proves nothing
            r304 = await client.get(
                url, headers={**AUTH, "If-None-Match": fetag}
            )
            assert r304.status == 304
        finally:
            await client.close()


@pytest.mark.resilience
class TestOverloadDoorGate:
    """The pre-auth door gate: genuine overflow 503s BEFORE the
    session join (true overload must not convert into session-store /
    cluster-cache load), while cache hits still pass."""

    @staticmethod
    def _url(x, y):
        return f"/tile/1/0/0/0?x={x}&y={y}&w=32&h=32&format=png"

    async def test_genuine_overflow_sheds_before_auth(
        self, tmp_path, loop
    ):
        class CountingStore(MemorySessionStore):
            def __init__(self):
                super().__init__({"ck": "key"})
                self.lookups = 0

            async def get_omero_session_key(self, session_id):
                self.lookups += 1
                return await super().get_omero_session_key(session_id)

        gate = threading.Event()
        store = CountingStore()
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1,
                                      "retry-after-s": 2}},
            slo={"queue-size": 1, "degrade": False},
            workers=2, session_store=store,
        )
        inner = app_obj.pipeline.handle

        def gated(ctx):
            gate.wait(10.0)
            return inner(ctx)

        app_obj.pipeline.handle = gated
        try:
            occ = asyncio.ensure_future(
                client.get(self._url(0, 0), headers=AUTH)
            )
            await asyncio.sleep(0.1)  # slot taken
            waiter = asyncio.ensure_future(
                client.get(self._url(32, 0), headers=AUTH)
            )
            await asyncio.sleep(0.05)  # queue genuinely full
            before = store.lookups
            # would-shed arrival: 503 at the DOOR — no session lookup,
            # even with a cookie the store would reject
            r = await client.get(
                self._url(0, 32),
                headers={"Cookie": "sessionid=garbage"},
            )
            assert r.status == 503
            assert "Retry-After" in r.headers
            assert store.lookups == before
            assert app_obj.scheduler.snapshot()["shed"][
                "interactive"
            ] == 1
            gate.set()
            r0, r1 = await asyncio.gather(occ, waiter)
            assert (r0.status, r1.status) == (200, 200)
        finally:
            gate.set()
            await client.close()

    async def test_door_exempts_cache_hits(self, tmp_path, loop):
        gate = threading.Event()
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1}},
            slo={"queue-size": 1, "degrade": False},
            workers=2, cache=True,
        )
        try:
            r = await client.get(self._url(0, 0), headers=AUTH)
            assert r.status == 200  # fills the cache, uncontended
            inner = app_obj.pipeline.handle

            def gated(ctx):
                gate.wait(10.0)
                return inner(ctx)

            app_obj.pipeline.handle = gated
            occ = asyncio.ensure_future(
                client.get(self._url(32, 0), headers=AUTH)
            )
            await asyncio.sleep(0.1)
            waiter = asyncio.ensure_future(
                client.get(self._url(0, 32), headers=AUTH)
            )
            await asyncio.sleep(0.05)  # queue genuinely full
            # a HIT passes the door (serving it costs no slot) ...
            r = await client.get(self._url(0, 0), headers=AUTH)
            assert r.status == 200
            assert r.headers.get("X-Cache") == "hit"
            # ... while a miss that would shed still 503s at the door
            r = await client.get(self._url(32, 32), headers=AUTH)
            assert r.status == 503
            gate.set()
            r0, r1 = await asyncio.gather(occ, waiter)
            assert (r0.status, r1.status) == (200, 200)
        finally:
            gate.set()
            await client.close()

    async def test_door_exempts_disk_tier_hits(self, tmp_path, loop):
        """An entry that aged out of RAM onto the disk tier serves
        without a scheduler slot exactly like a RAM hit — the door's
        hit exemption must consult the spill index too, or overload
        sheds precisely the cheap traffic the gate exists to keep."""
        gate = threading.Event()
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1}},
            slo={"queue-size": 1, "degrade": False},
            workers=2,
            config_extra={"cache": {
                "enabled": True,
                "disk-dir": str(tmp_path / "spill"),
                "prefetch": {"enabled": False},
            }},
        )
        try:
            r = await client.get(self._url(0, 0), headers=AUTH)
            assert r.status == 200  # fills the RAM tier, uncontended
            cache = app_obj.result_cache
            # demote the entry to the disk tier: in the spill index,
            # out of RAM — the shape an entry has under memory
            # pressure once the LRU pushed it down
            with cache.memory._lock:
                key, entry = next(iter(
                    list(cache.memory._protected.items())
                    + list(cache.memory._probation.items())
                ))
            cache.disk.put(key, entry)
            cache.memory.remove(key)
            assert not cache.contains(key)
            assert cache.contains_any_tier(key)
            inner = app_obj.pipeline.handle

            def gated(ctx):
                gate.wait(10.0)
                return inner(ctx)

            app_obj.pipeline.handle = gated
            occ = asyncio.ensure_future(
                client.get(self._url(32, 0), headers=AUTH)
            )
            await asyncio.sleep(0.1)
            waiter = asyncio.ensure_future(
                client.get(self._url(0, 32), headers=AUTH)
            )
            await asyncio.sleep(0.05)  # queue genuinely full
            # the disk-resident tile passes the door and serves
            r = await client.get(self._url(0, 0), headers=AUTH)
            assert r.status == 200
            assert r.headers.get("X-Cache") == "hit"
            # ... while a genuine miss still 503s at the door
            r = await client.get(self._url(32, 32), headers=AUTH)
            assert r.status == 503
            gate.set()
            r0, r1 = await asyncio.gather(occ, waiter)
            assert (r0.status, r1.status) == (200, 200)
        finally:
            gate.set()
            await client.close()

    async def _saturate(self, app_obj, client, gate):
        """Fill the one execution slot and the one queue seat with
        gated misses; returns the futures to release at teardown."""
        inner = app_obj.pipeline.handle

        def gated(ctx):
            gate.wait(10.0)
            return inner(ctx)

        app_obj.pipeline.handle = gated
        occ = asyncio.ensure_future(
            client.get(self._url(32, 0), headers=AUTH)
        )
        await asyncio.sleep(0.1)
        waiter = asyncio.ensure_future(
            client.get(self._url(0, 32), headers=AUTH)
        )
        await asyncio.sleep(0.05)  # queue genuinely full
        return occ, waiter

    async def test_door_exempts_normalized_w0_spelling(
        self, tmp_path, loop
    ):
        """The door probe normalizes w/h=0 full-plane defaulting the
        way _serve does (via the open-buffer extent peek), so a tile
        cached under its EXPLICIT spelling no longer door-sheds when
        the w=0 spelling asks for it under genuine overflow — the
        KNOWN_GAPS unnormalized-probe item."""
        gate = threading.Event()
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1}},
            slo={"queue-size": 1, "degrade": False},
            workers=2, cache=True,
        )
        try:
            # fill under the EXPLICIT full-plane spelling (the serve
            # path normalizes w=0 to this same key, and opens the
            # buffer the door's extent peek answers from)
            r = await client.get(
                "/tile/1/0/0/0?x=0&y=0&w=64&h=64&format=png",
                headers=AUTH,
            )
            assert r.status == 200
            occ, waiter = await self._saturate(app_obj, client, gate)
            # the w=0 spelling of the SAME tile passes the door
            r = await client.get(
                "/tile/1/0/0/0?w=0&h=0&format=png", headers=AUTH
            )
            assert r.status == 200
            assert r.headers.get("X-Cache") == "hit"
            # an uncached tile still sheds
            r = await client.get(self._url(32, 32), headers=AUTH)
            assert r.status == 503
            gate.set()
            r0, r1 = await asyncio.gather(occ, waiter)
            assert (r0.status, r1.status) == (200, 200)
        finally:
            gate.set()
            await client.close()

    async def test_door_exempts_cached_render_tiles(
        self, tmp_path, loop
    ):
        """/render requests parse their spec at the door (pure
        grammar + LUT registry — no I/O) instead of being
        categorically unprobeable: a cached rendered tile passes the
        door under genuine overflow like any raw hit."""
        gate = threading.Event()
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1}},
            slo={"queue-size": 1, "degrade": False},
            workers=2, cache=True,
        )
        render_url = (
            "/render/1/0/0/0?x=0&y=0&w=32&h=32&c=1|0:65535$FF0000"
        )
        try:
            r = await client.get(render_url, headers=AUTH)
            assert r.status == 200  # fills the render cache entry
            occ, waiter = await self._saturate(app_obj, client, gate)
            r = await client.get(render_url, headers=AUTH)
            assert r.status == 200
            assert r.headers.get("X-Cache") == "hit"
            # an uncached render spec still sheds at the door
            r = await client.get(
                "/render/1/0/0/0?x=32&y=0&w=32&h=32"
                "&c=1|0:65535$FF0000",
                headers=AUTH,
            )
            assert r.status == 503
            gate.set()
            r0, r1 = await asyncio.gather(occ, waiter)
            assert (r0.status, r1.status) == (200, 200)
        finally:
            gate.set()
            await client.close()


@pytest.mark.resilience
class TestSweepDemotionHttp:
    async def test_sweeping_session_classified_bulk(
        self, tmp_path, loop
    ):
        app_obj, client = await _make_app(
            tmp_path, slo={"sweep-window": 4},
        )
        try:
            for i in range(6):  # a constant-stride robot walk (the
                # detector observes BEFORE serving, so the off-image
                # tail 404s still count as accesses)
                r = await client.get(
                    f"/tile/1/0/0/0?format=png&w=16&h=16&x={i * 16}"
                    "&y=0", headers=AUTH,
                )
                assert r.status in (200, 404)
            # enough constant-stride steps: the NEXT request is bulk
            await client.get(
                "/tile/1/0/0/0?format=png&w=16&h=16&x=16&y=16",
                headers=AUTH,
            )
            snap = app_obj.scheduler.snapshot()
            assert snap["classified"]["bulk"] >= 1
            det = app_obj.sweep_detector.snapshot()
            assert det["bulk_sessions"] == 1
        finally:
            await client.close()

    async def test_labeled_prefetch_never_demotes_session(
        self, tmp_path, loop
    ):
        """A client honestly labeling its lookahead as prefetch runs
        the canonical constant-stride sweep shape; learning from it
        would demote the whole session and shed the same user's
        interactive pans. Header-labeled traffic must not train the
        detector."""
        app_obj, client = await _make_app(
            tmp_path, slo={"sweep-window": 4},
        )
        try:
            for i in range(6):  # same walk, but self-labeled
                r = await client.get(
                    f"/tile/1/0/0/0?format=png&w=16&h=16&x={i * 16}"
                    "&y=0",
                    headers={**AUTH, "X-OMPB-Prefetch": "1"},
                )
                assert r.status in (200, 404)
            # the user's own unlabeled pan stays interactive
            r = await client.get(
                "/tile/1/0/0/0?format=png&w=16&h=16&x=16&y=16",
                headers=AUTH,
            )
            assert r.status == 200
            snap = app_obj.scheduler.snapshot()
            assert snap["classified"]["bulk"] == 0
            assert snap["classified"]["prefetch"] >= 6
            det = app_obj.sweep_detector.snapshot()
            assert det["bulk_sessions"] == 0
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# satellites: deferred trailing group, session overlap, healthz probes
# ---------------------------------------------------------------------------


class TestDeferredDeviceGroups:
    """Satellite: device-encode groups resolve through the queue's
    readback callback instead of draining inline in handle_batch."""

    def _pipeline(self, tmp_path, **kw):
        path = str(tmp_path / "img.zarr")
        write_ngff(path, IMG, chunks=(32, 32))
        registry = ImageRegistry()
        registry.add(1, path, type="zarr")
        svc = PixelsService(registry)
        pipe = TilePipeline(
            svc, engine="device", device_deflate=True,
            device_deflate_mode="rle", buckets=(32,), **kw,
        )
        return svc, pipe

    def _ctxs(self, n=2):
        return [
            TileCtx(
                1, 0, 0, 0, RegionDef(x * 32, 0, 32, 32),
                resolution=0, format="png",
            )
            for x in range(n)
        ]

    def test_defer_returns_placeholders_with_identical_bytes(
        self, tmp_path
    ):
        svc, pipe = self._pipeline(tmp_path)
        try:
            inline = pipe.handle_batch(self._ctxs())
            deferred = pipe.handle_batch(self._ctxs(), defer=True)
            assert any(
                isinstance(r, DeferredTile) for r in deferred
            ), "device groups should defer"
            resolved = [
                r.future.result(timeout=30.0)
                if isinstance(r, DeferredTile) else r
                for r in deferred
            ]
            assert resolved == inline  # byte-identical either way
        finally:
            pipe.close()
            svc.close()

    def test_close_resolves_deferred_futures(self, tmp_path):
        svc, pipe = self._pipeline(tmp_path)
        try:
            deferred = pipe.handle_batch(self._ctxs(), defer=True)
            futs = [
                r.future for r in deferred
                if isinstance(r, DeferredTile)
            ]
            pipe.close()  # drains the queue: every future resolves
            for f in futs:
                assert f.result(timeout=5.0) is not None
        finally:
            pipe.close()
            svc.close()

    async def test_batcher_chains_deferred_lanes(self, loop):
        """The dispatch layer: a DeferredTile lane's HTTP future
        resolves from the group callback — the executor batch (and
        the worker slot) completes without it."""
        from omero_ms_pixel_buffer_tpu.dispatch.batcher import (
            BatchingTileWorker,
        )

        group_fut: concurrent.futures.Future = concurrent.futures.Future()
        batches = []

        class FakePipeline:
            def handle(self, ctx):
                return b"inline"

            def handle_batch(self, ctxs, defer=False):
                batches.append((len(ctxs), defer))
                if len(batches) == 1:
                    assert defer
                    return [DeferredTile(group_fut)] * len(ctxs)
                return [b"second"] * len(ctxs)

        class Validator:
            async def validate(self, key):
                return True

        worker = BatchingTileWorker(
            FakePipeline(), Validator(), max_batch=4,
            coalesce_window_ms=5.0, workers=2,
        )
        await worker.start()
        try:
            c1 = TileCtx(1, 0, 0, 0, RegionDef(0, 0, 32, 32),
                         format="png", omero_session_key="k")
            c2 = TileCtx(1, 0, 0, 0, RegionDef(32, 0, 32, 32),
                         format="png", omero_session_key="k")
            t1 = asyncio.ensure_future(worker.handle(c1))
            t2 = asyncio.ensure_future(worker.handle(c2))
            await asyncio.sleep(0.1)
            assert not t1.done() and not t2.done()
            # the worker is FREE while the group is in flight: a new
            # batch executes to completion
            c3 = TileCtx(2, 0, 0, 0, RegionDef(0, 0, 32, 32),
                         format="png", omero_session_key="k")
            c4 = TileCtx(2, 0, 0, 0, RegionDef(32, 0, 32, 32),
                         format="png", omero_session_key="k")
            r3, r4 = await asyncio.gather(
                worker.handle(c3), worker.handle(c4)
            )
            assert r3[0] == b"second" and r4[0] == b"second"
            assert not t1.done()
            group_fut.set_result(b"device-bytes")
            r1, r2 = await asyncio.gather(t1, t2)
            assert r1[0] == b"device-bytes"
            assert r2[0] == b"device-bytes"
        finally:
            if not group_fut.done():
                group_fut.set_result(None)
            await worker.close()


class TestSessionLookupOverlap:
    """Satellite: `synchronicity: sync` no longer serializes — two
    in-flight session checks overlap."""

    async def test_lookups_overlap_under_sync_config(
        self, tmp_path, loop
    ):
        class SlowStore(OmeroWebSessionStore):
            def __init__(self):
                self.concurrent = 0
                self.max_concurrent = 0

            async def get_omero_session_key(self, session_id):
                self.concurrent += 1
                self.max_concurrent = max(
                    self.max_concurrent, self.concurrent
                )
                await asyncio.sleep(0.1)
                self.concurrent -= 1
                return "key"

        store = SlowStore()
        _, client = await _make_app(
            tmp_path,
            config_extra={
                "session-store": {"type": "memory",
                                  "synchronicity": "sync"},
            },
            session_store=store,
        )
        try:
            url = "/tile/1/0/0/0?w=32&h=32&format=png"
            rs = await asyncio.gather(
                client.get(url, headers=AUTH),
                client.get(url, headers=AUTH),
            )
            assert all(r.status == 200 for r in rs)
            assert store.max_concurrent >= 2, (
                "session lookups still serialized"
            )
        finally:
            await client.close()


class TestHealthzProbes:
    """Satellite: opt-in active dependency pings on /healthz?probe=1."""

    async def test_probe_pings_session_store(self, tmp_path, loop):
        _, client = await _make_app(tmp_path)
        try:
            r = await client.get("/healthz")
            body = await r.json()
            assert "probes" not in body  # opt-in only
            r = await client.get("/healthz?probe=1")
            body = await r.json()
            assert body["probes"]["session-store"] == "ok"
        finally:
            await client.close()

    async def test_probe_falsy_values_ignored(self, tmp_path, loop):
        """``?probe=0`` / ``?probe=false`` must not trigger a probe
        round — only the documented truthy spellings opt in."""
        _, client = await _make_app(tmp_path)
        try:
            for qs in ("probe=0", "probe=false", "probe=", "probe=no"):
                r = await client.get(f"/healthz?{qs}")
                body = await r.json()
                assert "probes" not in body, qs
        finally:
            await client.close()

    async def test_probe_reports_failure_without_failing(
        self, tmp_path, loop
    ):
        class DeadStore(OmeroWebSessionStore):
            async def get_omero_session_key(self, session_id):
                raise ConnectionError("redis down")

        app_obj, client = await _make_app(tmp_path)
        try:
            app_obj.session_store_probe_only = True
            # swap the store the PROBE sees (requests keep the real
            # middleware store wired at make_app time)
            app_obj.session_store = DeadStore()
            r = await client.get("/healthz?probe=1")
            assert r.status == 200
            body = await r.json()
            assert "ConnectionError" in body["probes"]["session-store"]
        finally:
            await client.close()

    async def test_probe_rounds_throttled(self, tmp_path, loop):
        """/healthz is unauthenticated: ``?probe=1`` must not be an
        amplification lever. Repeated calls inside the throttle
        window share ONE probe round against the dependencies."""
        class CountingStore(MemorySessionStore):
            def __init__(self):
                super().__init__({"ck": "key"})
                self.probe_lookups = 0

            async def get_omero_session_key(self, session_id):
                if session_id == "__ompb_healthz_probe__":
                    self.probe_lookups += 1
                return await super().get_omero_session_key(session_id)

        store = CountingStore()
        _, client = await _make_app(tmp_path, session_store=store)
        try:
            for _ in range(3):
                r = await client.get("/healthz?probe=1")
                body = await r.json()
                assert body["probes"]["session-store"] == "ok"
            assert store.probe_lookups == 1
        finally:
            await client.close()

    async def test_probe_pings_postgres_resolver(self, tmp_path, loop):
        app_obj, client = await _make_app(tmp_path)
        try:
            class Resolver:
                def __init__(self):
                    self.queries = []

                def query(self, sql, params):
                    self.queries.append(sql)
                    return [("1",)]

            resolver = Resolver()
            app_obj.pixels_service.metadata_resolver = resolver
            r = await client.get("/healthz?probe=1")
            body = await r.json()
            assert body["probes"]["postgres"] == "ok"
            assert resolver.queries == ["SELECT 1"]
        finally:
            await client.close()


class TestPrefetcherSweepSuppression:
    async def test_sweep_sessions_never_predict(self, loop):
        from omero_ms_pixel_buffer_tpu.cache.prefetch import (
            ViewportPrefetcher,
        )

        class Detector:
            def is_sweep(self, session):
                return session == "robot"

        class Admission:
            def has_headroom(self, fraction=0.5):
                return True

        fetched = []

        async def fetch(ctx, key):
            fetched.append(key)

        pre = ViewportPrefetcher(
            fetch, None, Admission(), sweep_detector=Detector(),
        )
        for i in range(4):
            pre.observe(TileCtx(
                1, 0, 0, 0, RegionDef(i * 64, 0, 64, 64),
                omero_session_key="robot",
            ))
        assert pre.snapshot()["suppressed_sweep"] == 4
        assert pre.snapshot()["enqueued"] == 0
        # a human session on the same prefetcher still predicts
        for i in range(3):
            pre.observe(TileCtx(
                1, 0, 0, 0, RegionDef(i * 64, 0, 64, 64),
                omero_session_key="human",
            ))
        assert pre.snapshot()["enqueued"] > 0
