"""In-tree LZ4 block and Blosc container codecs (ops/lz4, ops/blosc).

The decoder contract is pinned two ways: hand-built byte vectors from
the LZ4 block spec (so a mirrored encoder/decoder misunderstanding
cannot self-validate), plus round-trips through the in-tree encoders
over adversarial shapes. Hostile-input paths must raise, never crash
or over-allocate.
"""

import struct
import zlib

import numpy as np
import pytest

from conftest import needs_zstd

from omero_ms_pixel_buffer_tpu.ops.blosc import (
    BloscError,
    blosc_compress,
    blosc_decompress,
)
from omero_ms_pixel_buffer_tpu.ops.lz4 import (
    Lz4Error,
    lz4_block_compress,
    lz4_block_decompress,
)

rng = np.random.default_rng(61)


class TestLz4SpecVectors:
    """Byte-level vectors built from lz4_Block_format.html by hand."""

    def test_literals_only(self):
        # token 0x50: 5 literals, no match (final sequence)
        assert lz4_block_decompress(b"\x50hello", 5) == b"hello"

    def test_simple_overlap_match(self):
        # token 0x11: 1 literal 'a', match len 1+4=5, offset 1
        # -> 'a' + five copies of previous byte = 'aaaaaa'
        # then final literals-only sequence: token 0x10? no — end with
        # a 0-literal final token is not required if input ends after a
        # match? The spec ends blocks on literals; decoder accepts
        # ending exactly after a match only if output is complete.
        data = b"\x11a\x01\x00"
        assert lz4_block_decompress(data, 6) == b"aaaaaa"

    def test_match_from_distance(self):
        # 'abcd' then match offset 4 len 4 -> 'abcdabcd'
        data = b"\x40abcd\x04\x00"
        assert lz4_block_decompress(data, 8) == b"abcdabcd"

    def test_extended_literal_length(self):
        # token 0xF0: 15+ext literals; ext byte 5 -> 20 literals
        lit = bytes(range(20))
        assert lz4_block_decompress(b"\xf0\x05" + lit, 20) == lit

    def test_extended_match_length(self):
        # 1 literal 'x', match len 15+4 + ext 10 = 29, offset 1
        data = b"\x1fx\x01\x00\x0a"
        assert lz4_block_decompress(data, 30) == b"x" * 30

    def test_extended_match_255_saturation(self):
        # match len 4+15 + 255 + 3 = 277, offset 1
        data = b"\x1fx\x01\x00\xff\x03"
        assert lz4_block_decompress(data, 278) == b"x" * 278

    @pytest.mark.parametrize(
        "data,out_size",
        [
            (b"\x11a\x00\x00", 6),    # offset 0 invalid
            (b"\x11a\x05\x00", 6),    # offset beyond output
            (b"\x50hel", 5),          # truncated literals
            (b"\x11a\x01", 6),        # truncated offset
            (b"\x50hello", 3),        # literal overrun
            (b"\x11a\x01\x00", 3),    # match overrun
            (b"\x50hello", 9),        # short output
        ],
    )
    def test_hostile_inputs_raise(self, data, out_size):
        with pytest.raises(Lz4Error):
            lz4_block_decompress(data, out_size)


class TestLz4RoundTrip:
    @pytest.mark.parametrize("n", [0, 1, 4, 12, 13, 64, 1000, 100_000])
    def test_random(self, n):
        data = rng.integers(0, 256, n).astype(np.uint8).tobytes()
        assert lz4_block_decompress(lz4_block_compress(data), n) == data

    @pytest.mark.parametrize("n", [16, 100, 65_536, 300_000])
    def test_runny(self, n):
        data = np.repeat(
            rng.integers(0, 5, n // 8 + 1), 8
        ).astype(np.uint8).tobytes()[:n]
        comp = lz4_block_compress(data)
        assert lz4_block_decompress(comp, n) == data
        if n >= 100:  # tiny inputs can't amortize token overhead
            assert len(comp) < n // 2  # actually compresses

    def test_offset_boundary_64k(self):
        # far matches must still be encodable/decodable (offset <= 65535)
        block = rng.integers(0, 256, 70_000).astype(np.uint8).tobytes()
        data = block + block[:100]
        assert (
            lz4_block_decompress(lz4_block_compress(data), len(data))
            == data
        )


class TestBlosc:
    @pytest.mark.parametrize(
        "cname",
        ["lz4", pytest.param("zstd", marks=needs_zstd), "zlib"],
    )
    @pytest.mark.parametrize("typesize,shuffle", [
        (1, False), (2, True), (4, True), (8, True),
    ])
    def test_roundtrip(self, cname, typesize, shuffle):
        data = np.repeat(
            rng.integers(0, 1000, 5000), 4
        ).astype(np.uint32).tobytes()
        frame = blosc_compress(
            data, typesize=typesize, cname=cname, shuffle=shuffle
        )
        assert blosc_decompress(frame, len(data)) == data

    def test_multi_block(self):
        data = rng.integers(0, 4, 1 << 20).astype(np.uint16).tobytes()
        frame = blosc_compress(
            data, typesize=2, cname="lz4", blocksize=1 << 17
        )
        assert blosc_decompress(frame, len(data)) == data

    def test_incompressible_stores_raw(self):
        data = rng.integers(0, 256, 10_000).astype(np.uint8).tobytes()
        frame = blosc_compress(data, typesize=1, cname="lz4",
                               shuffle=False)
        assert blosc_decompress(frame, len(data)) == data

    def test_empty(self):
        frame = blosc_compress(b"", typesize=2)
        assert blosc_decompress(frame, 0) == b""

    def test_odd_tail_with_shuffle(self):
        # length not divisible by typesize: trailing bytes unshuffled
        data = bytes(rng.integers(0, 256, 1001).astype(np.uint8))
        frame = blosc_compress(data, typesize=4, cname="zlib",
                               shuffle=True)
        assert blosc_decompress(frame, len(data)) == data

    def test_hostile_headers(self):
        good = blosc_compress(b"abcdefgh" * 100, typesize=1)
        with pytest.raises(BloscError):
            blosc_decompress(good[:10], 800)  # truncated header
        with pytest.raises(BloscError):
            blosc_decompress(good, 10)  # declares more than expected
        bad = bytearray(good)
        bad[2] |= 0x4  # bit-shuffle flag
        with pytest.raises(BloscError):
            blosc_decompress(bytes(bad), 800)
        trunc = good[:-5]  # truncated final block
        with pytest.raises(BloscError):
            blosc_decompress(
                trunc[:12] + struct.pack("<i", len(trunc)) + trunc[16:],
                800,
            )

class TestBloscBitShuffle:
    """Bit-shuffle (flag bit 2): round trips and reference vectors.

    The reference transform is the naive bit-by-bit definition of
    ``bshuf_trans_bit_elem`` (bitshuffle's scalar path, which c-blosc
    embeds): element bits ordered byte-major/LSB-first transpose into
    bit planes packed LSB-first. The obviously-correct double loop
    below IS the reference; the vectorized implementation must match
    it bit for bit."""

    @staticmethod
    def _reference_bitshuffle(data: bytes, typesize: int) -> bytes:
        nelem = len(data) // typesize
        main_elems = nelem - nelem % 8
        main = main_elems * typesize
        if main == 0:
            return data
        nbits = typesize * 8
        out = bytearray(main)
        for j in range(nbits):  # bit index within an element
            byte_i, bit_i = j // 8, j % 8
            for e in range(main_elems):
                bit = (data[e * typesize + byte_i] >> bit_i) & 1
                out[j * (main_elems // 8) + e // 8] |= bit << (e % 8)
        return bytes(out) + data[main:]

    @pytest.mark.parametrize("typesize", [1, 2, 4, 8])
    def test_forward_matches_reference(self, typesize):
        from omero_ms_pixel_buffer_tpu.ops.blosc import _bit_shuffle

        data = bytes(rng.integers(0, 256, 16 * typesize + 5).astype(
            np.uint8
        ))
        assert _bit_shuffle(data, typesize) == (
            self._reference_bitshuffle(data, typesize)
        )

    def test_reference_vector(self):
        """A hand-checkable vector: 8 uint16 elements whose k-th
        element is ``1 << k`` — bit plane k holds exactly one set bit
        (element k, LSB-first), every other plane is zero."""
        from omero_ms_pixel_buffer_tpu.ops.blosc import _bit_shuffle

        elems = np.array([1 << k for k in range(8)], dtype="<u2")
        shuffled = _bit_shuffle(elems.tobytes(), 2)
        expected = bytearray(16)
        for k in range(8):
            expected[k] = 1 << k  # plane k, element k
        assert shuffled == bytes(expected)

    @pytest.mark.parametrize("typesize", [1, 2, 4, 8])
    @pytest.mark.parametrize("n_extra", [0, 3, 7 * 8 + 1])
    def test_unshuffle_inverts(self, typesize, n_extra):
        from omero_ms_pixel_buffer_tpu.ops.blosc import (
            _bit_shuffle,
            _bit_unshuffle,
        )

        data = bytes(rng.integers(
            0, 256, 64 * typesize + n_extra
        ).astype(np.uint8))
        assert _bit_unshuffle(_bit_shuffle(data, typesize),
                              typesize) == data

    @pytest.mark.parametrize(
        "cname",
        ["lz4", pytest.param("zstd", marks=needs_zstd), "zlib"],
    )
    @pytest.mark.parametrize("typesize", [2, 4])
    def test_frame_roundtrip(self, cname, typesize):
        """A bit-shuffled Zarr-style chunk decodes back bit-exact —
        the previously hard-erroring path (KNOWN_GAPS: bit-shuffle ->
        unreadable chunk)."""
        data = np.repeat(
            rng.integers(0, 1000, 5000), 4
        ).astype(np.uint32).tobytes()
        frame = blosc_compress(
            data, typesize=typesize, cname=cname, shuffle="bit"
        )
        assert frame[2] & 0x4  # bit-shuffle flag is on the wire
        assert not frame[2] & 0x1
        assert blosc_decompress(frame, len(data)) == data

    def test_bitshuffle_improves_smooth_data(self):
        """The reason the mode exists: slowly-varying integers pack
        their entropy into few bit planes."""
        ramp = (np.arange(1 << 16, dtype="<u4") // 17).tobytes()
        plain = blosc_compress(ramp, typesize=4, cname="zlib",
                               shuffle=False)
        bit = blosc_compress(ramp, typesize=4, cname="zlib",
                             shuffle="bit")
        assert len(bit) < len(plain)
        assert blosc_decompress(bit, len(ramp)) == ramp

    def test_unknown_shuffle_mode_rejected(self):
        with pytest.raises(BloscError):
            blosc_compress(b"abcd", shuffle="diagonal")


class TestBloscZstd:
    @needs_zstd
    def test_zstd_payload_decodes_with_real_zstd(self):
        # cross-check container plumbing against the reference codec
        data = np.arange(4096, dtype=np.uint16).tobytes()
        frame = blosc_compress(data, typesize=2, cname="zstd",
                               shuffle=False)
        assert blosc_decompress(frame, len(data)) == data
        # and our lz4 frames against our own decoder via the container
        frame2 = blosc_compress(data, typesize=2, cname="lz4",
                                shuffle=True)
        assert blosc_decompress(frame2, len(data)) == data
