"""Baseline JPEG decode + JPEG-in-TIFF (VERDICT r3 item 5).

PIL (libjpeg) is the independent oracle: the host decode path uses a
bit-exact islow integer IDCT, libjpeg's fixed-point color conversion,
and its 'fancy' triangular chroma upsampling — gray and RGB at 4:4:4,
4:2:2, and 4:2:0 all decode EQUAL to PIL. The device IDCT (the MXU
matmul form) is pinned within +-1 of islow. TIFF integration covers
JPEGTables tag 347 abbreviated streams, the memo roundtrip, batched
reads, and the full HTTP surface.
"""

import io

import numpy as np
import pytest
from PIL import Image

from omero_ms_pixel_buffer_tpu.io.jpeg import (
    JpegError,
    decode_jpeg,
    idct_blocks_device,
    idct_blocks_float,
    idct_blocks_host,
    parse_tables,
    split_tables,
)

rng = np.random.default_rng(71)

_YY, _XX = np.mgrid[0:208, 0:240].astype(np.float32)
GRAY = (
    128 + 60 * np.sin(_XX / 13) + 50 * np.cos(_YY / 17)
    + rng.normal(0, 6, (208, 240))
).clip(0, 255).astype(np.uint8)
RGB = np.stack(
    [GRAY, np.roll(GRAY, 9, 0), np.roll(GRAY, 5, 1)], -1
)


def _jpeg(img, mode, **kw):
    buf = io.BytesIO()
    Image.fromarray(img, mode).save(buf, "JPEG", **kw)
    return buf.getvalue()


class TestDecoderVsPil:
    @pytest.mark.parametrize("quality", [75, 90, 98])
    def test_gray_bit_exact(self, quality):
        data = _jpeg(GRAY, "L", quality=quality)
        np.testing.assert_array_equal(
            decode_jpeg(data), np.array(Image.open(io.BytesIO(data)))
        )

    @pytest.mark.parametrize("quality", [80, 92])
    def test_rgb444_bit_exact(self, quality):
        data = _jpeg(RGB, "RGB", quality=quality, subsampling=0)
        np.testing.assert_array_equal(
            decode_jpeg(data), np.array(Image.open(io.BytesIO(data)))
        )

    @pytest.mark.parametrize("subsampling", [1, 2])
    def test_subsampled_bit_exact(self, subsampling):
        # 'fancy' (triangular) chroma upsampling reproduces libjpeg's
        # integer arithmetic exactly — 4:2:2 and 4:2:0 match PIL
        data = _jpeg(RGB, "RGB", quality=90, subsampling=subsampling)
        np.testing.assert_array_equal(
            decode_jpeg(data), np.array(Image.open(io.BytesIO(data)))
        )

    def test_subsampled_odd_dimensions_bit_exact(self):
        odd = RGB[:93, :117]
        data = _jpeg(odd, "RGB", quality=88, subsampling=2)
        np.testing.assert_array_equal(
            decode_jpeg(data), np.array(Image.open(io.BytesIO(data)))
        )

    def test_restart_intervals_bit_exact(self):
        data = _jpeg(GRAY, "L", quality=85, restart_marker_blocks=3)
        assert b"\xff\xdd" in data  # DRI present
        np.testing.assert_array_equal(
            decode_jpeg(data), np.array(Image.open(io.BytesIO(data)))
        )

    @pytest.mark.parametrize("shape", [(1, 1), (7, 5), (8, 8), (17, 23)])
    def test_odd_sizes(self, shape):
        img = rng.integers(0, 255, shape).astype(np.uint8)
        data = _jpeg(img, "L", quality=95)
        np.testing.assert_array_equal(
            decode_jpeg(data), np.array(Image.open(io.BytesIO(data)))
        )

    def test_progressive_rejected(self):
        data = _jpeg(GRAY, "L", quality=90, progressive=True)
        with pytest.raises(JpegError, match="progressive"):
            decode_jpeg(data)

    def test_garbage_rejected(self):
        with pytest.raises(JpegError):
            decode_jpeg(b"not a jpeg")
        data = _jpeg(GRAY, "L", quality=90)
        with pytest.raises(JpegError):
            decode_jpeg(data[: len(data) // 2] )

    def test_hostile_sof_dimensions_bounded(self):
        # a tiny stream declaring a 65535x65535 frame must raise before
        # any coefficient allocation (OOM defence)
        data = bytearray(_jpeg(GRAY, "L", quality=90))
        sof = data.find(b"\xff\xc0")
        assert sof > 0
        data[sof + 5 : sof + 9] = b"\xff\xff\xff\xff"  # height, width
        with pytest.raises(JpegError, match="exceeds"):
            decode_jpeg(bytes(data))

    def test_grayscale_sampling_factors_ignored(self):
        # jpegtran -grayscale keeps the color original's 2x2 sampling
        # in SOF; T.81 says one-component scans ignore it
        orig = _jpeg(GRAY, "L", quality=90)
        patched = bytearray(orig)
        sof = patched.find(b"\xff\xc0")
        # FFC0 len(2) precision(1) h(2) w(2) ncomp(1) cid(1) -> hv
        hv_off = sof + 11
        assert patched[hv_off] == 0x11
        patched[hv_off] = 0x22
        np.testing.assert_array_equal(
            decode_jpeg(bytes(patched)), decode_jpeg(orig)
        )

    def test_malformed_segment_bodies_are_jpeg_errors(self):
        # length-consistent but too-short DHT body: the bare IndexError
        # inside the field parser must surface as JpegError
        with pytest.raises(JpegError):
            parse_tables(b"\xff\xd8\xff\xc4\x00\x03\x00\xff\xd9")
        # too-short SOF body
        with pytest.raises(JpegError):
            decode_jpeg(b"\xff\xd8\xff\xc0\x00\x04\x08\x00\xff\xd9")


class TestNativeScan:
    """The C entropy walker (native/jpeg_scan.cc) against the pure-
    Python reference loop: same tables, same coefficients, same
    errors."""

    def test_python_fallback_bit_exact(self, monkeypatch):
        from omero_ms_pixel_buffer_tpu.io import jpeg as jpeg_mod

        data = _jpeg(RGB, "RGB", quality=90, subsampling=0)
        native = decode_jpeg(data)
        monkeypatch.setattr(jpeg_mod, "_native_engine", lambda: None)
        pure = decode_jpeg(data)
        np.testing.assert_array_equal(native, pure)
        # and both equal PIL
        np.testing.assert_array_equal(
            pure, np.array(Image.open(io.BytesIO(data)))
        )

    def test_python_fallback_restarts(self, monkeypatch):
        from omero_ms_pixel_buffer_tpu.io import jpeg as jpeg_mod

        data = _jpeg(GRAY, "L", quality=85, restart_marker_blocks=3)
        native = decode_jpeg(data)
        monkeypatch.setattr(jpeg_mod, "_native_engine", lambda: None)
        np.testing.assert_array_equal(native, decode_jpeg(data))

    @pytest.mark.parametrize("native", [True, False])
    def test_hostile_dc_category_rejected(self, monkeypatch, native):
        # DHT mapping a code to DC magnitude category 63: undefined
        # shifts in either walker — must be a JpegError at table build
        from omero_ms_pixel_buffer_tpu.io import jpeg as jpeg_mod

        if not native:
            monkeypatch.setattr(jpeg_mod, "_native_engine", lambda: None)
        data = bytearray(_jpeg(GRAY, "L", quality=90))
        dht = data.find(b"\xff\xc4")
        assert data[dht + 4] == 0x00  # DC table 0
        sym_off = dht + 5 + 16  # after tc/th + 16 counts
        data[sym_off] = 63
        with pytest.raises(JpegError, match="category"):
            decode_jpeg(bytes(data))

    def test_native_rejects_truncated_scan(self):
        data = _jpeg(GRAY, "L", quality=90)
        sos = data.find(b"\xff\xda")
        with pytest.raises(JpegError):
            decode_jpeg(data[: sos + 40])  # scan cut mid-entropy


class TestAbbreviatedStreams:
    def test_split_and_seed_roundtrip(self):
        data = _jpeg(RGB, "RGB", quality=88, subsampling=0)
        tables_stream, stripped = split_tables(data)
        assert b"\xff\xdb" in tables_stream  # DQT moved
        assert b"\xff\xdb" not in stripped
        full = decode_jpeg(data)
        with pytest.raises(JpegError):
            decode_jpeg(stripped)  # tables missing
        seeded = decode_jpeg(stripped, tables=parse_tables(tables_stream))
        np.testing.assert_array_equal(full, seeded)

    def test_split_tables_hostile_streams_raise_jpeg_error(self):
        # truncated segment-length fields must surface as JpegError,
        # not bare struct.error/IndexError (ADVICE r4)
        data = _jpeg(GRAY, "L", quality=90)
        dqt = data.find(b"\xff\xdb")
        for hostile in (
            data[: dqt + 3],            # length field cut mid-u16
            data[:dqt] + b"\xff\xdb",   # marker with no length at all
            data[: dqt + 10],           # declared length past the end
        ):
            with pytest.raises(JpegError):
                split_tables(hostile)


class TestIdctPaths:
    def test_device_matches_float_exactly_and_islow_closely(self):
        coefs = rng.integers(-500, 500, (200, 64)).astype(np.int32)
        q = rng.integers(1, 64, 64).astype(np.int32)
        islow = idct_blocks_host(coefs, q)
        flt = idct_blocks_float(coefs, q)
        dev = idct_blocks_device(coefs, q)
        np.testing.assert_array_equal(flt, dev)  # f32 HIGHEST precision
        assert np.abs(islow.astype(int) - flt.astype(int)).max() <= 2

    def test_device_mode_decode(self, monkeypatch):
        data = _jpeg(GRAY, "L", quality=90)
        host = decode_jpeg(data, idct_mode="host")
        dev = decode_jpeg(data, idct_mode="device")
        assert np.abs(host.astype(int) - dev.astype(int)).max() <= 1


class TestJpegInTiff:
    @pytest.fixture(scope="class")
    def fixture(self, tmp_path_factory):
        from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff

        root = tmp_path_factory.mktemp("jpegtiff")
        path = str(root / "rgb.ome.tiff")
        write_ome_tiff(
            path, RGB[None, None, None], tile_size=(64, 64),
            compression="jpeg", pyramid_levels=2, jpeg_quality=92,
            jpeg_subsampling=0,
        )
        return path

    def test_tables_tag_written_once(self, fixture):
        data = open(fixture, "rb").read()
        # abbreviated tiles: DQT only in the tag-347 stream(s), one
        # per IFD (main + pyramid), not once per tile
        n_tiles = (240 // 64 + 1) * (208 // 64 + 1)
        assert data.count(b"\xff\xdb") < n_tiles

    def test_channel_reads_match_pil_within_1(self, fixture):
        from omero_ms_pixel_buffer_tpu.io.ometiff import (
            OmeTiffPixelBuffer,
        )

        # independent truth: PIL decodes the same (full-table) streams
        ref = np.array(
            Image.open(
                io.BytesIO(_jpeg(RGB, "RGB", quality=92, subsampling=0))
            )
        )
        buf = OmeTiffPixelBuffer(fixture)
        try:
            assert buf.meta.size_c == 3
            for c in range(3):
                tile = buf.get_tile_at(0, 0, c, 0, 32, 16, 120, 100)
                d = np.abs(
                    tile.astype(int)
                    - ref[16:116, 32:152, c].astype(int)
                )
                assert d.max() <= 1, f"channel {c}: {d.max()}"
        finally:
            buf.close()

    def test_batched_equals_sequential(self, fixture):
        from omero_ms_pixel_buffer_tpu.io.ometiff import (
            OmeTiffPixelBuffer,
        )

        buf = OmeTiffPixelBuffer(fixture)
        try:
            coords = [
                (0, 0, 0, 0, 0, 64, 64),
                (0, 1, 0, 48, 80, 100, 60),
                (0, 2, 0, 200, 180, 40, 28),  # edge
            ]
            batched = buf.read_tiles(coords)
            for co, tile in zip(coords, batched):
                np.testing.assert_array_equal(
                    tile, buf.get_tile_at(0, *co)
                )
        finally:
            buf.close()

    def test_pyramid_level(self, fixture):
        from omero_ms_pixel_buffer_tpu.io.ometiff import (
            OmeTiffPixelBuffer,
        )

        buf = OmeTiffPixelBuffer(fixture)
        try:
            assert buf.resolution_levels == 2
            lv = buf.get_tile_at(1, 0, 0, 0, 0, 0, 60, 50)
            assert lv.shape == (50, 60)
        finally:
            buf.close()

    def test_memo_roundtrip_preserves_tables(self, fixture, tmp_path):
        from omero_ms_pixel_buffer_tpu.io.ometiff import (
            OmeTiffPixelBuffer,
        )

        memo = str(tmp_path / "memo")
        b1 = OmeTiffPixelBuffer(fixture, memo_dir=memo)
        t1 = b1.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)
        b1.close()
        b2 = OmeTiffPixelBuffer(fixture, memo_dir=memo)  # from memo
        try:
            np.testing.assert_array_equal(
                t1, b2.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)
            )
        finally:
            b2.close()

    async def test_served_through_http(self, fixture, loop):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_pixel_buffer_tpu.auth.stores import (
            MemorySessionStore,
        )
        from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            ImageRegistry,
            PixelsService,
        )
        from omero_ms_pixel_buffer_tpu.utils.config import Config

        registry = ImageRegistry()
        registry.add(9, fixture)
        app_obj = PixelBufferApp(
            Config.from_dict({"session-store": {"type": "memory"}}),
            pixels_service=PixelsService(registry),
            session_store=MemorySessionStore({"ck": "key"}),
        )
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        await client.start_server()
        try:
            resp = await client.get(
                "/tile/9/0/1/0?x=16&y=24&w=96&h=80&format=png",
                headers={"Cookie": "sessionid=ck"},
            )
            assert resp.status == 200
            png = np.array(Image.open(io.BytesIO(await resp.read())))
            ref = np.array(
                Image.open(
                    io.BytesIO(
                        _jpeg(RGB, "RGB", quality=92, subsampling=0)
                    )
                )
            )[24:104, 16:112, 1]
            # pixel-tolerant (+-1) vs the independent libjpeg decode
            assert np.abs(png.astype(int) - ref.astype(int)).max() <= 1
        finally:
            await client.close()
