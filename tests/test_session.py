"""Interactive session plane (session/, r22).

The contracts the new subsystem must hold:

- **Auth matrix**: every session/annotation route sits behind the
  session middleware (unauthenticated -> 403), and a browser session
  revoked mid-channel loses its live channel within one ping
  interval — with an explicit close frame, never a silent stall.
- **Delta beats TTL**: an invalidation reaches a subscribed channel
  as a push within seconds (the ping interval and cache TTL are both
  far longer — the frame can only have been pushed).
- **Cross-replica**: an annotation write on replica A reaches a
  channel held open on replica B, riding the existing purge fan-out
  (the acceptance criterion of the r22 issue).
- **Viewport-true speculation**: a reported viewport rect supersedes
  the prefetcher's fixed span band; nonsense rects are client errors.
- **Annotation overlays**: stored shapes composite through the roi=
  mask path — same cache key, same ETag, byte-identical host vs
  device engines.
- **Fleet citizenship** (``-m resilience``): a rolling drain with 10
  live channels drops zero sessions (every client gets a reconnect
  frame) and serves zero 5xx; the successor absorbs the handoff.
"""

import asyncio
import dataclasses
import json
import socket
import time

import numpy as np
import pytest
from aiohttp import ClientSession, WSMsgType, web
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.cache.prefetch import ViewportPrefetcher
from omero_ms_pixel_buffer_tpu.cluster import FleetBrains
from omero_ms_pixel_buffer_tpu.errors import BadRequestError
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
from omero_ms_pixel_buffer_tpu.session import (
    AnnotationStore,
    ChannelRegistry,
)
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

rng = np.random.default_rng(17)
IMG = rng.integers(0, 4096, (1, 2, 2, 96, 128), dtype=np.uint16)
AUTH = {"Cookie": "sessionid=ck"}
RECT = {"type": "rect", "x": 8, "y": 8, "w": 24, "h": 16}


def _write_fixture(tmp_path):
    path = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(path, IMG, tile_size=(64, 64))
    registry = ImageRegistry()
    registry.add(1, path)
    return registry


async def _make_app(tmp_path, config_extra=None, sessions=None):
    registry = _write_fixture(tmp_path)
    raw = {
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 1.0}},
    }
    if config_extra:
        raw.update(config_extra)
    config = Config.from_dict(raw)
    store = MemorySessionStore(
        dict(sessions) if sessions else {"ck": "omero-key-1"}
    )
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=store,
    )
    client = TestClient(
        TestServer(app_obj.make_app()), loop=asyncio.get_running_loop()
    )
    await client.start_server()
    return app_obj, client, store


async def _recv_json(ws, timeout=10.0):
    msg = await asyncio.wait_for(ws.receive(), timeout)
    assert msg.type == WSMsgType.TEXT, msg
    return json.loads(msg.data)


# ---------------------------------------------------------------------------
# config: the session: block
# ---------------------------------------------------------------------------

class TestSessionConfig:
    BASE = {"session-store": {"type": "memory"}}

    def test_defaults(self):
        cfg = Config.from_dict(dict(self.BASE))
        sp = cfg.session
        assert sp.enabled is True
        assert sp.max_channels == 256
        assert sp.max_per_image == 64
        assert sp.queue_size == 64
        assert sp.ping_interval_s == 15.0
        assert sp.max_annotations_per_image == 64
        assert sp.max_annotation_images == 1024

    def test_unknown_key_fails_startup(self):
        with pytest.raises(ConfigError, match="session"):
            Config.from_dict({
                **self.BASE,
                "session": {"enabled": True, "max-chanels": 9},
            })

    def test_bad_values_fail(self):
        with pytest.raises(ConfigError):
            Config.from_dict({
                **self.BASE, "session": {"max-channels": "lots"},
            })
        with pytest.raises(ConfigError):
            Config.from_dict({
                **self.BASE, "session": {"ping-interval-s": 0},
            })

    def test_disabled_removes_routes(self):
        cfg = Config.from_dict({
            **self.BASE, "session": {"enabled": False},
        })
        assert cfg.session.enabled is False


# ---------------------------------------------------------------------------
# auth matrix
# ---------------------------------------------------------------------------

class TestSessionAuth:
    async def test_unauthenticated_403(self, tmp_path):
        app_obj, client, _store = await _make_app(tmp_path)
        try:
            for method, path in (
                ("GET", "/session/1/live"),
                ("POST", "/session/1/viewport"),
                ("GET", "/annotations/1"),
                ("POST", "/annotations/1"),
                ("GET", "/annotations/1/a1"),
                ("PUT", "/annotations/1/a1"),
                ("DELETE", "/annotations/1/a1"),
            ):
                r = await client.request(method, path)
                assert r.status == 403, (method, path, r.status)
        finally:
            await client.close()

    async def test_unknown_cookie_403(self, tmp_path):
        app_obj, client, _store = await _make_app(tmp_path)
        try:
            r = await client.get(
                "/annotations/1",
                headers={"Cookie": "sessionid=who-is-this"},
            )
            assert r.status == 403
        finally:
            await client.close()

    async def test_revoked_session_disconnects_channel(self, tmp_path):
        """A browser session revoked in the store loses its live
        channel within ~one ping interval, with an explicit close
        frame — the pump's revalidation lane."""
        app_obj, client, store = await _make_app(
            tmp_path,
            config_extra={"session": {"ping-interval-s": 0.1}},
        )
        try:
            ws = await client.ws_connect(
                "/session/1/live", headers=AUTH
            )
            hello = await _recv_json(ws)
            assert hello["type"] == "hello"
            del store.sessions["ck"]  # revocation
            closed = None
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                msg = await asyncio.wait_for(ws.receive(), 10.0)
                if msg.type != WSMsgType.TEXT:
                    break  # server closed us
                frame = json.loads(msg.data)
                if frame["type"] == "close":
                    closed = frame
            assert closed == {"type": "close", "reason": "revoked"}
            assert app_obj.session_channels.snapshot()["revoked"] == 1
            await ws.close()
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# delta push (single replica)
# ---------------------------------------------------------------------------

class TestDeltaPush:
    async def test_ws_hello_carries_epochs(self, tmp_path):
        app_obj, client, _store = await _make_app(tmp_path)
        try:
            ws = await client.ws_connect(
                "/session/1/live", headers=AUTH
            )
            hello = await _recv_json(ws)
            assert hello["type"] == "hello"
            assert hello["image"] == 1
            assert hello["transport"] == "ws"
            assert "epoch" in hello and "annotations" in hello
            await ws.close()
        finally:
            await client.close()

    async def test_invalidation_pushed_not_polled(self, tmp_path):
        """The delta frame lands in seconds while the ping interval
        (15s default) and cache TTL are far longer — only a push
        explains the arrival time."""
        app_obj, client, _store = await _make_app(tmp_path)
        try:
            ws = await client.ws_connect(
                "/session/1/live", headers=AUTH
            )
            await _recv_json(ws)  # hello
            t0 = time.monotonic()
            r = await client.post(
                "/annotations/1", headers=AUTH,
                json={"shape": RECT, "label": "tumor"},
            )
            assert r.status == 201
            kinds = set()
            while len(kinds) < 2:
                frame = await _recv_json(ws, timeout=5.0)
                kinds.add(frame["type"])
                assert frame["image"] == 1
                assert "tiles" in frame and "epoch" in frame
                if frame["type"] == "annotations":
                    assert frame["annotations"] == 1
            elapsed = time.monotonic() - t0
            # both the purge delta and the annotation sub-epoch frame,
            # well inside one ping interval
            assert kinds == {"invalidate", "annotations"}
            assert elapsed < 5.0
            await ws.close()
        finally:
            await client.close()

    async def test_sse_fallback_same_frames(self, tmp_path):
        app_obj, client, _store = await _make_app(tmp_path)
        try:
            resp = await client.get(
                "/session/1/live", headers=AUTH
            )
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "text/event-stream"
            )

            async def next_frame():
                while True:
                    line = await asyncio.wait_for(
                        resp.content.readline(), 10.0
                    )
                    if line.startswith(b"data: "):
                        return json.loads(line[6:])

            hello = await next_frame()
            assert hello["type"] == "hello"
            assert hello["transport"] == "sse"
            app_obj.session_channels.push_delta(1, epoch=7)
            frame = await next_frame()
            assert frame == {
                "type": "invalidate", "image": 1,
                "tiles": [], "epoch": 7,
            }
            resp.close()
        finally:
            await client.close()

    async def test_capacity_503_with_retry_after(self, tmp_path):
        app_obj, client, _store = await _make_app(
            tmp_path,
            config_extra={"session": {"max-channels": 1}},
        )
        try:
            held = await client.get("/session/1/live", headers=AUTH)
            assert held.status == 200
            await asyncio.wait_for(held.content.readline(), 10.0)
            second = await client.get("/session/1/live", headers=AUTH)
            assert second.status == 503
            assert second.headers["Retry-After"] == "1"
            snap = app_obj.session_channels.snapshot()
            assert snap["rejected_full"] == 1
            held.close()
        finally:
            await client.close()

    async def test_slow_consumer_drops_frames_never_blocks(self):
        """A full channel queue drops the frame (counted) instead of
        blocking the purge path — and the close sentinel still lands
        by displacing a queued frame."""
        reg = ChannelRegistry(
            max_channels=4, max_per_image=4, queue_size=2,
        )
        loop = asyncio.get_running_loop()
        reg.start(loop)
        ch = reg.register(1, "sid", "key", "ws")
        assert ch is not None
        for epoch in range(5):
            reg.push_delta(1, epoch=epoch)
        await asyncio.sleep(0)  # let call_soon_threadsafe drain
        assert ch.queue.qsize() == 2
        assert ch.dropped == 3
        await reg.close()
        # the sentinel displaced a queued frame rather than vanishing
        drained = []
        while not ch.queue.empty():
            drained.append(ch.queue.get_nowait())
        assert drained[-1] is None


# ---------------------------------------------------------------------------
# viewport-true speculation
# ---------------------------------------------------------------------------

class _FakeAdmission:
    def has_headroom(self, fraction=0.5):
        return True


def _ctx(x=0, y=0, w=64, h=64, resolution=None, session="sk"):
    return TileCtx(
        image_id=1, z=0, c=0, t=0,
        region=RegionDef(x, y, w, h), resolution=resolution,
        format="png", omero_session_key=session,
    )


class TestViewportTrue:
    def test_note_viewport_validation(self):
        pre = ViewportPrefetcher(None, None, _FakeAdmission())
        assert pre.note_viewport(
            "sk", 1, {"x": 0, "y": 0, "w": 256, "h": 128}
        )
        for bad in (
            {}, {"x": 0, "y": 0, "w": 0, "h": 64},
            {"x": -1, "y": 0, "w": 64, "h": 64},
            {"x": 0, "y": 0, "w": 64, "h": "tall"},
            {"x": 0, "y": 0, "w": 64, "h": 64, "zoom": "in"},
        ):
            assert not pre.note_viewport("sk", 1, bad), bad

    async def test_rect_supersedes_span_band(self):
        """With a reported rect, predictions cover the rect's tile
        footprint along the motion vector — including rows the fixed
        span band (span=0 continuation) would never reach."""
        fetched = []

        async def fetch(ctx, key):
            fetched.append((ctx.region.x, ctx.region.y))

        pre = ViewportPrefetcher(
            fetch, None, _FakeAdmission(),
            lookahead=1, viewport_span=0,
        )
        pre.start()
        try:
            # a 3x2-tile viewport, reported over the live channel
            assert pre.note_viewport(
                "sk", 1, {"x": 0, "y": 64, "w": 192, "h": 128}
            )
            pre.observe(_ctx(x=0, y=64))
            pre.observe(_ctx(x=64, y=64))  # panning right
            for _ in range(100):
                if len(fetched) >= 6:
                    break
                await asyncio.sleep(0.01)
            assert pre.snapshot()["viewport_true"] >= 1
            # the rect shifted one step right: columns 64..255,
            # rows 64..191 — BOTH rows, where the span-0 band only
            # predicts the continuation line at y=64
            for want in (
                (64, 64), (128, 64), (192, 64),
                (64, 128), (128, 128), (192, 128),
            ):
                assert want in fetched, (want, fetched)
        finally:
            await pre.close()

    async def test_zoom_mismatch_falls_back_to_band(self):
        fetched = []

        async def fetch(ctx, key):
            fetched.append((ctx.region.x, ctx.region.y))

        pre = ViewportPrefetcher(
            fetch, None, _FakeAdmission(),
            lookahead=1, viewport_span=0,
        )
        pre.start()
        try:
            pre.note_viewport(
                "sk", 1,
                {"x": 0, "y": 0, "w": 192, "h": 128, "zoom": 3},
            )
            pre.observe(_ctx(x=0, y=0, resolution=0))
            pre.observe(_ctx(x=64, y=0, resolution=0))
            for _ in range(100):
                if fetched:
                    break
                await asyncio.sleep(0.01)
            assert pre.snapshot()["viewport_true"] == 0
            assert (128, 0) in fetched  # the old continuation line
        finally:
            await pre.close()

    def test_invalidate_image_drops_viewports(self):
        pre = ViewportPrefetcher(None, None, _FakeAdmission())
        pre.note_viewport("sk", 1, {"x": 0, "y": 0, "w": 64, "h": 64})
        pre.note_viewport("sk", 2, {"x": 0, "y": 0, "w": 64, "h": 64})
        pre.invalidate_image(1)
        assert ("sk", 1) not in pre._viewports
        assert ("sk", 2) in pre._viewports

    async def test_viewport_post_endpoint(self, tmp_path):
        app_obj, client, _store = await _make_app(
            tmp_path,
            config_extra={"cache": {"prefetch": {"enabled": True}}},
        )
        try:
            r = await client.post(
                "/session/1/viewport", headers=AUTH,
                json={"x": 0, "y": 0, "w": 256, "h": 128},
            )
            assert r.status == 200
            assert (await r.json()) == {"noted": True}
            r = await client.post(
                "/session/1/viewport", headers=AUTH,
                json={"x": 0, "y": 0, "w": 0, "h": 128},
            )
            assert r.status == 400
            r = await client.post(
                "/session/1/viewport", headers=AUTH, data=b"not json",
            )
            assert r.status == 400
        finally:
            await client.close()

    async def test_ws_viewport_frame_feeds_prefetcher(self, tmp_path):
        app_obj, client, _store = await _make_app(
            tmp_path,
            config_extra={"cache": {"prefetch": {"enabled": True}}},
        )
        try:
            ws = await client.ws_connect(
                "/session/1/live", headers=AUTH
            )
            await _recv_json(ws)  # hello
            await ws.send_json({
                "type": "viewport",
                "x": 64, "y": 0, "w": 256, "h": 128,
            })
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if ("omero-key-1", 1) in app_obj.prefetcher._viewports:
                    break
                await asyncio.sleep(0.02)
            rect = app_obj.prefetcher._viewports[("omero-key-1", 1)]
            assert rect["w"] == 256 and rect["x"] == 64
            # garbled and unknown frames are no-ops, not disconnects
            await ws.send_str("not json{")
            await ws.send_json({"type": "mystery"})
            await ws.send_json({
                "type": "viewport", "x": 1, "y": 1, "w": 64, "h": 64,
            })
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                rect = app_obj.prefetcher._viewports[
                    ("omero-key-1", 1)
                ]
                if rect["x"] == 1:
                    break
                await asyncio.sleep(0.02)
            assert rect["x"] == 1
            await ws.close()
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# annotations: CRUD + the render-plane join
# ---------------------------------------------------------------------------

class TestAnnotationCrud:
    async def test_crud_lifecycle(self, tmp_path):
        app_obj, client, _store = await _make_app(tmp_path)
        try:
            r = await client.post(
                "/annotations/1", headers=AUTH,
                json={"shape": RECT, "label": "tumor"},
            )
            assert r.status == 201
            created = await r.json()
            ann_id = created["annotation"]["id"]
            assert created["epoch"] == 1
            assert created["annotation"]["label"] == "tumor"

            r = await client.get("/annotations/1", headers=AUTH)
            listing = await r.json()
            assert listing["epoch"] == 1
            assert [a["id"] for a in listing["annotations"]] == [ann_id]

            r = await client.get(
                f"/annotations/1/{ann_id}", headers=AUTH
            )
            assert r.status == 200

            r = await client.put(
                f"/annotations/1/{ann_id}", headers=AUTH,
                json={"shape": {**RECT, "w": 40}, "label": "bigger"},
            )
            updated = await r.json()
            assert updated["epoch"] == 2
            assert updated["annotation"]["shape"]["w"] == 40

            r = await client.delete(
                f"/annotations/1/{ann_id}", headers=AUTH
            )
            assert (await r.json()) == {"deleted": True, "epoch": 3}

            for method, path in (
                ("GET", f"/annotations/1/{ann_id}"),
                ("PUT", f"/annotations/1/{ann_id}"),
                ("DELETE", f"/annotations/1/{ann_id}"),
            ):
                r = await client.request(
                    method, path, headers=AUTH,
                    json={"shape": RECT},
                )
                assert r.status == 404, (method, r.status)
        finally:
            await client.close()

    async def test_grammar_rejections(self, tmp_path):
        app_obj, client, _store = await _make_app(tmp_path)
        try:
            for body in (
                b"not json",
                json.dumps(["a", "list"]).encode(),
                json.dumps({"shape": {"type": "blob"}}).encode(),
                json.dumps(
                    {"shape": {**RECT, "mystery": 1}}
                ).encode(),
            ):
                r = await client.post(
                    "/annotations/1", headers=AUTH, data=body,
                )
                assert r.status == 400, body
        finally:
            await client.close()

    def test_store_bounds(self):
        store = AnnotationStore(max_images=2, max_per_image=2)
        store.create(1, {"shape": RECT})
        store.create(1, {"shape": RECT})
        with pytest.raises(BadRequestError):
            store.create(1, {"shape": RECT})
        # LRU image eviction
        store.create(2, {"shape": RECT})
        store.create(3, {"shape": RECT})
        assert store.sub_epoch(1) == 0  # evicted
        assert store.snapshot()["evicted_images"] == 1


class TestAnnotationOverlays:
    async def test_overlay_shares_cache_entry_with_roi(self, tmp_path):
        """annotations=1 with stored shapes == an explicit roi= of
        the same shapes: one RenderSpec signature, one cache entry,
        one ETag. The second spelling must HIT the first's entry."""
        app_obj, client, _store = await _make_app(tmp_path)
        try:
            r = await client.post(
                "/annotations/1", headers=AUTH, json={"shape": RECT},
            )
            assert r.status == 201
            base = "/render/1/0/0/0?c=1|0:4095$FF0000&w=64&h=64"
            ra = await client.get(
                base + "&annotations=1", headers=AUTH
            )
            assert ra.status == 200
            assert ra.headers["X-Cache"] == "miss"
            roi = json.dumps([RECT], separators=(",", ":"))
            rb = await client.get(
                base + f"&roi={roi}", headers=AUTH
            )
            assert rb.status == 200
            assert rb.headers["X-Cache"] == "hit"  # SAME entry
            assert rb.headers["ETag"] == ra.headers["ETag"]
            assert (await rb.read()) == (await ra.read())
            # and the overlay actually changed the bytes
            plain = await client.get(base, headers=AUTH)
            assert (await plain.read()) != (await ra.read())
        finally:
            await client.close()

    async def test_annotation_write_invalidates_overlay(self, tmp_path):
        app_obj, client, _store = await _make_app(tmp_path)
        try:
            r = await client.post(
                "/annotations/1", headers=AUTH, json={"shape": RECT},
            )
            ann_id = (await r.json())["annotation"]["id"]
            base = (
                "/render/1/0/0/0?c=1|0:4095$FF0000&w=64&h=64"
                "&annotations=1"
            )
            first = await client.get(base, headers=AUTH)
            body_one = await first.read()
            r = await client.put(
                f"/annotations/1/{ann_id}", headers=AUTH,
                json={"shape": {**RECT, "w": 48}},
            )
            assert r.status == 200
            second = await client.get(base, headers=AUTH)
            # the shape set keys the cache: a changed overlay is a
            # changed key, never a stale hit
            assert second.headers["X-Cache"] == "miss"
            assert (await second.read()) != body_one
        finally:
            await client.close()

    def test_overlay_bytes_identical_host_vs_device(self, tmp_path):
        """The engine-identity contract extends to annotation
        overlays: the merged mask tuple renders byte-identical on the
        host and device engines (masks are engine-independent host
        math, composited before encode)."""
        registry = _write_fixture(tmp_path)
        service = PixelsService(registry)
        store = AnnotationStore()
        store.create(1, {"shape": RECT})
        store.create(
            1,
            {"shape": {"type": "ellipse", "cx": 40, "cy": 30,
                       "rx": 12, "ry": 8}},
        )
        spec = RenderSpec.from_params({"c": "1|0:4095$FF0000"})
        spec = dataclasses.replace(
            spec, masks=spec.masks + store.shapes(1)
        )

        def ctx():
            return TileCtx(
                image_id=1, z=0, c=0, t=0,
                region=RegionDef(0, 0, 64, 64), format="png",
                omero_session_key="k", render=spec,
            )

        host_pipe = TilePipeline(service, engine="host")
        dev_pipe = TilePipeline(
            service, engine="device", device_deflate=True
        )
        dev_pipe.mesh = None
        try:
            host_png = host_pipe.handle(ctx())
            dev_png = dev_pipe.handle_batch([ctx()])[0]
            assert host_png is not None
            assert host_png == dev_png
        finally:
            host_pipe.close()
            dev_pipe.close()
            service.close()


# ---------------------------------------------------------------------------
# fleet SLI aggregation (satellite: brain exchange)
# ---------------------------------------------------------------------------

class TestFleetSli:
    def test_apply_fleet_takes_worst_burn(self):
        brains = FleetBrains(None, "http://self:1")
        fleet = {
            "http://a:1": {"sli": {
                "5m": {"interactive": 14.2, "bulk": 0.1},
            }},
            "http://b:2": {"sli": {
                "5m": {"interactive": 0.3},
                "1h": {"prefetch": 2.5},
            }},
            "http://c:3": {"sli": "garbage"},  # malformed: ignored
        }
        brains.apply_fleet(fleet, list(fleet))
        sli = brains.fleet_sli
        # max, not mean: the 14.2x burn is the page signal
        assert sli["5m"]["interactive"] == 14.2
        assert sli["5m"]["bulk"] == 0.1
        assert sli["1h"]["prefetch"] == 2.5
        assert brains.snapshot()["fleet_sli"] == sli

    def test_malformed_cannot_grow_vocabulary(self):
        brains = FleetBrains(None, "http://self:1")
        brains.apply_fleet({
            "http://a:1": {"sli": {
                "5m": {"interactive": 1.0, "made-up-class": 9.0},
                "made-up-window": {"interactive": 9.0},
            }},
        }, ["http://a:1"])
        assert set(brains.fleet_sli) <= {"5m", "30m", "1h"}
        assert set(brains.fleet_sli.get("5m", {})) <= {
            "interactive", "prefetch", "bulk",
        }


# ---------------------------------------------------------------------------
# gossip join hint (satellite: contact adoption)
# ---------------------------------------------------------------------------

class _HintMembership:
    def __init__(self):
        self.noted = []

    def note_contact(self, url):
        self.noted.append(url)


class TestJoinHint:
    def _coordinator(self):
        from omero_ms_pixel_buffer_tpu.cache.plane.coordinator import (
            CachePlane,
        )

        coord = CachePlane.__new__(CachePlane)
        coord.self_url = "http://self:1"
        coord.membership = _HintMembership()
        return coord

    def test_url_shaped_contacts_adopted(self):
        coord = self._coordinator()
        coord.note_peer_contact("http://peer:9")
        assert coord.membership.noted == ["http://peer:9"]

    def test_garbage_rejected(self):
        coord = self._coordinator()
        for bad in (
            None, "", "bench-ops", "redis://x", 7,
            "http://self:1", "http://" + "x" * 600,
        ):
            coord.note_peer_contact(bad)
        assert coord.membership.noted == []

    def test_membership_without_hint_support_is_noop(self):
        coord = self._coordinator()
        coord.membership = object()  # lease-mode MembershipManager
        coord.note_peer_contact("http://peer:9")  # must not raise


# ---------------------------------------------------------------------------
# the two-replica lanes: cross-replica delta + drain handoff
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _boot_replica(img_path, members, self_url, port,
                        cluster_extra=None):
    registry = ImageRegistry()
    registry.add(1, img_path)
    config = Config.from_dict({
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 1.0}},
        "cache": {"prefetch": {"enabled": False}},
        "cluster": {
            "members": members,
            "self": self_url,
            "peer-timeout-ms": 3000,
            **(cluster_extra or {}),
        },
    })
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=MemorySessionStore({"ck": "omero-key-1"}),
    )
    runner = web.AppRunner(app_obj.make_app())
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    return app_obj, runner


async def _make_pair(tmp_path, cluster_extra=None, l2=False):
    img_path = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(img_path, IMG, tile_size=(64, 64))
    resp = None
    extra = dict(cluster_extra or {})
    if l2:
        from omero_ms_pixel_buffer_tpu.cache.plane.resp_stub import (
            InMemoryRespServer,
        )

        resp = InMemoryRespServer()
        await resp.start()
        extra["l2"] = {"uri": resp.uri}
    ports = [_free_port() for _ in range(2)]
    members = [f"http://127.0.0.1:{p}" for p in ports]
    nodes = []
    for i, port in enumerate(ports):
        nodes.append(await _boot_replica(
            img_path, members, members[i], port,
            cluster_extra=extra,
        ))

    async def cleanup():
        for _app, runner in nodes:
            await runner.cleanup()
        if resp is not None:
            await resp.close()

    return nodes, members, cleanup


PEER_OPS = {**AUTH, "X-OMPB-Peer": "ops"}


class TestCrossReplica:
    @pytest.mark.resilience
    async def test_annotation_write_reaches_remote_channel(
        self, tmp_path
    ):
        """THE acceptance criterion: a write on replica A arrives at
        a channel held open on replica B, as a delta push riding the
        purge fan-out — no polling, no TTL expiry involved."""
        nodes, members, cleanup = await _make_pair(tmp_path)
        try:
            (app_a, _), (app_b, _) = nodes
            url_a, url_b = members
            async with ClientSession() as http:
                ws = await asyncio.wait_for(
                    http.ws_connect(
                        url_b + "/session/1/live", headers=AUTH
                    ), 10.0,
                )
                hello = await _recv_json(ws)
                assert hello["type"] == "hello"
                async with http.post(
                    url_a + "/annotations/1", headers=AUTH,
                    json={"shape": RECT, "label": "from-A"},
                ) as r:
                    assert r.status == 201
                frame = await _recv_json(ws, timeout=10.0)
                assert frame["type"] == "invalidate"
                assert frame["image"] == 1
                await ws.close()
                # the obs plumbing saw the push on B
                snap = app_b.session_channels.snapshot()
                assert snap["delta_pushes"] >= 1
                # and /healthz reports the session plane fleet-wide
                async with http.get(url_b + "/healthz") as r:
                    health = await r.json()
                assert health["session"]["delta_pushes"] >= 1
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_drain_hands_off_live_channels(self, tmp_path):
        """Rolling drain with 10 live channels: every client gets an
        explicit reconnect frame naming the successor (zero silent
        drops), tile traffic sees zero 5xx throughout, the successor
        absorbs the subscription summary, and reconnecting to the
        named successor works immediately."""
        nodes, members, cleanup = await _make_pair(
            tmp_path, l2=True,
            cluster_extra={
                "lease-ttl-s": 0.6,
                "drain": {"deadline-s": 5, "signal": False},
            },
        )
        try:
            (app_a, _), (app_b, _) = nodes
            url_a, url_b = members
            await asyncio.sleep(0.5)  # leases discovered
            statuses = []
            async with ClientSession() as http:
                sockets = []
                for _ in range(10):
                    ws = await asyncio.wait_for(
                        http.ws_connect(
                            url_a + "/session/1/live", headers=AUTH
                        ), 10.0,
                    )
                    hello = await _recv_json(ws)
                    assert hello["type"] == "hello"
                    sockets.append(ws)

                async def tile_round():
                    for url in (url_a, url_b):
                        async with http.get(
                            url + "/tile/1/0/0/0?w=64&h=64&format=png",
                            headers=AUTH,
                        ) as r:
                            statuses.append(r.status)
                            await r.read()

                await tile_round()

                async def drain():
                    async with http.post(
                        url_a + "/internal/drain?wait=1",
                        headers=PEER_OPS,
                    ) as r:
                        return r.status, await r.json()

                drain_task = asyncio.ensure_future(drain())
                while not drain_task.done():
                    await tile_round()
                    await asyncio.sleep(0.05)
                status, drained = await drain_task
                assert status == 200
                assert drained["state"] == "drained"
                sessions = drained["stats"]["sessions"]
                assert sessions["channels"] == 10
                assert sessions["successor"] == url_b
                assert sessions["pushed"] is True

                # zero dropped sessions: every channel got the
                # explicit reconnect frame before its close
                reconnects = 0
                for ws in sockets:
                    frame = await _recv_json(ws, timeout=10.0)
                    assert frame["type"] == "reconnect"
                    assert frame["reconnect"] == url_b
                    reconnects += 1
                    msg = await asyncio.wait_for(ws.receive(), 10.0)
                    assert msg.type in (
                        WSMsgType.CLOSE, WSMsgType.CLOSED,
                        WSMsgType.CLOSING,
                    )
                    await ws.close()
                assert reconnects == 10

                # the successor absorbed the handoff summary...
                snap_b = app_b.session_channels.snapshot()
                assert snap_b["handoff_in"] == 10
                snap_a = app_a.session_channels.snapshot()
                assert snap_a["handoff_out"] == 10

                # ...and accepts the reconnect wave right now
                ws = await asyncio.wait_for(
                    http.ws_connect(
                        url_b + "/session/1/live", headers=AUTH
                    ), 10.0,
                )
                hello = await _recv_json(ws)
                assert hello["type"] == "hello"
                await ws.close()

                # the fleet SLI aggregate rides the brain exchange
                # and lands in /healthz (satellite: SLI burn rates)
                async with http.get(url_b + "/healthz") as r:
                    health = await r.json()
                assert "fleet_sli" in health["cluster"]["brains"]

            # a planned leave is not a crash
            assert statuses and all(s < 500 for s in statuses), (
                [s for s in statuses if s >= 500]
            )
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_session_handoff_endpoint_validation(self, tmp_path):
        nodes, members, cleanup = await _make_pair(tmp_path)
        try:
            url_a = members[0]
            async with ClientSession() as http:
                # JSON content-type routes to the session branch;
                # a malformed payload is a 400, not an absorb
                async with http.post(
                    url_a + "/internal/handoff",
                    headers={
                        **PEER_OPS,
                        "Content-Type": "application/json",
                    },
                    data=b'{"kind": "mystery"}',
                ) as r:
                    assert r.status == 400
                async with http.post(
                    url_a + "/internal/handoff",
                    headers={
                        **PEER_OPS,
                        "Content-Type": "application/json",
                    },
                    data=json.dumps({
                        "kind": "session_handoff",
                        "subscriptions": [
                            {"image": 1, "channels": 3},
                        ],
                        "channels": 3,
                    }).encode(),
                ) as r:
                    assert r.status == 200
                    assert (await r.json()) == {"absorbed": 3}
                # no peer marker: refused like the rest of /internal/*
                async with http.post(
                    url_a + "/internal/handoff",
                    headers={"Content-Type": "application/json"},
                    data=b"{}",
                ) as r:
                    assert r.status == 403
        finally:
            await cleanup()
