"""Suppression fixture: the violation is real but carries an inline
rule-scoped suppression — ompb-lint must count it as suppressed, not
as a finding."""

import time


async def justified():
    time.sleep(0.001)  # ompb-lint: disable=loop-block -- fixture: deliberate, justified inline


async def standalone_comment_form():
    # ompb-lint: disable=loop-block -- fixture: comment-above form
    time.sleep(0.001)
