"""Seeded loop-block violations (never imported — parsed by ompb-lint
in tests/test_lint.py). One violation per async function below."""

import subprocess
import time


def helper():
    # not a violation by itself: sync helpers may block — the rule
    # fires where an ASYNC caller reaches this without an executor hop
    time.sleep(0.5)


async def direct_sleep():
    time.sleep(1)  # SEEDED: loop-block (direct)


async def indirect_sleep():
    helper()  # SEEDED: loop-block (via the intra-module call graph)


async def future_wait(fut):
    return fut.result()  # SEEDED: loop-block (blocking Future.result)


async def sync_read(path):
    with open(path) as f:  # SEEDED: loop-block (sync file I/O)
        return f.read()


async def shell_out():
    subprocess.run(["ls"])  # SEEDED: loop-block (subprocess)
