# ompb-lint: scope=error-taxonomy
"""Seeded error-taxonomy violations: a bare except, a swallowed
CancelledError, and an exception with no HTTP status mapping raised
on a (fixture) request path."""

import asyncio


def parse(raw):
    try:
        return int(raw)
    except:  # SEEDED: error-taxonomy (bare except)  # noqa: E722
        return None


async def worker(q):
    try:
        await q.get()
    except asyncio.CancelledError:  # SEEDED: error-taxonomy (swallowed)
        pass


def handler(image_id):
    raise KeyError(image_id)  # SEEDED: error-taxonomy (unmapped)
