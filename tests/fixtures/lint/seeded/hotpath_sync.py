# ompb-lint: scope=jax-hotpath
"""Seeded jax-hotpath violations: a host sync on a device value, an
explicit block_until_ready, and a per-call jit."""

import jax
import jax.numpy as jnp
import numpy as np


def sync_pull(x):
    y = jnp.abs(x)
    return np.asarray(y)  # SEEDED: jax-hotpath (host sync)


def eager_wait(x):
    y = jnp.abs(x)
    y.block_until_ready()  # SEEDED: jax-hotpath (full device sync)
    return y


def per_call_jit(x):
    fn = jax.jit(lambda v: v + 1)  # SEEDED: jax-hotpath (re-traces per call)
    return fn(x)
