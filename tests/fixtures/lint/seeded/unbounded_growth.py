# ompb-lint: scope=bounded-growth
"""Seeded bounded-growth violations: collections that only ever grow
(the PR-9 immortal-negative-cache shape)."""

_SEEN = []


def note(event):
    _SEEN.append(event)  # SEEDED: module-level growth, no eviction


class SessionIndex:
    def __init__(self):
        self.by_key = {}
        self.order = []

    def record(self, key, value):
        self.by_key[key] = value  # SEEDED: dynamic-key store, no eviction
        self.order.append(key)  # SEEDED: append, no eviction
