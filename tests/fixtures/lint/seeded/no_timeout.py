# ompb-lint: scope=resilience-coverage
"""Seeded resilience-coverage violation (timeout flavor): the remote
GET is breaker-gated and fault-injected, but NO caller path bounds the
exchange with a per-call timeout — a dependency that stops answering
parks the caller."""

import http.client


class _Breaker:
    def allow(self):
        pass

    def record_success(self, duration_s=None):
        pass


class _Injector:
    def fire(self, point):
        pass


breaker = _Breaker()
INJECTOR = _Injector()


def raw_get(host, key):
    conn = http.client.HTTPConnection(host)  # SEEDED: resilience-coverage (no timeout)
    conn.request("GET", "/" + key)
    return conn.getresponse().read()


def guarded_get(host, key):
    breaker.allow()
    INJECTOR.fire("store.fixture")
    body = raw_get(host, key)
    breaker.record_success()
    return body
