# ompb-lint: scope=jax-hotpath
"""Seeded jax-hotpath loop violations: per-iteration host syncs on
device values inside ``for``/``while`` bodies — each one a full device
round trip per lane (the dispatcher-code pattern the r9 rule
extension exists to catch)."""

import jax.numpy as jnp
import numpy as np


def per_lane_pull(batch):
    y = jnp.abs(batch)
    out = []
    for i in range(4):
        out.append(np.asarray(y))  # SEEDED: jax-hotpath (asarray in loop)
    return out


def per_lane_item(lengths):
    y = jnp.cumsum(lengths)
    total = 0
    while total < 10:
        total += y.item()  # SEEDED: jax-hotpath (.item() in loop)
    return total


def per_lane_float(x):
    y = jnp.abs(x)
    acc = 0.0
    for _ in range(2):
        acc += float(y)  # SEEDED: jax-hotpath (float() in loop)
    return acc
