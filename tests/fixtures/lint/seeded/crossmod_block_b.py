"""Sync helper for crossmod_block_a — not a violation by itself; the
rule fires where an ASYNC caller in another module reaches this."""

import time


def busy_wait():
    time.sleep(0.2)
