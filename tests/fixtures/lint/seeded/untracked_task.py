# ompb-lint: scope=task-hygiene
"""Seeded task-hygiene violations (never imported — parsed by
ompb-lint in tests/test_lint.py). Each spawn below drops its task on
the floor in a different way: the PR-14 hang class."""

import asyncio


class Poller:
    def __init__(self):
        self._task = None

    async def start(self):
        asyncio.create_task(self._run())  # SEEDED: bare fire-and-forget

    async def start_untracked(self):
        # SEEDED: stored on self but nothing ever awaits/cancels it
        self._task = asyncio.ensure_future(self._run())

    async def _run(self):
        await asyncio.sleep(0.1)


async def spawn_and_drop():
    t = asyncio.create_task(asyncio.sleep(0.1))  # SEEDED: never used again
    return None


async def offload_and_forget(loop, work):
    loop.run_in_executor(None, work)  # SEEDED: bare fire-and-forget
