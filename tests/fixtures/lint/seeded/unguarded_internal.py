# ompb-lint: scope=trust-surface
"""Seeded trust-surface violations: an /internal/* route with no
cluster-HMAC verification anywhere on its path, and a remote-byte
ingress that never crosses the integrity check."""


async def state_handler(request):
    return {"ok": True}


def setup(router):
    # SEEDED: handler never verifies, no guard middleware here
    router.add_get("/internal/state", state_handler)


def ingest(payload):
    entry = decode_transfer(payload)  # SEEDED: unverified remote bytes
    return entry
