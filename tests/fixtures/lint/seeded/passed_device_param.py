# ompb-lint: scope=jax-hotpath
"""Seeded jax-hotpath violation the module-local analyzer provably
missed: the device value escapes through a PARAMETER — the caller
produces it, the callee host-syncs it."""

import numpy as np
import jax.numpy as jnp


def _finish_lanes(filtered):
    return np.asarray(filtered)  # SEEDED: device value via parameter


def render(tiles):
    filtered = jnp.square(jnp.asarray(tiles))
    return _finish_lanes(filtered)
