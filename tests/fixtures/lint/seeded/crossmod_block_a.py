"""Seeded cross-module loop-block: the helper lives in a sibling
module, so only the interprocedural call graph can see the chain."""

from crossmod_block_b import busy_wait


async def tick():
    busy_wait()  # SEEDED: loop-block via the cross-module call graph
