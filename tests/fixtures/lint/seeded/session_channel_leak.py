# ompb-lint: scope=task-hygiene,bounded-growth
"""Seeded fleet-invariant violations in SESSION-CHANNEL shapes: the
exact leaks the interactive session plane (session/channels.py) must
never grow — a channel registry without caps, a push fan-out task
dropped on the floor, and a per-channel pump stored but never
cancelled. r22's "every channel bounded, every pump drained" contract,
inverted."""

import asyncio


class LeakyChannelRegistry:
    def __init__(self):
        self.channels = {}
        self.pushes = []
        self._pump = None

    def register(self, channel_id, channel):
        # SEEDED: dynamic-key channel store, no cap, no eviction — a
        # reconnect storm grows this forever
        self.channels[channel_id] = channel

    def push_delta(self, image_id, epoch):
        self.pushes.append((image_id, epoch))  # SEEDED: append, no bound
        # SEEDED: fan-out task dropped on the floor — a failed push
        # dies silently and the delta never reaches the viewer
        asyncio.create_task(self._fan_out(image_id, epoch))

    async def start(self):
        # SEEDED: pump stored on self but nothing awaits or cancels
        # it — drain leaves it running against a dead loop
        self._pump = asyncio.ensure_future(self._run())

    async def _fan_out(self, image_id, epoch):
        await asyncio.sleep(0)

    async def _run(self):
        await asyncio.sleep(0.1)
