# ompb-lint: scope=config-drift
"""Seeded config-drift violations (doc pair: drift_config.yaml).
One finding of each type: an undocumented key, a documented-but-
never-parsed key, and a parsed-but-never-consumed (dead) key."""


def load(raw):
    unknown = set(raw) - {"port", "dead-timeout-ms", "mystery-knob"}
    if unknown:
        raise ValueError(f"unknown keys: {unknown}")
    return {
        "port": raw.get("port", 8082),
        "dead": raw.get("dead-timeout-ms", 100),  # SEEDED: dead key
        "knob": raw.get("mystery-knob", 1),  # SEEDED: undocumented
    }
