# ompb-lint: scope=resilience-coverage
"""Seeded resilience-coverage violation: a remote GET with no circuit
breaker and no fault-injection point on any caller path."""

import http.client


def naked_get(host, key):
    conn = http.client.HTTPConnection(host)  # SEEDED: resilience-coverage
    conn.request("GET", "/" + key)
    return conn.getresponse().read()
