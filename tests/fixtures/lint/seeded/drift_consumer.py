"""Consumer side of the seeded drift corpus: uses port and
mystery-knob so only dead-timeout-ms reads as dead config."""


def apply(cfg):
    return cfg.port, cfg.mystery_knob
