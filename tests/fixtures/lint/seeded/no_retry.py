# ompb-lint: scope=resilience-coverage
"""Seeded resilience-coverage violation (retry flavor, r18): the
remote GET is breaker-gated, fault-injected, AND timeout-bounded, but
NO caller path carries a retry policy — one transient transport error
surfaces as a request failure instead of a redial."""

import http.client


class _Breaker:
    def allow(self):
        pass

    def record_success(self, duration_s=None):
        pass


class _Injector:
    def fire(self, point):
        pass


breaker = _Breaker()
INJECTOR = _Injector()


def raw_get(host, key):
    conn = http.client.HTTPConnection(host, timeout=2)  # SEEDED: resilience-coverage (no retry)
    conn.request("GET", "/" + key)
    return conn.getresponse().read()


def guarded_get(host, key):
    breaker.allow()
    INJECTOR.fire("store.fixture")
    body = raw_get(host, key)
    breaker.record_success()
    return body
