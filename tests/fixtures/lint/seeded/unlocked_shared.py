"""Seeded lock-discipline violation: ``items`` is mutated under the
lock in ``add`` but drained without it in ``drain``."""

import threading


class SharedQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def add(self, x):
        with self._lock:
            self.items.append(x)

    def drain(self):
        out = list(self.items)  # SEEDED: lock-discipline
        self.items.clear()  # SEEDED: lock-discipline
        return out
