# ompb-lint: scope=trust-surface
"""Clean corpus: the /internal/* handler verifies the cluster HMAC
and the remote-byte ingress crosses the integrity check — ompb-lint
must report nothing here."""


def verify_cluster_request(request):
    return True


def body_matches(entry, body):
    return True


async def state_handler(request):
    verify_cluster_request(request)
    return {"ok": True}


def setup(router):
    router.add_get("/internal/state", state_handler)


def ingest(payload):
    entry = decode_transfer(payload)
    if not body_matches(entry, payload):
        raise ValueError("corrupt transfer")
    return entry
