# ompb-lint: scope=task-hygiene
"""Clean corpus: every spawned task is awaited, tracked-and-drained,
or handed to a consumer — ompb-lint must report nothing here."""

import asyncio


class Worker:
    def __init__(self):
        self._task = None
        self._jobs = set()

    async def start(self):
        self._task = asyncio.create_task(self._run())

    async def close(self):
        if self._task is not None:
            self._task.cancel()

    def spawn(self, coro):
        t = asyncio.create_task(coro)
        self._jobs.add(t)
        t.add_done_callback(self._jobs.discard)
        return t

    async def _run(self):
        await asyncio.sleep(0.1)


async def awaited_directly():
    await asyncio.create_task(asyncio.sleep(0.01))


async def gathered(coros):
    tasks = [asyncio.ensure_future(c) for c in coros]
    return await asyncio.gather(*tasks)
