"""Clean corpus: every mutable shared attribute stays under the lock;
immutable config attrs set once in __init__ don't need it. A helper
only ever called with the lock held is recognized as lock-held."""

import threading


class GoodQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []
        self.limit = 10  # immutable after __init__

    def add(self, x):
        with self._lock:
            if len(self.items) < self.limit:
                self.items.append(x)
            self._trim()

    def _trim(self):
        # callers hold self._lock
        while len(self.items) > self.limit:
            self.items.pop()

    def size(self):
        with self._lock:
            return len(self.items)

    def cap(self):
        return self.limit
