# ompb-lint: scope=error-taxonomy
"""Clean corpus: taxonomy-mapped raises, cancellation propagates."""

import asyncio


async def worker(q):
    try:
        await q.get()
    except asyncio.CancelledError:
        raise


def handler(image_id):
    raise NotFoundError(f"Cannot find Image:{image_id}")  # noqa: F821
