"""Clean corpus: async code that blocks ONLY behind executor hops —
ompb-lint must report nothing here."""

import asyncio
import time


def blocking_helper():
    time.sleep(0.1)


async def fetch(loop):
    await asyncio.sleep(0.01)
    await loop.run_in_executor(None, blocking_helper)


async def inline_lambda(loop):
    return await loop.run_in_executor(None, lambda: time.sleep(0.2))


async def named_nested(loop):
    def work():
        time.sleep(0.2)

    return await loop.run_in_executor(None, work)


async def via_assigned_lambda(loop):
    work = lambda: time.sleep(0.2)  # noqa: E731
    return await loop.run_in_executor(None, work)
