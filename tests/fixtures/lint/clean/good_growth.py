# ompb-lint: scope=bounded-growth
"""Clean corpus: every growing collection carries eviction evidence —
maxlen by construction, pop/len cap, rebuild, or a fixed-slot
record — ompb-lint must report nothing here."""

from collections import deque


class BoundedIndex:
    def __init__(self):
        self.recent = deque(maxlen=64)
        self.by_key = {}
        self.outcomes = {"hit": 0, "miss": 0}

    def record(self, key, value):
        while len(self.by_key) >= 64:
            self.by_key.pop(next(iter(self.by_key)))
        self.by_key[key] = value
        self.recent.append(key)
        self.outcomes["hit"] = self.outcomes["hit"] + 1


_EVENTS = []


def note(event):
    _EVENTS.append(event)


def reset():
    global _EVENTS
    _EVENTS = []
