# ompb-lint: scope=task-hygiene,bounded-growth
"""Clean corpus: the session-channel shapes done RIGHT — capped
registry with eviction, tracked-and-drained fan-out tasks, a pump
cancelled on close — ompb-lint must report nothing here."""

import asyncio
from collections import deque


class BoundedChannelRegistry:
    def __init__(self):
        self.channels = {}
        self.pushes = deque(maxlen=256)
        self._jobs = set()
        self._pump = None

    def register(self, channel_id, channel):
        while len(self.channels) >= 256:
            self.channels.pop(next(iter(self.channels)))
        self.channels[channel_id] = channel

    def push_delta(self, image_id, epoch):
        self.pushes.append((image_id, epoch))
        t = asyncio.create_task(self._fan_out(image_id, epoch))
        self._jobs.add(t)
        t.add_done_callback(self._jobs.discard)

    async def start(self):
        self._pump = asyncio.create_task(self._run())

    async def close(self):
        if self._pump is not None:
            self._pump.cancel()
        for t in list(self._jobs):
            t.cancel()
        self._jobs.clear()

    async def _fan_out(self, image_id, epoch):
        await asyncio.sleep(0)

    async def _run(self):
        await asyncio.sleep(0.1)
