# ompb-lint: scope=resilience-coverage
"""Clean corpus: the remote GET flows through a breaker gate and a
fault-injection point (in a caller — guard markers propagate over the
module-local call graph)."""

import http.client


class _Breaker:
    def allow(self):
        pass

    def record_success(self, duration_s=None):
        pass


class _Injector:
    def fire(self, point):
        pass


breaker = _Breaker()
INJECTOR = _Injector()


def raw_get(host, key):
    conn = http.client.HTTPConnection(host)
    conn.request("GET", "/" + key)
    return conn.getresponse().read()


def guarded_get(host, key):
    breaker.allow()
    INJECTOR.fire("store.fixture")
    body = raw_get(host, key)
    breaker.record_success()
    return body
