# ompb-lint: scope=resilience-coverage
"""Clean corpus: the remote GET flows through a breaker gate, a
fault-injection point, and a reconnect-once retry (in a caller —
guard markers propagate over the module-local call graph)."""

import http.client


class _Breaker:
    def allow(self):
        pass

    def record_success(self, duration_s=None):
        pass


class _Injector:
    def fire(self, point):
        pass


breaker = _Breaker()
INJECTOR = _Injector()


def raw_get(host, key):
    # the per-call timeout rides the primitive itself (the
    # resilience-coverage timeout marker)
    conn = http.client.HTTPConnection(host, timeout=2)
    conn.request("GET", "/" + key)
    return conn.getresponse().read()


def guarded_get(host, key):
    breaker.allow()
    INJECTOR.fire("store.fixture")
    try:
        body = raw_get(host, key)
    except OSError:
        # reconnect-once: the retry marker the rule requires on at
        # least one caller path
        body = raw_get(host, key)
    breaker.record_success()
    return body
