# ompb-lint: scope=jax-hotpath
"""Clean corpus: device values pulled once through jax.device_get,
jit at module level or behind a module-level cache."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(1,))
def roll_rows(x, k):
    return jnp.roll(x, k, axis=0)


_fn_cache: dict = {}


def cached_jit(x):
    fn = _fn_cache.get("fn")
    if fn is None:
        fn = jax.jit(lambda v: v * 2)
        _fn_cache["fn"] = fn
    return fn(x)


def single_pull(x):
    y = jnp.abs(x)
    total, host = jax.device_get((y.sum(), y))
    return int(total), host
