# ompb-lint: scope=jax-hotpath
"""Clean corpus: device values pulled once through jax.device_get,
jit at module level or behind a module-level cache."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(1,))
def roll_rows(x, k):
    return jnp.roll(x, k, axis=0)


_fn_cache: dict = {}


def cached_jit(x):
    fn = _fn_cache.get("fn")
    if fn is None:
        fn = jax.jit(lambda v: v * 2)
        _fn_cache["fn"] = fn
    return fn(x)


def single_pull(x):
    y = jnp.abs(x)
    total, host = jax.device_get((y.sum(), y))
    return int(total), host


def batched_pull_then_loop(batch):
    """The loop-safe shape: ONE device_get outside the loop, host-side
    per-lane work inside it."""
    y = jnp.abs(batch)
    host = jax.device_get(y)
    out = []
    for lane in range(4):
        out.append(float(host[lane].sum()))
    return out


def host_item_in_loop(lengths):
    lengths_np = jax.device_get(jnp.cumsum(lengths))
    total = 0
    while total < 10:
        total += lengths_np.item()
    return total
