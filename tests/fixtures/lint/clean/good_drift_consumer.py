"""Consumer side of the clean drift corpus: both keys are used."""


def apply(cfg):
    return cfg.port, cfg.depth
