# ompb-lint: scope=config-drift
"""Clean corpus (doc pair: good_drift.yaml): every key is validated,
documented, and consumed — ompb-lint must report nothing here."""


def load(raw):
    unknown = set(raw) - {"port", "depth"}
    if unknown:
        raise ValueError(f"unknown keys: {unknown}")
    return {
        "port": raw.get("port", 8082),
        "depth": raw.get("depth", 2),
    }
