"""Postgres wire client + the two consumers built on it (session
store, metadata resolver), against an in-process fake server."""

import asyncio
import base64
import hashlib
import hmac
import pickle
import struct

import pytest

from omero_ms_pixel_buffer_tpu.db.metadata import (
    OmeroPostgresMetadataResolver,
    PIXELS_QUERY,
)
from omero_ms_pixel_buffer_tpu.db.postgres import (
    PostgresClient,
    PostgresError,
    md5_password,
    parse_dsn,
    scram_client_final,
    scram_client_first,
)
from omero_ms_pixel_buffer_tpu.auth.stores import PostgresSessionStore


class TestScram:
    def test_rfc7677_vectors(self):
        """RFC 7677 §3 SCRAM-SHA-256 example exchange."""
        nonce = "rOprNGfwEbeRWgbNEkqO"
        first, bare = scram_client_first(nonce)
        assert first == "n,,n=,r=rOprNGfwEbeRWgbNEkqO"
        # RFC vector uses n=user; our bare omits the name (Postgres
        # ignores it), so recompute the vector with n= empty is not
        # possible — instead check the math against the RFC's bare.
        bare = "n=user,r=rOprNGfwEbeRWgbNEkqO"
        server_first = (
            "r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
            "s=W22ZaJ0SNY7soEsUEjb6gQ==,i=4096"
        )
        final, server_sig = scram_client_final("pencil", bare, server_first)
        assert final == (
            "c=biws,r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
            "p=dHzbZapWIk4jUhN+Ute9ytag9zjfMHgsqmmiz7AndVQ="
        )
        assert base64.b64encode(server_sig).decode() == (
            "6rriTRBi23WpRR/wtup+mMhUZUn/dB5nLTJRsjl95G4="
        )

    def test_md5_password(self):
        # md5(md5("secret" + "user") + salt) with a fixed salt
        out = md5_password("user", "secret", b"\x01\x02\x03\x04")
        inner = hashlib.md5(b"secretuser").hexdigest()
        expect = "md5" + hashlib.md5(
            inner.encode() + b"\x01\x02\x03\x04"
        ).hexdigest()
        assert out == expect


class TestDsn:
    def test_basic(self):
        p = parse_dsn("postgresql://alice:pw@db.example:5433/omero_web")
        assert p["host"] == "db.example"
        assert p["port"] == "5433"
        assert p["user"] == "alice"
        assert p["password"] == "pw"
        assert p["database"] == "omero_web"

    def test_jdbc_spelling(self):
        p = parse_dsn("jdbc:postgresql://db:5432/omero")
        assert p["host"] == "db"
        assert p["database"] == "omero"

    def test_defaults_and_rejects(self):
        p = parse_dsn("postgresql://localhost")
        assert p["port"] == "5432"
        assert p["database"] == "omero"
        with pytest.raises(ValueError):
            parse_dsn("mysql://db/x")


# ---------------------------------------------------------------------------
# Fake server: enough of protocol v3 for auth + extended query
# ---------------------------------------------------------------------------


class FakePg:
    """Serves canned rows; supports trust / cleartext / md5 / SCRAM auth.
    Records the SQL + params of every query it answers."""

    def __init__(self, auth="trust", user="omero", password="pw",
                 rows_for=None):
        self.auth = auth
        self.user = user
        self.password = password
        self.rows_for = rows_for or (lambda sql, params: [])
        self.queries = []
        self.server = None
        self.port = None

    async def __aenter__(self):
        self.server = await asyncio.start_server(
            self._handle, "127.0.0.1", 0
        )
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def __aexit__(self, *exc):
        self.server.close()
        await self.server.wait_closed()

    @staticmethod
    def _msg(type_byte: bytes, payload: bytes) -> bytes:
        return type_byte + struct.pack("!I", len(payload) + 4) + payload

    async def _read_msg(self, r):
        head = await r.readexactly(5)
        (length,) = struct.unpack("!I", head[1:5])
        return head[:1], await r.readexactly(length - 4)

    async def _handle(self, r, w):
        try:
            await self._session(r, w)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            w.close()

    async def _session(self, r, w):
        head = await r.readexactly(4)
        (length,) = struct.unpack("!I", head)
        body = await r.readexactly(length - 4)
        (proto,) = struct.unpack("!I", body[:4])
        assert proto == 196608

        ok = self._msg(b"R", struct.pack("!I", 0))
        if self.auth == "trust":
            w.write(ok)
        elif self.auth == "cleartext":
            w.write(self._msg(b"R", struct.pack("!I", 3)))
            await w.drain()
            t, payload = await self._read_msg(r)
            assert t == b"p"
            if payload.rstrip(b"\x00").decode() != self.password:
                w.write(self._error("28P01", "password authentication failed"))
                return
            w.write(ok)
        elif self.auth == "md5":
            salt = b"\xde\xad\xbe\xef"
            w.write(self._msg(b"R", struct.pack("!I", 5) + salt))
            await w.drain()
            t, payload = await self._read_msg(r)
            expect = md5_password(self.user, self.password, salt)
            if payload.rstrip(b"\x00").decode() != expect:
                w.write(self._error("28P01", "password authentication failed"))
                return
            w.write(ok)
        elif self.auth == "scram":
            w.write(self._msg(
                b"R", struct.pack("!I", 10) + b"SCRAM-SHA-256\x00\x00"
            ))
            await w.drain()
            t, payload = await self._read_msg(r)
            assert t == b"p"
            mech_end = payload.index(b"\x00")
            assert payload[:mech_end] == b"SCRAM-SHA-256"
            (blen,) = struct.unpack(
                "!I", payload[mech_end + 1 : mech_end + 5]
            )
            client_first = payload[mech_end + 5 : mech_end + 5 + blen].decode()
            client_bare = client_first.split(",", 2)[2]
            client_nonce = dict(
                kv.split("=", 1) for kv in client_bare.split(",")
            )["r"]
            salt, iters = b"0123456789abcdef", 4096
            server_nonce = client_nonce + "SRVNONCE"
            server_first = (
                f"r={server_nonce},s={base64.b64encode(salt).decode()},"
                f"i={iters}"
            )
            w.write(self._msg(
                b"R", struct.pack("!I", 11) + server_first.encode()
            ))
            await w.drain()
            t, payload = await self._read_msg(r)
            client_final = payload.decode()
            attrs = dict(
                kv.split("=", 1) for kv in client_final.split(",")
            )
            # verify the proof exactly as a real server does
            salted = hashlib.pbkdf2_hmac(
                "sha256", self.password.encode(), salt, iters
            )
            client_key = hmac.new(
                salted, b"Client Key", hashlib.sha256
            ).digest()
            stored = hashlib.sha256(client_key).digest()
            without_proof = client_final.rsplit(",p=", 1)[0]
            auth_msg = ",".join(
                (client_bare, server_first, without_proof)
            ).encode()
            sig = hmac.new(stored, auth_msg, hashlib.sha256).digest()
            proof = base64.b64decode(attrs["p"])
            recovered = bytes(a ^ b for a, b in zip(proof, sig))
            if hashlib.sha256(recovered).digest() != stored:
                w.write(self._error("28P01", "SCRAM proof mismatch"))
                return
            server_key = hmac.new(
                salted, b"Server Key", hashlib.sha256
            ).digest()
            server_sig = hmac.new(
                server_key, auth_msg, hashlib.sha256
            ).digest()
            final = "v=" + base64.b64encode(server_sig).decode()
            w.write(self._msg(b"R", struct.pack("!I", 12) + final.encode()))
            w.write(ok)
        w.write(self._msg(b"Z", b"I"))
        await w.drain()

        # extended-query loop
        sql, params = None, []
        while True:
            t, payload = await self._read_msg(r)
            if t == b"P":
                sql = payload.split(b"\x00")[1].decode()
            elif t == b"B":
                params = self._parse_bind(payload)
            elif t == b"E":
                pass
            elif t == b"S":
                self.queries.append((sql, params))
                rows = self.rows_for(sql, params)
                for row in rows:
                    cols = b""
                    for v in row:
                        if v is None:
                            cols += struct.pack("!i", -1)
                        else:
                            data = str(v).encode()
                            cols += struct.pack("!I", len(data)) + data
                    w.write(self._msg(
                        b"D", struct.pack("!H", len(row)) + cols
                    ))
                w.write(self._msg(b"C", b"SELECT %d\x00" % len(rows)))
                w.write(self._msg(b"Z", b"I"))
                await w.drain()
            elif t == b"X":
                return

    @staticmethod
    def _parse_bind(payload):
        off = 0
        for _ in range(2):  # portal, statement names
            off = payload.index(b"\x00", off) + 1
        (nfmt,) = struct.unpack_from("!H", payload, off)
        off += 2 + 2 * nfmt
        (nparams,) = struct.unpack_from("!H", payload, off)
        off += 2
        params = []
        for _ in range(nparams):
            (n,) = struct.unpack_from("!i", payload, off)
            off += 4
            if n == -1:
                params.append(None)
            else:
                params.append(payload[off : off + n].decode())
                off += n
        return params

    def _error(self, code, message):
        fields = b"SERROR\x00C" + code.encode() + b"\x00M" + \
            message.encode() + b"\x00\x00"
        return self._msg(b"E", fields)


class TestPostgresClient:
    @pytest.mark.parametrize("auth", ["trust", "cleartext", "md5", "scram"])
    def test_auth_and_query(self, loop, auth):
        async def run():
            async with FakePg(
                auth=auth, user="u1", password="sekret",
                rows_for=lambda sql, params: [("1", "hello"), ("2", None)],
            ) as pg:
                client = PostgresClient(
                    host="127.0.0.1", port=pg.port, user="u1",
                    password="sekret", database="db",
                )
                rows = await client.query("SELECT a, b FROM t WHERE x=$1",
                                          ["42"])
                await client.close()
                assert rows == [("1", "hello"), ("2", None)]
                assert pg.queries[-1] == (
                    "SELECT a, b FROM t WHERE x=$1", ["42"]
                )

        loop.run_until_complete(run())

    def test_bad_password_raises(self, loop):
        async def run():
            async with FakePg(auth="cleartext", password="right") as pg:
                client = PostgresClient(
                    host="127.0.0.1", port=pg.port, password="wrong",
                )
                with pytest.raises(PostgresError):
                    await client.query("SELECT 1")
                await client.close_nowait()

        loop.run_until_complete(run())

    def test_empty_result(self, loop):
        async def run():
            async with FakePg() as pg:
                client = PostgresClient(host="127.0.0.1", port=pg.port)
                rows = await client.query("SELECT 1")
                assert rows == []
                await client.close()

        loop.run_until_complete(run())


DJANGO_SESSION = base64.b64encode(
    b"hash:" + pickle.dumps(
        {"connector": {"omero_session_key": "omero-key-123"}}
    )
).decode()


class TestPostgresSessionStore:
    def test_lookup(self, loop):
        def rows_for(sql, params):
            assert "django_session" in sql
            if params == ["good-cookie"]:
                return [(DJANGO_SESSION,)]
            return []

        async def run():
            async with FakePg(rows_for=rows_for) as pg:
                store = PostgresSessionStore(
                    f"postgresql://omero:pw@127.0.0.1:{pg.port}/omero_web"
                )
                assert await store.get_omero_session_key(
                    "good-cookie"
                ) == "omero-key-123"
                assert await store.get_omero_session_key("bad") is None
                await store.close()

        loop.run_until_complete(run())


def pixels_row(
    pid="99", sx="4096", sy="2048", sz="16", sc="3", st="1",
    ptype="uint16", name="plate1", owner="2", group="3", perms="-120",
    fmt=None, e_type=None, e_lsid=None, e_uuid=None,
):
    """One PIXELS_QUERY result row (the widened ACL+format shape)."""
    return (pid, sx, sy, sz, sc, st, ptype, name, owner, group, perms,
            fmt, e_type, e_lsid, e_uuid)


class TestMetadataResolver:
    def test_pixels_contract(self, loop):
        def rows_for(sql, params):
            assert sql == PIXELS_QUERY
            if params == ["7"]:
                return [pixels_row(
                    fmt="OMETiff", e_type="ome.model.core.Image",
                    e_lsid="urn:lsid:x", e_uuid="u-1",
                )]
            return []

        async def run():
            async with FakePg(rows_for=rows_for) as pg:
                resolver = OmeroPostgresMetadataResolver(
                    f"postgresql://omero:pw@127.0.0.1:{pg.port}/omero"
                )
                meta = await resolver.get_pixels_async(7)
                assert meta.size_x == 4096 and meta.size_y == 2048
                assert meta.size_z == 16 and meta.size_c == 3
                assert meta.pixels_type == "uint16"
                assert meta.image_name == "plate1"
                # i.format / i.details.externalInfo parity
                # (TileRequestHandler.java:228-236)
                assert meta.image_format == "OMETiff"
                assert meta.external_info == {
                    "entityType": "ome.model.core.Image",
                    "lsid": "urn:lsid:x", "uuid": "u-1",
                }
                assert await resolver.get_pixels_async(8) is None  # -> 404
                await resolver.close()

        loop.run_until_complete(run())


class TestCrossLoopReuse:
    def test_sync_adapter_survives_multiple_calls(self):
        """get_pixels uses asyncio.run per call; the client must not
        reuse streams or locks bound to the previous (closed) loop."""
        import threading

        def rows_for(sql, params):
            return [pixels_row(pid="1", sx="64", sy="32", sz="1",
                               sc="1", st="1", ptype="uint8", name="img")]

        results = {}
        started = threading.Event()

        # run the fake server on its own thread+loop so each
        # asyncio.run() in get_pixels sees a live server
        def server_thread():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)

            async def run():
                async with FakePg(rows_for=rows_for) as pg:
                    results["port"] = pg.port
                    started.set()
                    await asyncio.sleep(5)

            try:
                loop.run_until_complete(run())
            finally:
                loop.close()

        t = threading.Thread(target=server_thread, daemon=True)
        t.start()
        assert started.wait(5)
        resolver = OmeroPostgresMetadataResolver(
            f"postgresql://omero:pw@127.0.0.1:{results['port']}/omero"
        )
        m1 = resolver.get_pixels(1)  # first asyncio.run
        m2 = resolver.get_pixels(2)  # second loop: must reconnect
        assert m1.size_x == 64 and m2.size_x == 64


def test_sslmode_require_rejected():
    with pytest.raises(ValueError, match="sslmode"):
        parse_dsn("postgresql://u:p@db/omero?sslmode=require")
    # prefer/disable pass through
    assert parse_dsn("postgresql://db/omero?sslmode=disable")["host"] == "db"


class TestResolverWiring:
    def test_resolver_overrides_metadata_plane(self, loop, tmp_path):
        """With a metadata resolver set, get_pixels answers from the DB
        contract; a resolver miss is a 404 even when the registry knows
        a path."""
        import numpy as np

        from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            ImageRegistry,
            PixelsService,
        )

        data = np.zeros((1, 1, 1, 64, 64), np.uint16)
        path = str(tmp_path / "img.ome.tiff")
        write_ome_tiff(path, data, tile_size=(64, 64))

        class FakeResolver:
            def get_pixels(self, image_id):
                if int(image_id) == 1:
                    from omero_ms_pixel_buffer_tpu.io.pixel_buffer import (
                        PixelsMeta,
                    )

                    return PixelsMeta(1, 64, 64, 1, 1, 1, "uint16", "db-img")
                return None

        registry = ImageRegistry()
        registry.add(1, path)
        registry.add(2, path)  # path known, but resolver says no
        service = PixelsService(registry, metadata_resolver=FakeResolver())
        meta = service.get_pixels(1)
        assert meta.image_name == "db-img"  # resolver, not the file
        assert service.get_pixels(2) is None  # -> 404
        # buffer plane still resolves through the registry
        assert service.get_pixel_buffer(1) is not None
        service.close()


class TestResolverCache:
    def test_metadata_cached_per_image(self, loop):
        calls = []

        def rows_for(sql, params):
            calls.append(params)
            return [pixels_row(pid="9", sx="128", sy="64", sz="1",
                               sc="1", st="1", ptype="uint8", name="img")]

        async def run():
            async with FakePg(rows_for=rows_for) as pg:
                resolver = OmeroPostgresMetadataResolver(
                    f"postgresql://omero:pw@127.0.0.1:{pg.port}/omero"
                )
                m1 = await resolver.get_pixels_async(5)
                m2 = await resolver.get_pixels_async(5)  # cache hit
                assert m1 == m2
                assert len(calls) == 1  # one DB roundtrip, not two
                await resolver.close()

        loop.run_until_complete(run())

    def test_closed_resolver_rejects(self, loop):
        async def run():
            async with FakePg() as pg:
                resolver = OmeroPostgresMetadataResolver(
                    f"postgresql://omero:pw@127.0.0.1:{pg.port}/omero"
                )
                resolver.close_sync()
                with pytest.raises(RuntimeError):
                    resolver.get_pixels(1)

        loop.run_until_complete(run())
