"""Native C++ engine: build, codec round-trips, and pipeline parity."""

import os
import zlib

import numpy as np
import pytest

from omero_ms_pixel_buffer_tpu.runtime.native import get_engine

engine = get_engine()
pytestmark = pytest.mark.skipif(
    engine is None, reason="native toolchain unavailable"
)


def test_engine_loads():
    assert engine.version >= 1
    assert engine.pool_size >= 1


def test_deflate_batch_roundtrip():
    rng = np.random.default_rng(0)
    payloads = [
        rng.integers(0, 255, size, dtype=np.uint8).tobytes()
        for size in (1, 100, 65536, 7)
    ]
    outs = engine.deflate_batch(payloads, level=6)
    assert all(o is not None for o in outs)
    for original, compressed in zip(payloads, outs):
        assert zlib.decompress(compressed) == original


def test_inflate_batch_matches_zlib():
    rng = np.random.default_rng(1)
    raws = [rng.integers(0, 64, size, dtype=np.uint8).tobytes()
            for size in (10, 4096, 100_000)]
    comp = [zlib.compress(r, 5) for r in raws]
    outs = engine.inflate_batch(comp, [len(r) for r in raws])
    for original, arr in zip(raws, outs):
        assert arr is not None
        assert arr.tobytes() == original


def test_inflate_corrupt_lane_is_none():
    good = zlib.compress(b"hello world")
    outs = engine.inflate_batch([good, b"not a zlib stream"], [11, 64])
    assert outs[0] is not None and outs[0].tobytes() == b"hello world"
    assert outs[1] is None


def test_png_assemble_matches_python_path():
    from omero_ms_pixel_buffer_tpu.ops.png import (
        decode_png,
        encode_png,
        filter_rows_np,
    )
    from omero_ms_pixel_buffer_tpu.ops.convert import to_big_endian_bytes_np

    rng = np.random.default_rng(2)
    tiles = [
        rng.integers(0, 60000, (32, 48), dtype=np.uint16),
        rng.integers(0, 255, (16, 16), dtype=np.uint8),
    ]
    payloads, widths, heights, depths = [], [], [], []
    for t in tiles:
        rows = to_big_endian_bytes_np(t)
        payloads.append(filter_rows_np(rows, t.dtype.itemsize, "up").tobytes())
        heights.append(t.shape[0])
        widths.append(t.shape[1])
        depths.append(t.dtype.itemsize * 8)
    outs = engine.png_assemble_batch(
        payloads, widths, heights, depths, [0, 0], level=6
    )
    for t, png in zip(tiles, outs):
        assert png is not None
        assert png[:8] == b"\x89PNG\r\n\x1a\n"
        decoded = decode_png(png)
        np.testing.assert_array_equal(decoded, t)
        # native stream should decode identically to the python encoder's
        ref = decode_png(encode_png(t, filter_mode="up"))
        np.testing.assert_array_equal(decoded, ref)


def test_png_chunk_crcs_are_strict():
    """Every chunk CRC must validate (zlib crc32(nullptr,0) pitfall):
    strict decoders reject bad critical-chunk CRCs."""
    import struct

    png = engine.png_assemble_batch([b"\x00\xaa"], [1], [1], [8], [0])[0]
    assert png.endswith(b"IEND\xaeB`\x82")  # spec CRC for empty IEND
    pos = 8
    while pos < len(png):
        (length,) = struct.unpack(">I", png[pos : pos + 4])
        tag = png[pos + 4 : pos + 8]
        body = png[pos + 8 : pos + 8 + length]
        (crc,) = struct.unpack(
            ">I", png[pos + 8 + length : pos + 12 + length]
        )
        assert crc == (zlib.crc32(body, zlib.crc32(tag)) & 0xFFFFFFFF), tag
        pos += 12 + length


def test_corrupt_block_degrades_per_lane(tmp_path):
    """One corrupt compressed block must only fail the lanes touching
    it, not the whole coalesced batch."""
    from omero_ms_pixel_buffer_tpu.io.ometiff import (
        OmeTiffPixelBuffer,
        write_ome_tiff,
    )

    rng = np.random.default_rng(9)
    data = rng.integers(0, 60000, (1, 1, 1, 256, 256), dtype=np.uint16)
    path = str(tmp_path / "c.ome.tiff")
    write_ome_tiff(path, data, tile_size=(128, 128), compression="zlib")
    buf = OmeTiffPixelBuffer(path, image_id=1)
    # corrupt the block holding (x=128..256, y=128..256)
    reader = buf._reader_for(0, 0, 0, 128, 128, 128, 128, 0)
    (bi,) = reader.plan_region(128, 128, 128, 128)
    off, cnt, _ = reader.block_span(bi)
    with open(path, "r+b") as f:
        f.seek(off)
        f.write(b"\xde\xad\xbe\xef")
    buf.close()
    buf = OmeTiffPixelBuffer(path, image_id=1)
    out = buf.read_tiles(
        [(0, 0, 0, 0, 0, 128, 128), (0, 0, 0, 128, 128, 128, 128)]
    )
    np.testing.assert_array_equal(out[0], data[0, 0, 0, :128, :128])
    assert out[1] is None
    buf.close()


def test_batched_tiff_read_uses_native_inflate(tmp_path):
    """read_tiles over a zlib OME-TIFF: native batched decode must equal
    per-tile reads, across planes (Z) and partial overlaps."""
    from omero_ms_pixel_buffer_tpu.io.ometiff import (
        OmeTiffPixelBuffer,
        write_ome_tiff,
    )

    rng = np.random.default_rng(3)
    data = rng.integers(0, 60000, (1, 2, 3, 300, 400), dtype=np.uint16)
    path = str(tmp_path / "z.ome.tiff")
    write_ome_tiff(path, data, tile_size=(128, 128), compression="zlib")
    buf = OmeTiffPixelBuffer(path, image_id=1)
    coords = [
        (0, 0, 0, 0, 0, 128, 128),
        (1, 1, 0, 64, 64, 200, 100),     # crosses block boundaries
        (2, 0, 0, 272, 172, 128, 128),   # right/bottom edge
        (0, 1, 0, 0, 0, 400, 300),       # full plane
    ]
    batch = buf.read_tiles(coords)
    for (z, c, t, x, y, w, h), got in zip(coords, batch):
        expect = data[t, c, z, y : y + h, x : x + w]
        np.testing.assert_array_equal(got, expect)
    buf.close()


def test_pipeline_batch_uses_native_png(tmp_path):
    """End-to-end handle_batch with the native engine: decoded pixels
    must match ground truth."""
    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
    from omero_ms_pixel_buffer_tpu.ops.png import decode_png
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

    rng = np.random.default_rng(4)
    data = rng.integers(0, 60000, (1, 1, 1, 256, 256), dtype=np.uint16)
    path = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(path, data, tile_size=(128, 128), compression="zlib")
    registry = ImageRegistry()
    registry.add(1, path)
    pipe = TilePipeline(PixelsService(registry), use_device=True)
    ctxs = [
        TileCtx(image_id=1, z=0, c=0, t=0,
                region=RegionDef(x, y, 128, 128), format="png",
                omero_session_key="k")
        for (x, y) in [(0, 0), (128, 0), (0, 128), (128, 128)]
    ]
    outs = pipe.handle_batch(ctxs)
    for ctx, png in zip(ctxs, outs):
        assert png is not None
        decoded = decode_png(png)
        x, y = ctx.region.x, ctx.region.y
        np.testing.assert_array_equal(
            decoded, data[0, 0, 0, y : y + 128, x : x + 128]
        )


class TestFusedPngEncode:
    """ompb_png_encode_batch: byteswap + filter + deflate + framing in
    one native call must decode pixel-identically to the python
    encoder's output."""

    def _check(self, tiles, mode, strategy="rle"):
        from omero_ms_pixel_buffer_tpu.ops.png import decode_png, encode_png

        pngs = engine.png_encode_batch(
            tiles, filter_mode=mode, level=6, strategy=strategy
        )
        assert pngs is not None
        for t, png in zip(tiles, pngs):
            assert png is not None
            dec = decode_png(png)
            ref = decode_png(encode_png(t, filter_mode=mode, level=6))
            np.testing.assert_array_equal(dec, ref)

    def test_modes_and_shapes(self):
        rng = np.random.default_rng(7)
        tiles = [
            rng.integers(0, 60000, (37, 53), dtype=np.uint16),
            rng.integers(0, 255, (64, 64), dtype=np.uint8),
            rng.integers(0, 255, (16, 24, 3), dtype=np.uint8),  # RGB
            rng.integers(0, 60000, (256, 256), dtype=np.uint16),
        ]
        for mode in ("none", "sub", "up"):
            self._check(tiles, mode)

    def test_strategies(self):
        rng = np.random.default_rng(8)
        tiles = [rng.integers(0, 60000, (128, 128), dtype=np.uint16)]
        for strategy in ("default", "filtered", "huffman", "rle"):
            self._check(tiles, "up", strategy)

    def test_big_endian_input_normalized(self):
        from omero_ms_pixel_buffer_tpu.ops.png import decode_png

        rng = np.random.default_rng(9)
        t = rng.integers(0, 60000, (32, 40), dtype=np.uint16)
        png = engine.png_encode_batch([t.astype(">u2")], "up", 6)[0]
        np.testing.assert_array_equal(decode_png(png), t)

    def test_unsupported_inputs_fall_back_to_none(self):
        f32 = np.zeros((8, 8), np.float32)
        assert engine.png_encode_batch([f32], "up", 6) is None
        assert engine.png_encode_batch(
            [np.zeros((4, 4), np.uint8)], "paeth", 6
        ) is None  # fused path only does none/sub/up

    def test_empty_batch(self):
        assert engine.png_encode_batch([], "up", 6) == []


def test_rle_strategy_ratio_on_smooth_data():
    """The service default (up filter + RLE deflate) must compress
    smooth microscopy-like data at least as well as zlib level-6
    default-strategy while being the fast path."""
    from omero_ms_pixel_buffer_tpu.ops.png import encode_png

    rng = np.random.default_rng(11)
    yy, xx = np.mgrid[0:256, 0:256].astype(np.float32)
    base = 2000 + 1500 * np.sin(xx / 97.0) + 1500 * np.cos(yy / 131.0)
    tile = (base + rng.normal(0, 120, (256, 256))).clip(0, 65535)
    tile = tile.astype(np.uint16)
    rle = engine.png_encode_batch([tile], "up", 6, strategy="rle")[0]
    ref = encode_png(tile, filter_mode="up", level=6, strategy="default")
    assert len(rle) <= len(ref) * 1.05


class TestFastDeflate:
    """The in-house RLE+dynamic-Huffman deflate (strategy "fast"):
    every output must inflate (via zlib, the oracle) to the input."""

    def _roundtrip(self, payload: bytes):
        from omero_ms_pixel_buffer_tpu.ops.png import decode_png

        # drive through the png path: filtered scanlines == payload
        out = engine.png_assemble_batch(
            [payload], widths=[1], heights=[1], bit_depths=[8],
            color_types=[0], level=6, strategy="fast",
        )[0]
        assert out is not None
        # extract IDAT + inflate with zlib as the oracle
        import struct as _s

        pos, idat = 8, b""
        while pos < len(out):
            (length,) = _s.unpack(">I", out[pos : pos + 4])
            if out[pos + 4 : pos + 8] == b"IDAT":
                idat += out[pos + 8 : pos + 8 + length]
            pos += 12 + length
        assert zlib.decompress(idat) == payload

    def test_oracle_cases(self):
        rng = np.random.default_rng(21)
        cases = [
            b"\x00", bytes(4096), b"\x7f" * 1000, b"aaab", b"a",
            rng.integers(0, 256, 5000, dtype=np.uint8).tobytes(),
            rng.integers(0, 4, 9000, dtype=np.uint8).tobytes(),
            b"".join(
                bytes([int(rng.integers(0, 256))])
                * int(rng.integers(1, 300))
                for _ in range(40)
            ),
        ]
        for payload in cases:
            self._roundtrip(payload)

    def test_fast_encode_pixels_decode_exactly(self):
        from omero_ms_pixel_buffer_tpu.ops.png import decode_png

        rng = np.random.default_rng(22)
        tile = rng.integers(0, 60000, (96, 112), dtype=np.uint16)
        png = engine.png_encode_batch(
            [tile], filter_mode="up", level=6, strategy="fast"
        )[0]
        np.testing.assert_array_equal(decode_png(png), tile)

    def test_fast_ratio_competitive(self):
        rng = np.random.default_rng(23)
        yy, xx = np.mgrid[0:256, 0:256].astype(np.float32)
        smooth = 2000 + 1500 * np.sin(xx / 97.0) + 1500 * np.cos(yy / 131.0)
        tile = (smooth + rng.normal(0, 120, (256, 256))).clip(0, 65535)
        tile = tile.astype(np.uint16)
        fast = engine.png_encode_batch([tile], "up", 6, strategy="fast")[0]
        rle = engine.png_encode_batch([tile], "up", 6, strategy="rle")[0]
        assert len(fast) <= len(rle) * 1.02


class TestSimdLiteralPacker:
    """r12: the AVX2/NEON literal emit must be byte-identical to the
    scalar path (OMPB_NO_SIMD=1 forces scalar at runtime — the same
    binary, so the comparison pins the vector code, not the build)."""

    def _assemble(self, payloads, w, h):
        return engine.png_assemble_batch(
            payloads,
            widths=[w] * len(payloads), heights=[h] * len(payloads),
            bit_depths=[16] * len(payloads),
            color_types=[0] * len(payloads),
            level=6, strategy="fast",
        )

    def test_simd_and_scalar_streams_byte_identical(self, monkeypatch):
        rng = np.random.default_rng(23)
        w, h = 311, 200  # odd width: exercises the <8 literal tail
        row = 1 + w * 2
        payloads = []
        noisy = rng.integers(0, 256, h * row, dtype=np.uint8)
        payloads.append(noisy.tobytes())
        runny = np.repeat(
            rng.integers(0, 6, h * row, dtype=np.uint8), 3
        )[: h * row]
        payloads.append(runny.tobytes())
        payloads.append(bytes(h * row))  # all-zero: one giant run
        monkeypatch.delenv("OMPB_NO_SIMD", raising=False)
        simd = self._assemble(payloads, w, h)
        monkeypatch.setenv("OMPB_NO_SIMD", "1")
        scalar = self._assemble(payloads, w, h)
        assert all(s is not None for s in simd)
        for i, (a, b) in enumerate(zip(simd, scalar)):
            assert a == b, f"lane {i}: SIMD and scalar PNGs differ"

    def test_streams_decode_exact_with_simd(self, monkeypatch):
        import struct

        def idat(png):
            i, out = 8, b""
            while i < len(png):
                ln, typ = struct.unpack(">I4s", png[i : i + 8])
                if typ == b"IDAT":
                    out += png[i + 8 : i + 8 + ln]
                i += 12 + ln
            return out

        monkeypatch.delenv("OMPB_NO_SIMD", raising=False)
        rng = np.random.default_rng(29)
        w = h = 96
        payload = rng.integers(
            0, 256, h * (1 + w * 2), dtype=np.uint8
        ).tobytes()
        (png,) = self._assemble([payload], w, h)
        assert zlib.decompress(idat(png)) == payload
