"""Rendering engine suite (render/ package + the /render surface).

Covers: RenderSpec parsing (incl. malformed params -> 400 over HTTP),
LUT registry + ImageJ .lut round-trips, the engine against an
INDEPENDENT per-pixel float reference across a (window, gamma,
reverse, model) grid, z-projection correctness, the byte-identity
contract (fused device chain == host mirror == 8-way CPU-mesh
shard_map, and the numpy RLE stream == the device stream), cache-key
isolation between specs, and — under ``-m resilience`` — the
``render.engine`` chaos lane proving the host fallback serves
byte-identical tiles.
"""

import asyncio
import io
import os

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.errors import BadRequestError
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
    zlib_rle_batch,
    zlib_rle_np,
)
from omero_ms_pixel_buffer_tpu.ops.png import decode_png, frame_png
from omero_ms_pixel_buffer_tpu.render import engine as rengine
from omero_ms_pixel_buffer_tpu.render import projection
from omero_ms_pixel_buffer_tpu.render.luts import (
    LutRegistry,
    builtin_luts,
    load_imagej_lut,
    write_imagej_lut,
)
from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
    INJECTOR,
    always,
)
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

rng = np.random.default_rng(17)
AUTH = {"Cookie": "sessionid=ck"}

# (T, C, Z, Y, X) multi-channel fixture shared by the pipeline/HTTP
# tests (written per-test-dir by _write_fixture)
IMG = rng.integers(0, 4096, (1, 3, 4, 96, 128), dtype=np.uint16)


@pytest.fixture(autouse=True)
def _clean_chaos():
    INJECTOR.clear()
    yield
    INJECTOR.clear()
    BOARD.reset()


def _write_fixture(tmp_path):
    path = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(path, IMG, tile_size=(64, 64))
    registry = ImageRegistry()
    registry.add(1, path)
    return registry


def _ctx(spec, z=0, c=0, t=0, x=0, y=0, w=64, h=48, session="k"):
    return TileCtx(
        image_id=1, z=z, c=c, t=t, region=RegionDef(x, y, w, h),
        format=spec.format, omero_session_key=session, render=spec,
    )


# ---------------------------------------------------------------------------
# RenderSpec parsing
# ---------------------------------------------------------------------------


class TestRenderSpecParsing:
    def test_full_channel_dialect(self):
        spec = RenderSpec.from_params({
            "c": "1|100:600$FF0000,-2,3|0:4095$00FF00",
            "m": "c",
        })
        assert [ch.index for ch in spec.channels] == [0, 2]
        assert spec.channels[0].window == (100.0, 600.0)
        assert spec.channels[0].color == "FF0000"
        assert spec.channels[1].color == "00FF00"
        assert spec.model == "c" and spec.format == "png"

    def test_lut_suffix_and_negative_window(self):
        spec = RenderSpec.from_params({"c": "1|-100:200$fire"})
        assert spec.channels[0].lut == "fire"
        assert spec.channels[0].window == (-100.0, 200.0)

    def test_eight_digit_hex_is_color_not_lut(self):
        spec = RenderSpec.from_params({"c": "1$FF0000AA"})
        assert spec.channels[0].color == "FF0000"
        assert spec.channels[0].lut is None

    def test_maps_reverse_and_gamma(self):
        spec = RenderSpec.from_params({
            "c": "1,2",
            "maps": '[{"reverse": {"enabled": true}},'
                    ' {"quantization": {"family": "exponential",'
                    ' "coefficient": 1.5}}]',
        })
        assert spec.channels[0].reverse is True
        assert spec.channels[1].family == "exponential"
        assert spec.channels[1].coefficient == 1.5

    def test_defaults_from_path_channel(self):
        spec = RenderSpec.from_params({}, default_channel=2)
        assert [ch.index for ch in spec.channels] == [2]
        assert spec.channels[0].window is None

    def test_projection_parse(self):
        spec = RenderSpec.from_params({"p": "intmax|2:5"})
        assert (spec.projection, spec.proj_start, spec.proj_end) == (
            "intmax", 2, 5
        )
        spec2 = RenderSpec.from_params({"p": "intmean"})
        assert spec2.projection == "intmean"
        assert spec2.proj_start is None and spec2.proj_end is None

    def test_quality_and_format(self):
        spec = RenderSpec.from_params({"format": "jpg", "q": "0.75"})
        assert spec.format == "jpeg" and spec.quality == 75

    def test_signature_canonical_under_channel_order(self):
        a = RenderSpec.from_params({"c": "2|0:10$00FF00,1|0:20$FF0000"})
        b = RenderSpec.from_params({"c": "1|0:20$FF0000,2|0:10$00FF00"})
        assert a.signature() == b.signature()

    def test_signature_distinguishes_specs(self):
        base = RenderSpec.from_params({"c": "1|0:255$FF0000"})
        for other_params in (
            {"c": "1|0:254$FF0000"},
            {"c": "1|0:255$FF0001"},
            {"c": "1|0:255$FF0000", "m": "g"},
            {"c": "1|0:255$FF0000", "p": "intmax"},
            {"c": "1|0:255$FF0000",
             "maps": '[{"reverse": {"enabled": true}}]'},
        ):
            assert base.signature() != RenderSpec.from_params(
                other_params
            ).signature()

    def test_json_round_trip(self):
        spec = RenderSpec.from_params({
            "c": "1|5:99$cool-lut,3|0:10$0000FF",
            "m": "g", "p": "intmean|0:2", "format": "jpeg", "q": "0.5",
            "maps": '[{"reverse": {"enabled": true}}]',
        })
        again = RenderSpec.from_json(spec.to_json())
        assert again == spec
        assert again.signature() == spec.signature()

    @pytest.mark.parametrize("params", [
        {"c": "xx"},
        {"c": "0"},  # 1-based dialect: 0 is malformed
        {"c": "1|9:1"},  # min >= max
        {"c": "1,1"},  # duplicate
        {"c": "1", "maps": "{not json"},
        {"c": "1", "maps": '[{"quantization": {"family": "poly"}}]'},
        {"c": "1", "maps":
         '[{"quantization": {"coefficient": -1}}]'},
        {"m": "z"},
        {"p": "wat"},
        {"p": "intmax|5:2"},
        {"q": "2"},
        {"q": "0"},
        {"format": "bmp"},
        {"c": "-1,-2"},  # nothing active
    ])
    def test_malformed_raises_bad_request(self, params):
        with pytest.raises(BadRequestError):
            RenderSpec.from_params(params)

    def test_resolve_channels_validates_size_c(self):
        spec = RenderSpec.from_params({"c": "1,4"})
        with pytest.raises(ValueError):
            spec.resolve_channels(3)
        assert len(spec.resolve_channels(4)) == 2

    def test_z_range(self):
        spec = RenderSpec.from_params({"p": "intmax|1:9"})
        assert spec.z_range(0, 4) == [1, 2, 3]  # clipped to the stack
        plain = RenderSpec.from_params({})
        assert plain.z_range(2, 4) == [2]


# ---------------------------------------------------------------------------
# LUTs
# ---------------------------------------------------------------------------


class TestLuts:
    def test_builtins_present(self):
        reg = LutRegistry()
        for name in ("grey", "red", "green", "blue", "fire", "ice",
                     "spectrum"):
            assert name in reg
            assert reg.get(name).shape == (256, 3)

    def test_grey_is_identity_ramp(self):
        grey = builtin_luts()["grey"]
        np.testing.assert_array_equal(
            grey, np.stack([np.arange(256)] * 3, axis=1)
        )

    def test_lut_file_round_trip(self, tmp_path):
        table = rng.integers(0, 256, (256, 3), dtype=np.uint8)
        path = str(tmp_path / "custom.lut")
        write_imagej_lut(path, table)
        np.testing.assert_array_equal(load_imagej_lut(path), table)

    def test_icol_header_variant(self, tmp_path):
        table = rng.integers(0, 256, (256, 3), dtype=np.uint8)
        path = str(tmp_path / "nih.lut")
        with open(path, "wb") as f:
            f.write(b"ICOL" + bytes(28) + table.T.tobytes())
        np.testing.assert_array_equal(load_imagej_lut(path), table)

    def test_registry_loads_dir_case_insensitive(self, tmp_path):
        table = rng.integers(0, 256, (256, 3), dtype=np.uint8)
        write_imagej_lut(str(tmp_path / "Cool.lut"), table)
        with open(tmp_path / "bad.lut", "wb") as f:
            f.write(b"short")  # must be skipped, not fatal
        reg = LutRegistry(str(tmp_path))
        assert "cool" in reg and "COOL.lut" in reg
        np.testing.assert_array_equal(reg.get("cool.lut"), table)
        assert "bad" not in reg


# ---------------------------------------------------------------------------
# Engine vs an independent per-pixel float reference
# ---------------------------------------------------------------------------


def _reference_render(stack, specs, model="c"):
    """Straight per-pixel float implementation of the rendering model
    (window -> reverse -> gamma -> 8-bit level -> color ramp ->
    additive composite), independent of the engine's table approach.
    ``specs``: [(lo, hi, (r, g, b), reverse, gamma), ...]."""
    chans = range(1 if model == "g" else len(specs))
    out = np.zeros(stack.shape[1:] + (3,), np.int64)
    for pos in chans:
        lo, hi, color, reverse, gamma = specs[pos]
        x = np.clip(
            (stack[pos].astype(np.float64) - lo) / (hi - lo), 0.0, 1.0
        )
        if reverse:
            x = 1.0 - x
        if gamma != 1.0:
            x = np.power(x, gamma)
        level = np.floor(x * 255.0 + 0.5).astype(np.int64)
        col = (255, 255, 255) if model == "g" else color
        for k in range(3):
            out[..., k] += np.floor(
                level * col[k] / 255.0 + 0.5
            ).astype(np.int64)
    return np.minimum(out, 255).astype(np.uint8)


_GRID = [
    # (dtype, window, reverse, gamma, model)
    (np.uint8, (0, 255), False, 1.0, "c"),
    (np.uint8, (10, 200), False, 1.0, "c"),
    (np.uint8, (10, 200), True, 1.0, "c"),
    (np.uint8, (0, 255), False, 2.2, "c"),
    (np.uint16, (100, 4000), False, 1.0, "c"),
    (np.uint16, (100, 4000), True, 0.5, "c"),
    (np.uint16, (0, 65535), False, 1.0, "g"),
    (np.int16, (-500, 500), False, 1.0, "c"),
    (np.int16, (-500, 500), True, 1.5, "g"),
]


class TestEngineVsReference:
    @pytest.mark.parametrize("dtype,window,reverse,gamma,model", _GRID)
    def test_host_and_device_match_reference(
        self, dtype, window, reverse, gamma, model
    ):
        dtype = np.dtype(dtype)
        info = np.iinfo(dtype)
        stack = rng.integers(
            info.min, info.max + 1, (2, 24, 32), dtype=dtype
        )
        colors = [(255, 0, 0), (0, 255, 0)]
        maps = []
        for _ in range(2):
            entry = {}
            if reverse:
                entry["reverse"] = {"enabled": True}
            if gamma != 1.0:
                entry["quantization"] = {
                    "family": "exponential", "coefficient": gamma,
                }
            maps.append(entry)
        import json

        spec = RenderSpec.from_params({
            "c": f"1|{window[0]}:{window[1]}$FF0000,"
                 f"2|{window[0]}:{window[1]}$00FF00",
            "m": model,
            "maps": json.dumps(maps),
        })
        tables, luts = rengine.build_tables(spec, dtype, LutRegistry())
        ref = _reference_render(
            stack,
            [(window[0], window[1], colors[i], reverse, gamma)
             for i in range(2)],
            model=model,
        )
        stack_u = rengine.unsigned_view(stack)
        host = rengine.render_host(stack_u, tables, luts)
        np.testing.assert_array_equal(host, ref)
        device = np.asarray(
            rengine.render_batch(stack_u[None], tables, luts)
        )[0]
        np.testing.assert_array_equal(device, ref)

    def test_named_lut_applies(self):
        spec = RenderSpec.from_params({"c": "1|0:255$fire"})
        tables, luts = rengine.build_tables(
            spec, np.dtype(np.uint8), LutRegistry()
        )
        fire = builtin_luts()["fire"]
        stack = np.arange(256, dtype=np.uint8).reshape(1, 16, 16)
        out = rengine.render_host(stack, tables, luts)
        np.testing.assert_array_equal(
            out, fire[np.arange(256)].reshape(16, 16, 3)
        )

    def test_unrenderable_dtypes_rejected(self):
        spec = RenderSpec.from_params({"c": "1"})
        for dtype in (np.float32, np.uint32, np.int32):
            with pytest.raises(rengine.RenderError):
                rengine.build_tables(spec, np.dtype(dtype), LutRegistry())

    def test_unknown_lut_raises(self):
        spec = RenderSpec.from_params({"c": "1$nosuch"})
        with pytest.raises(rengine.RenderError):
            rengine.build_tables(
                spec, np.dtype(np.uint8), LutRegistry()
            )


# ---------------------------------------------------------------------------
# Projection
# ---------------------------------------------------------------------------


class TestProjection:
    @pytest.mark.parametrize("dtype", [np.uint8, np.uint16, np.int16])
    @pytest.mark.parametrize("mode", ["intmax", "intmean"])
    def test_device_matches_host_matches_numpy(self, dtype, mode):
        dtype = np.dtype(dtype)
        info = np.iinfo(dtype)
        stack = rng.integers(
            info.min, info.max + 1, (2, 5, 12, 16), dtype=dtype
        )
        host = projection.project(stack, mode, device=False)
        device = projection.project(stack, mode, device=True)
        np.testing.assert_array_equal(host, device)
        if mode == "intmax":
            np.testing.assert_array_equal(host, stack.max(axis=-3))
        else:
            np.testing.assert_array_equal(
                host,
                (stack.astype(np.int64).sum(axis=-3) // 5).astype(dtype),
            )
        assert host.dtype == dtype

    def test_single_plane_passthrough(self):
        stack = rng.integers(0, 255, (1, 1, 8, 8), dtype=np.uint8)
        np.testing.assert_array_equal(
            projection.project(stack, "intmean"), stack[:, 0]
        )

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError):
            projection.project(
                np.zeros((1, 2, 4, 4), np.uint8), "sum"
            )


# ---------------------------------------------------------------------------
# Byte pinning: device chain == host mirror == shard_map
# ---------------------------------------------------------------------------


class TestBytePinning:
    def test_numpy_stream_matches_device_stream(self):
        payloads = [
            np.zeros(400, np.uint8),
            rng.integers(0, 256, 513, dtype=np.uint8),
            np.repeat(
                rng.integers(0, 256, 7, dtype=np.uint8),
                rng.integers(1, 700, 7),
            ),
        ]
        for p in payloads:
            streams, lengths = zlib_rle_batch(p[None])
            dev = bytes(np.asarray(streams[0][: int(lengths[0])]))
            assert zlib_rle_np(p) == dev

    def test_fused_device_chain_matches_host_mirror(self):
        spec = RenderSpec.from_params(
            {"c": "1|0:4095$FF0000,2|0:4095$00FF00"}
        )
        tables, luts = rengine.build_tables(
            spec, np.dtype(np.uint16), LutRegistry()
        )
        planes = rng.integers(0, 4096, (3, 2, 24, 32), dtype=np.uint16)
        streams, lengths = rengine.fused_render_filter_deflate_batch(
            planes, tables, luts, 24, 1 + 32 * 3
        )
        for lane in range(3):
            dev_png = frame_png(
                bytes(np.asarray(streams[lane][: int(lengths[lane])])),
                32, 24, 8, 2,
            )
            host_png = rengine.render_png_host(
                planes[lane], tables, luts
            )
            assert dev_png == host_png
            np.testing.assert_array_equal(
                decode_png(dev_png),
                rengine.render_host(planes[lane], tables, luts),
            )

    def test_bucket_padding_never_leaks_into_real_bytes(self):
        spec = RenderSpec.from_params(
            {"c": "1|0:255$FF0000",
             "maps": '[{"reverse": {"enabled": true}}]'}
        )  # reverse: padded zeros render to 255 — the worst case
        tables, luts = rengine.build_tables(
            spec, np.dtype(np.uint8), LutRegistry()
        )
        plane = rng.integers(0, 256, (1, 1, 20, 28), dtype=np.uint8)
        padded = np.zeros((1, 1, 64, 64), np.uint8)
        padded[:, :, :20, :28] = plane
        s1, l1 = rengine.fused_render_filter_deflate_batch(
            padded, tables, luts, 20, 1 + 28 * 3
        )
        host = rengine.render_png_host(plane[0], tables, luts)
        assert frame_png(
            bytes(np.asarray(s1[0][: int(l1[0])])), 28, 20, 8, 2
        ) == host

    def test_eight_way_mesh_bytes_identical(self):
        import jax

        from omero_ms_pixel_buffer_tpu.parallel.mesh import make_mesh
        from omero_ms_pixel_buffer_tpu.parallel.sharding import (
            shard_batch,
            sharded_render_filter_deflate,
        )

        assert len(jax.devices()) == 8
        mesh = make_mesh(("data",))
        spec = RenderSpec.from_params(
            {"c": "1|50:3000$FF00FF,2|0:4095$ice"}
        )
        tables, luts = rengine.build_tables(
            spec, np.dtype(np.uint16), LutRegistry()
        )
        planes = rng.integers(0, 4096, (8, 2, 16, 24), dtype=np.uint16)
        single_s, single_l = rengine.fused_render_filter_deflate_batch(
            planes, tables, luts, 16, 1 + 24 * 3
        )
        import jax.numpy as jnp

        sharded = shard_batch(mesh, jnp.asarray(planes))
        mesh_s, mesh_l = sharded_render_filter_deflate(
            mesh, sharded, tables, luts, 16, 1 + 24 * 3
        )
        np.testing.assert_array_equal(
            np.asarray(single_l), np.asarray(mesh_l)
        )
        for lane in range(8):
            n = int(single_l[lane])
            assert bytes(np.asarray(mesh_s[lane][:n])) == bytes(
                np.asarray(single_s[lane][:n])
            )


# ---------------------------------------------------------------------------
# Pipeline integration: device dispatch vs host engine, projection,
# chaos fallback
# ---------------------------------------------------------------------------


class TestPipelineRender:
    @pytest.fixture
    def service(self, tmp_path):
        svc = PixelsService(_write_fixture(tmp_path))
        yield svc
        svc.close()

    def _spec(self):
        return RenderSpec.from_params(
            {"c": "1|0:4095$FF0000,2|0:4095$00FF00"}
        )

    def test_device_pipeline_matches_host_pipeline_bytes(self, service):
        spec = self._spec()
        host_pipe = TilePipeline(service, engine="host")
        dev_pipe = TilePipeline(
            service, engine="device", device_deflate=True
        )
        dev_pipe.mesh = None
        try:
            host_png = host_pipe.handle(_ctx(spec, z=1, x=8, y=4))
            dev_png = dev_pipe.handle_batch([_ctx(spec, z=1, x=8, y=4)])[0]
            assert host_png is not None and host_png == dev_png
            decoded = decode_png(host_png)
            tables, luts = rengine.build_tables(
                spec, np.dtype(np.uint16), LutRegistry()
            )
            ref = rengine.render_host(
                np.stack([
                    IMG[0, 0, 1, 4:52, 8:72], IMG[0, 1, 1, 4:52, 8:72]
                ]),
                tables, luts,
            )
            np.testing.assert_array_equal(decoded, ref)
        finally:
            host_pipe.close()
            dev_pipe.close()

    def test_mesh_pipeline_matches_host_bytes(self, service):
        """The 8-way CPU-mesh shard_map path through the FULL pipeline
        (dispatcher mesh lane) pins byte-identical to the host
        engine."""
        spec = self._spec()
        host_pipe = TilePipeline(service, engine="host")
        mesh_pipe = TilePipeline(
            service, engine="device", device_deflate=True
        )
        try:
            ctxs = [
                _ctx(spec, z=z, x=8 * z, y=4, session="k")
                for z in range(4)
            ]
            mesh_out = mesh_pipe.handle_batch(ctxs)
            assert mesh_pipe.last_mesh_dispatch is not None
            assert mesh_pipe.last_mesh_dispatch["executed"]
            host_out = [
                host_pipe.handle(_ctx(spec, z=z, x=8 * z, y=4))
                for z in range(4)
            ]
            assert mesh_out == host_out
        finally:
            host_pipe.close()
            mesh_pipe.close()

    def test_projection_through_pipeline(self, service):
        spec = RenderSpec.from_params(
            {"c": "1|0:4095$FF0000", "p": "intmax|0:3"}
        )
        pipe = TilePipeline(service, engine="host")
        try:
            png = pipe.handle(_ctx(spec, z=0, w=64, h=48))
            assert png is not None
            decoded = decode_png(png)
            tables, luts = rengine.build_tables(
                spec, np.dtype(np.uint16), LutRegistry()
            )
            projected = IMG[0, 0, :, :48, :64].max(axis=0)
            ref = rengine.render_host(projected[None], tables, luts)
            np.testing.assert_array_equal(decoded, ref)
        finally:
            pipe.close()

    def test_channel_out_of_range_is_none(self, service):
        spec = RenderSpec.from_params({"c": "7"})
        pipe = TilePipeline(service, engine="host")
        try:
            assert pipe.handle(_ctx(spec)) is None  # -> 404
        finally:
            pipe.close()

    def test_jpeg_lane(self, service):
        spec = RenderSpec.from_params(
            {"c": "1|0:4095$FF0000", "format": "jpeg", "q": "0.9"}
        )
        pipe = TilePipeline(service, engine="host")
        try:
            body = pipe.handle(_ctx(spec))
            assert body is not None and body[:2] == b"\xff\xd8"
            img = np.array(Image.open(io.BytesIO(body)))
            assert img.shape == (48, 64, 3)
        finally:
            pipe.close()

    def test_plane_cache_never_claims_render_lanes(self, service):
        """Regression: with the HBM plane path active (device engine,
        single chip, bucket-fitting region) a render lane must NOT be
        staged as a raw plane lane — a degraded spec must answer None
        (404), never a stale raw-tile PNG, and a good lane must carry
        RENDERED bytes."""
        pipe = TilePipeline(
            service, engine="device", buckets=(64,),
            use_plane_cache=True, device_deflate=False,
        )
        pipe.mesh = None
        host_pipe = TilePipeline(service, engine="host")
        try:
            bad = RenderSpec.from_params({"c": "7"})  # SizeC is 3
            good = self._spec()
            out = pipe.handle_batch([
                _ctx(bad, x=0, y=0, w=64, h=32),
                _ctx(good, x=0, y=0, w=64, h=32),
            ])
            assert out[0] is None  # -> 404, not raw bytes
            assert out[1] == host_pipe.handle(
                _ctx(good, x=0, y=0, w=64, h=32)
            )
        finally:
            pipe.close()
            host_pipe.close()

    def test_prefetch_predictions_carry_render_spec(self):
        """Regression: a /render pan warms RENDER cache keys (the
        spec rides every prediction), and its motion stream never
        mixes with a raw /tile stream over the same plane."""
        from omero_ms_pixel_buffer_tpu.cache.prefetch import (
            ViewportPrefetcher,
        )

        enqueued = []

        class _Admission:
            def has_headroom(self, fraction=0.5):
                return True

        pre = ViewportPrefetcher(
            lambda ctx, key: None, cache=None, admission=_Admission(),
            lookahead=1,
        )
        spec = self._spec()
        pre._enqueue = lambda origin, region, res: enqueued.append(
            (origin.render, region)
        )
        pre.observe(_ctx(spec, x=0, y=0, w=64, h=48))
        pre.observe(_ctx(spec, x=64, y=0, w=64, h=48))
        assert enqueued and all(r is spec for r, _ in enqueued)
        # and for real (no stubbed _enqueue): keys carry the signature
        pre2 = ViewportPrefetcher(
            lambda ctx, key: None, cache=None, admission=_Admission(),
            lookahead=1,
        )
        pre2.observe(_ctx(spec, x=0, y=0, w=64, h=48))
        pre2.observe(_ctx(spec, x=64, y=0, w=64, h=48))
        keys = [key for _, key in pre2._queue._queue]
        assert keys and all("render=" in key for key in keys)

    @pytest.mark.resilience
    def test_render_engine_fault_falls_back_byte_identical(self, service):
        """The chaos lane: render.engine down -> every lane serves
        from the host mirror, byte-identical to the device bytes."""
        spec = self._spec()
        pipe = TilePipeline(
            service, engine="device", device_deflate=True
        )
        pipe.mesh = None
        try:
            clean = pipe.handle_batch([_ctx(spec, z=2)])[0]
            assert clean is not None
            INJECTOR.install(
                "render.engine", always(RuntimeError("engine down"))
            )
            faulted = pipe.handle_batch([_ctx(spec, z=2)])[0]
            assert faulted == clean
            assert INJECTOR.calls("render.engine") >= 1
        finally:
            pipe.close()


# ---------------------------------------------------------------------------
# HTTP: /render end to end
# ---------------------------------------------------------------------------


async def _make_app(tmp_path, config_extra=None):
    registry = _write_fixture(tmp_path)
    raw = {
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 1.0}},
    }
    if config_extra:
        raw.update(config_extra)
    config = Config.from_dict(raw)
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=MemorySessionStore({"ck": "omero-key-1"}),
    )
    client = TestClient(
        TestServer(app_obj.make_app()), loop=asyncio.get_running_loop()
    )
    await client.start_server()
    return app_obj, client


class TestRenderHttp:
    async def test_end_to_end_rendered_png(self, tmp_path, loop):
        app_obj, client = await _make_app(tmp_path)
        try:
            r = await client.get(
                "/render/1/1/0/0?c=1|0:4095$FF0000,2|0:4095$00FF00"
                "&w=64&h=48", headers=AUTH,
            )
            assert r.status == 200
            assert r.headers["Content-Type"] == "image/png"
            assert "ETag" in r.headers
            body = await r.read()
            decoded = np.array(Image.open(io.BytesIO(body)))
            spec = RenderSpec.from_params(
                {"c": "1|0:4095$FF0000,2|0:4095$00FF00"}
            )
            tables, luts = rengine.build_tables(
                spec, np.dtype(np.uint16), LutRegistry()
            )
            ref = rengine.render_host(
                np.stack([IMG[0, 0, 1, :48, :64], IMG[0, 1, 1, :48, :64]]),
                tables, luts,
            )
            np.testing.assert_array_equal(decoded, ref)
        finally:
            await client.close()

    async def test_cache_key_isolation_between_specs(self, tmp_path, loop):
        app_obj, client = await _make_app(tmp_path)
        try:
            url_a = "/render/1/0/0/0?c=1|0:4095$FF0000&w=64&h=48"
            url_b = "/render/1/0/0/0?c=1|0:4095$00FF00&w=64&h=48"
            ra = await client.get(url_a, headers=AUTH)
            rb = await client.get(url_b, headers=AUTH)
            assert ra.headers["X-Cache"] == "miss"
            assert rb.headers["X-Cache"] == "miss"  # not A's entry
            body_a, body_b = await ra.read(), await rb.read()
            assert body_a != body_b
            assert ra.headers["ETag"] != rb.headers["ETag"]
            # replays hit their own entries
            ra2 = await client.get(url_a, headers=AUTH)
            assert ra2.headers["X-Cache"] == "hit"
            assert await ra2.read() == body_a
            # and a raw /tile of the same region is yet another entry
            rt = await client.get(
                "/tile/1/0/0/0?w=64&h=48&format=png", headers=AUTH
            )
            assert rt.status == 200
            assert await rt.read() != body_a
        finally:
            await client.close()

    async def test_conditional_get_304(self, tmp_path, loop):
        app_obj, client = await _make_app(tmp_path)
        try:
            url = "/render/1/0/0/0?c=1|0:4095$FF0000&w=32&h=32"
            r = await client.get(url, headers=AUTH)
            etag = r.headers["ETag"]
            r2 = await client.get(
                url, headers={**AUTH, "If-None-Match": etag}
            )
            assert r2.status == 304
        finally:
            await client.close()

    async def test_greyscale_and_projection_over_http(self, tmp_path, loop):
        app_obj, client = await _make_app(tmp_path)
        try:
            r = await client.get(
                "/render/1/0/1/0?m=g&p=intmean|0:3&w=32&h=32",
                headers=AUTH,
            )
            assert r.status == 200
            decoded = np.array(Image.open(io.BytesIO(await r.read())))
            projected = (
                IMG[0, 1, :, :32, :32].astype(np.int64).sum(axis=0) // 4
            ).astype(np.uint16)
            spec = RenderSpec.from_params(
                {"m": "g", "p": "intmean|0:3"}, default_channel=1
            )
            tables, luts = rengine.build_tables(
                spec, np.dtype(np.uint16), LutRegistry()
            )
            ref = rengine.render_host(projected[None], tables, luts)
            np.testing.assert_array_equal(decoded, ref)
        finally:
            await client.close()

    async def test_errors_over_http(self, tmp_path, loop):
        app_obj, client = await _make_app(tmp_path)
        try:
            for bad in (
                "c=1|9:1$FF0000", "c=zz", "m=q", "p=no", "q=7",
                "format=gif", "c=1$not-a-lut",
            ):
                r = await client.get(
                    f"/render/1/0/0/0?{bad}&w=32&h=32", headers=AUTH
                )
                assert r.status == 400, (bad, r.status)
            # channel out of range / unknown image -> 404
            r = await client.get(
                "/render/1/0/0/0?c=9&w=32&h=32", headers=AUTH
            )
            assert r.status == 404
            r = await client.get(
                "/render/77/0/0/0?w=32&h=32", headers=AUTH
            )
            assert r.status == 404
            # no cookie -> 403 (same auth gate as /tile)
            r = await client.get("/render/1/0/0/0?w=32&h=32")
            assert r.status == 403
        finally:
            await client.close()

    async def test_custom_lut_dir_over_http(self, tmp_path, loop):
        table = np.zeros((256, 3), np.uint8)
        table[:, 2] = np.arange(256)  # blue ramp
        lut_dir = tmp_path / "luts"
        lut_dir.mkdir()
        write_imagej_lut(str(lut_dir / "bluez.lut"), table)
        app_obj, client = await _make_app(
            tmp_path, {"render": {"lut-dir": str(lut_dir)}}
        )
        try:
            r = await client.get(
                "/render/1/0/0/0?c=1|0:4095$bluez.lut&w=32&h=32",
                headers=AUTH,
            )
            assert r.status == 200
            decoded = np.array(Image.open(io.BytesIO(await r.read())))
            assert decoded[..., 0].max() == 0  # red never set
            assert decoded[..., 2].max() > 0
        finally:
            await client.close()

    async def test_render_disabled_404(self, tmp_path, loop):
        app_obj, client = await _make_app(
            tmp_path, {"render": {"enabled": False}}
        )
        try:
            r = await client.get(
                "/render/1/0/0/0?w=32&h=32", headers=AUTH
            )
            # no GET route registered: aiohttp answers 405 (the
            # OPTIONS catch-all still matches the path) — either way,
            # the surface is off
            assert r.status in (404, 405)
            # /tile unaffected
            r2 = await client.get(
                "/tile/1/0/0/0?w=32&h=32", headers=AUTH
            )
            assert r2.status == 200
        finally:
            await client.close()

    async def test_healthz_render_snapshot(self, tmp_path, loop):
        app_obj, client = await _make_app(tmp_path)
        try:
            await client.get(
                "/render/1/0/0/0?w=32&h=32", headers=AUTH
            )
            body = await (await client.get("/healthz")).json()
            assert body["render"]["enabled"] is True
            assert body["render"]["specs_cached"] >= 1
            assert body["render"]["luts"] >= 10
            text = await (await client.get("/metrics")).text()
            assert "render_tiles_total" in text
        finally:
            await client.close()


class TestRenderConfig:
    def test_defaults(self):
        config = Config.from_dict({"session-store": {"type": "memory"}})
        assert config.render.enabled is True
        assert config.render.lut_dir is None
        assert config.render.jpeg_quality == 90
        assert config.mesh.probe_interval_ms == 0.0

    @pytest.mark.parametrize("block", [
        {"render": {"jpeg-quality": 0}},
        {"render": {"jpeg-quality": "xx"}},
        {"render": {"lut-dir": ""}},
        {"render": {"typo-key": 1}},
        {"mesh": {"probe-interval-ms": -5}},
        {"mesh": {"typo": 1}},
    ])
    def test_invalid_blocks_fail_at_startup(self, block):
        raw = {"session-store": {"type": "memory"}, **block}
        with pytest.raises(ConfigError):
            Config.from_dict(raw)
