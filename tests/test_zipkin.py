"""Zipkin v2 exporter: finished spans land at the configured sink as
compliant v2 JSON, batched off-thread, with a flush on close."""

import http.server
import json
import threading
import time

from omero_ms_pixel_buffer_tpu.utils.tracing import (
    Tracer,
    ZipkinReporter,
)


class ZipkinSink:
    """Minimal HTTP sink recording POSTed span batches."""

    def __init__(self):
        self.batches = []
        sink = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                sink.batches.append(json.loads(self.rfile.read(n)))
                self.send_response(202)
                self.end_headers()

            def log_message(self, *a):
                pass

        self.httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}/api/v2/spans"

    @property
    def spans(self):
        return [s for b in self.batches for s in b]

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_spans_exported_v2_shape():
    sink = ZipkinSink()
    try:
        tracer = Tracer(enabled=True, service_name="test-svc")
        tracer.reporter = ZipkinReporter(
            sink.url, "test-svc", flush_interval_s=0.05
        )
        with tracer.start_span("handle_get_tile") as root:
            root.tag("omero.session_key", "k123")
            with tracer.start_span("get_pixels"):
                pass
        tracer.reporter.close()
        spans = sink.spans
        assert len(spans) == 2
        by_name = {s["name"]: s for s in spans}
        root_doc = by_name["handle_get_tile"]
        child = by_name["get_pixels"]
        # v2 schema essentials
        assert root_doc["localEndpoint"]["serviceName"] == "test-svc"
        assert root_doc["tags"] == {"omero.session_key": "k123"}
        assert child["traceId"] == root_doc["traceId"]
        assert child["parentId"] == root_doc["id"]
        assert child["timestamp"] >= root_doc["timestamp"] > 1e15
        assert child["duration"] >= 1  # micros, never zero
    finally:
        sink.close()


def test_sink_down_never_blocks_serving():
    tracer = Tracer(enabled=True)
    # nothing listens on this port
    tracer.reporter = ZipkinReporter(
        "http://127.0.0.1:9/api/v2/spans", "svc", flush_interval_s=0.01
    )
    t0 = time.perf_counter()
    for _ in range(50):
        with tracer.start_span("s"):
            pass
    assert time.perf_counter() - t0 < 1.0  # report path is non-blocking
    tracer.reporter.close()


def test_configure_wiring():
    from omero_ms_pixel_buffer_tpu.utils import tracing

    sink = ZipkinSink()
    try:
        tracing.configure(enabled=True, log_spans=True, zipkin_url=sink.url)
        assert tracing.TRACER.reporter is not None
        assert not tracing.TRACER.log_spans  # zipkin overrides log reporter
        with tracing.TRACER.start_span("x"):
            pass
        tracing.TRACER.reporter.close()
        assert [s["name"] for s in sink.spans] == ["x"]
    finally:
        tracing.configure(enabled=True, log_spans=False)
        sink.close()
