"""Pixel I/O layer: Zarr/OME-NGFF, OME-TIFF (pyramidal, tiled,
compressed), ROMIO — fixture write -> reader round-trip, resolution
levels, bounds, and the pixels-service resolution path
(reference contracts: ome.io.nio.PixelBuffer getTileDirect /
setResolutionLevel, ZarrPixelsService, PixelsService.getPixelBuffer)."""

import json
import os

import numpy as np
import pytest

from omero_ms_pixel_buffer_tpu.io.ometiff import (
    OmeTiffPixelBuffer,
    write_ome_tiff,
)
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.io.romio import RomioPixelBuffer, write_romio
from omero_ms_pixel_buffer_tpu.io.pixel_buffer import PixelsMeta
from omero_ms_pixel_buffer_tpu.io.zarr import ZarrPixelBuffer, write_ngff

rng = np.random.default_rng(7)


def make_5d(t=1, c=2, z=3, y=100, x=120, dtype=np.uint16):
    if np.dtype(dtype).kind == "f":
        return rng.standard_normal((t, c, z, y, x)).astype(dtype)
    hi = min(np.iinfo(dtype).max, 60000)
    return rng.integers(0, hi, (t, c, z, y, x), dtype=dtype)


class TestZarr:
    @pytest.mark.parametrize("compressor", [None, "zlib", "gzip"])
    def test_roundtrip(self, tmp_path, compressor):
        data = make_5d()
        root = str(tmp_path / "img.zarr")
        write_ngff(root, data, chunks=(32, 32), compressor=compressor)
        buf = ZarrPixelBuffer(root)
        m = buf.meta
        assert (m.size_t, m.size_c, m.size_z, m.size_y, m.size_x) == data.shape
        assert m.pixels_type == "uint16"
        tile = buf.get_tile_at(0, z=1, c=1, t=0, x=10, y=20, w=50, h=40)
        np.testing.assert_array_equal(tile, data[0, 1, 1, 20:60, 10:60])

    def test_pyramid_levels(self, tmp_path):
        data = make_5d(z=1, c=1, y=128, x=128)
        root = str(tmp_path / "pyr.zarr")
        write_ngff(root, data, chunks=(32, 32), levels=3)
        buf = ZarrPixelBuffer(root)
        assert buf.resolution_levels == 3
        assert buf.level_size(0) == (128, 128)
        assert buf.level_size(1) == (64, 64)
        assert buf.level_size(2) == (32, 32)
        lvl1 = buf.get_tile_at(1, 0, 0, 0, 0, 0, 64, 64)
        np.testing.assert_array_equal(lvl1, data[0, 0, 0, ::2, ::2])
        # reference-shaped cursor API (TileRequestHandler.java:89-91)
        buf.set_resolution_level(2)
        np.testing.assert_array_equal(
            buf.get_tile(0, 0, 0, 0, 0, 32, 32), data[0, 0, 0, ::4, ::4]
        )

    def test_out_of_bounds_raises(self, tmp_path):
        data = make_5d(z=1, c=1)
        root = str(tmp_path / "b.zarr")
        write_ngff(root, data)
        buf = ZarrPixelBuffer(root)
        with pytest.raises(ValueError):
            buf.get_tile_at(0, 0, 0, 0, 100, 0, 50, 10)  # x+w > 120
        with pytest.raises(ValueError):
            buf.get_tile_at(0, 5, 0, 0, 0, 0, 10, 10)  # z out of range
        with pytest.raises(ValueError):
            buf.set_resolution_level(3)

    def test_batched_read_chunk_dedup(self, tmp_path):
        data = make_5d(z=4, c=1)
        root = str(tmp_path / "m.zarr")
        write_ngff(root, data, chunks=(64, 64))
        buf = ZarrPixelBuffer(root)
        coords = [(z, 0, 0, 8, 8, 48, 48) for z in range(4)]
        tiles = buf.read_tiles(coords)
        for z, tile in enumerate(tiles):
            np.testing.assert_array_equal(tile, data[0, 0, z, 8:56, 8:56])


class TestOmeTiff:
    @pytest.mark.parametrize("compression", [None, "zlib"])
    @pytest.mark.parametrize("big_endian", [True, False])
    def test_roundtrip(self, tmp_path, compression, big_endian):
        data = make_5d()
        path = str(tmp_path / "img.ome.tiff")
        write_ome_tiff(
            path, data, tile_size=(48, 48),
            compression=compression, big_endian=big_endian,
        )
        buf = OmeTiffPixelBuffer(path)
        m = buf.meta
        assert (m.size_t, m.size_c, m.size_z, m.size_y, m.size_x) == data.shape
        assert m.pixels_type == "uint16"
        tile = buf.get_tile_at(0, z=2, c=1, t=0, x=30, y=10, w=64, h=80)
        np.testing.assert_array_equal(tile, data[0, 1, 2, 10:90, 30:94])

    def test_stripped_layout(self, tmp_path):
        data = make_5d(c=1, z=1, dtype=np.uint8)
        path = str(tmp_path / "strips.ome.tiff")
        write_ome_tiff(path, data, tile_size=None)
        buf = OmeTiffPixelBuffer(path)
        tile = buf.get_tile_at(0, 0, 0, 0, 5, 7, 30, 20)
        np.testing.assert_array_equal(tile, data[0, 0, 0, 7:27, 5:35])

    def test_pyramid_subifds(self, tmp_path):
        data = make_5d(c=1, z=1, y=256, x=256)
        path = str(tmp_path / "pyr.ome.tiff")
        write_ome_tiff(path, data, tile_size=(64, 64), pyramid_levels=3)
        buf = OmeTiffPixelBuffer(path)
        assert buf.resolution_levels == 3
        assert buf.level_size(1) == (128, 128)
        lvl2 = buf.get_tile_at(2, 0, 0, 0, 0, 0, 64, 64)
        np.testing.assert_array_equal(lvl2, data[0, 0, 0, ::4, ::4])

    def test_plane_order_xyczt(self, tmp_path):
        data = make_5d(t=2, c=3, z=2, y=16, x=16)
        path = str(tmp_path / "planes.ome.tiff")
        write_ome_tiff(path, data, tile_size=None)
        buf = OmeTiffPixelBuffer(path)
        for t in range(2):
            for c in range(3):
                for z in range(2):
                    tile = buf.get_tile_at(0, z, c, t, 0, 0, 16, 16)
                    np.testing.assert_array_equal(tile, data[t, c, z])

    @pytest.mark.parametrize("dtype", [np.uint8, np.int16, np.float32])
    def test_dtypes(self, tmp_path, dtype):
        data = make_5d(c=1, z=1, dtype=dtype)
        path = str(tmp_path / "dt.ome.tiff")
        write_ome_tiff(path, data)
        buf = OmeTiffPixelBuffer(path)
        tile = buf.get_tile_at(0, 0, 0, 0, 0, 0, 120, 100)
        np.testing.assert_array_equal(tile, data[0, 0, 0])


class TestRomio:
    def test_roundtrip(self, tmp_path):
        data = make_5d(t=2, c=2, z=2, y=40, x=50)
        path = str(tmp_path / "42")
        write_romio(path, data)
        meta = PixelsMeta(
            image_id=42, size_x=50, size_y=40, size_z=2, size_c=2,
            size_t=2, pixels_type="uint16",
        )
        buf = RomioPixelBuffer(path, meta)
        tile = buf.get_tile_at(0, z=1, c=1, t=1, x=5, y=10, w=20, h=15)
        np.testing.assert_array_equal(tile, data[1, 1, 1, 10:25, 5:25])

    def test_size_mismatch_rejected(self, tmp_path):
        path = str(tmp_path / "bad")
        with open(path, "wb") as f:
            f.write(b"\x00" * 100)
        meta = PixelsMeta(
            image_id=1, size_x=50, size_y=40, size_z=1, size_c=1,
            size_t=1, pixels_type="uint16",
        )
        with pytest.raises(ValueError):
            RomioPixelBuffer(path, meta)


class TestPixelsService:
    def test_registry_resolution_and_cache(self, tmp_path):
        tiff_data = make_5d(c=1, z=1)
        zarr_data = make_5d(c=2, z=1, dtype=np.uint8)
        write_ome_tiff(str(tmp_path / "a.ome.tiff"), tiff_data)
        write_ngff(str(tmp_path / "b.zarr"), zarr_data)
        romio_data = make_5d(c=1, z=1, y=32, x=32)
        write_romio(str(tmp_path / "3"), romio_data)
        registry_doc = {
            "images": [
                {"id": 1, "path": "a.ome.tiff", "name": "a"},
                {"id": 2, "path": "b.zarr", "type": "zarr"},
                {"id": 3, "path": "3", "type": "romio", "sizeX": 32,
                 "sizeY": 32, "sizeZ": 1, "sizeC": 1, "sizeT": 1,
                 "pixelsType": "uint16"},
            ]
        }
        reg_path = str(tmp_path / "registry.json")
        with open(reg_path, "w") as f:
            json.dump(registry_doc, f)

        svc = PixelsService(ImageRegistry(reg_path))
        # metadata plane (getPixels contract: None for unknown image)
        assert svc.get_pixels(999) is None
        meta1 = svc.get_pixels(1)
        assert meta1.pixels_type == "uint16" and meta1.size_x == 120
        # buffer plane: correct reader per storage type
        b1 = svc.get_pixel_buffer(1)
        b2 = svc.get_pixel_buffer(2)
        b3 = svc.get_pixel_buffer(3)
        assert isinstance(b1, OmeTiffPixelBuffer)
        assert isinstance(b2, ZarrPixelBuffer)
        assert isinstance(b3, RomioPixelBuffer)
        np.testing.assert_array_equal(
            b1.get_tile_at(0, 0, 0, 0, 0, 0, 8, 8), tiff_data[0, 0, 0, :8, :8]
        )
        np.testing.assert_array_equal(
            b2.get_tile_at(0, 0, 1, 0, 0, 0, 8, 8), zarr_data[0, 1, 0, :8, :8]
        )
        # cache: same instance back
        assert svc.get_pixel_buffer(1) is b1
        assert svc.get_pixel_buffer(999) is None
        svc.close()


class TestBigTiff:
    """BigTIFF (magic 43, 64-bit offsets): whole-slide pyramids exceed
    classic TIFF's 4 GB address space."""

    def test_roundtrip_pyramidal(self, tmp_path):
        from omero_ms_pixel_buffer_tpu.io.ometiff import (
            OmeTiffPixelBuffer,
            write_ome_tiff,
        )

        rng = np.random.default_rng(51)
        data = rng.integers(0, 60000, (1, 2, 1, 200, 300), dtype=np.uint16)
        path = str(tmp_path / "big.ome.tiff")
        write_ome_tiff(
            path, data, tile_size=(128, 128), pyramid_levels=2,
            compression="zlib", bigtiff=True,
        )
        with open(path, "rb") as f:
            header = f.read(4)
        assert header[2:4] in (b"\x00+", b"+\x00")  # magic 43
        buf = OmeTiffPixelBuffer(path)
        assert buf.meta.size_c == 2
        assert buf.resolution_levels == 2
        tile = buf.get_tile_at(0, 0, 1, 0, 32, 16, 200, 100)
        np.testing.assert_array_equal(
            tile, data[0, 1, 0, 16:116, 32:232]
        )
        lvl = buf.get_tile_at(1, 0, 0, 0, 0, 0, 150, 100)
        np.testing.assert_array_equal(
            lvl, data[0, 0, 0, ::2, ::2][:100, :150]
        )
        buf.close()

    def test_pil_can_read_our_bigtiff(self, tmp_path):
        """Interop check: an independent decoder accepts the layout.
        Little-endian only — Pillow (<=12) detects BigTIFF via
        ``header[2] == 43``, which misses the spec-correct big-endian
        spelling ``MM\\x00\\x2b`` (our own reader handles both)."""
        from PIL import Image

        from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff

        rng = np.random.default_rng(52)
        data = rng.integers(0, 255, (1, 1, 1, 64, 80), dtype=np.uint8)
        path = str(tmp_path / "interop.ome.tiff")
        write_ome_tiff(
            path, data, tile_size=None, bigtiff=True, big_endian=False
        )
        img = np.array(Image.open(path))
        np.testing.assert_array_equal(img, data[0, 0, 0])


def test_corrupt_bigtiff_counts_raise_tifferror(tmp_path):
    """Hostile 64-bit counts must raise TiffError, never MemoryError
    or an allocation attempt."""
    import struct

    from omero_ms_pixel_buffer_tpu.io.ometiff import (
        OmeTiffPixelBuffer,
        TiffError,
    )

    # little-endian BigTIFF: one IFD at offset 16 with one entry whose
    # count claims 2^40 values
    buf = bytearray(b"II+\x00" + struct.pack("<HHQ", 8, 0, 16))
    buf += struct.pack("<Q", 1)  # one entry
    buf += struct.pack("<HHQQ", 256, 4, 1 << 40, 0)  # WIDTH, huge count
    buf += struct.pack("<Q", 0)  # next IFD
    path = tmp_path / "evil.tiff"
    path.write_bytes(bytes(buf))
    with pytest.raises((TiffError, ValueError)):
        OmeTiffPixelBuffer(str(path))

    # absurd entry count must not spin
    buf2 = bytearray(b"II+\x00" + struct.pack("<HHQ", 8, 0, 16))
    buf2 += struct.pack("<Q", 1 << 50)
    path2 = tmp_path / "evil2.tiff"
    path2.write_bytes(bytes(buf2))
    with pytest.raises((TiffError, ValueError)):
        OmeTiffPixelBuffer(str(path2))
