"""OME-NGFF over filesystem / HTTP / S3 stores with real-world codecs
(VERDICT r3 item 4): blosc(lz4|zstd) + bare zstd/lz4 chunks served
pixel-exact, s3:// URIs signed with SigV4 (verified server-side by the
fake S3), http:// hierarchies read directly, and the full HTTP tile
surface on top of a blosc NGFF image.
"""

import datetime
import functools
import io
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest
from PIL import Image

from omero_ms_pixel_buffer_tpu.io.stores import (
    FileStore,
    HTTPStore,
    S3Store,
    StoreError,
    make_store,
    sigv4_headers,
)
from omero_ms_pixel_buffer_tpu.io.zarr import (
    ZarrPixelBuffer,
    write_ngff,
)

from conftest import needs_zstd

rng = np.random.default_rng(67)
IMG = rng.integers(0, 60000, (1, 2, 2, 100, 120), dtype=np.uint16)

ACCESS_KEY = "AKIATEST12345"
SECRET_KEY = "testsecretkey/abc"


@pytest.fixture(scope="module")
def ngff_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("ngff")
    path = str(root / "img.zarr")
    write_ngff(path, IMG, chunks=(32, 32), levels=2,
               compressor="blosc-lz4")
    return path


def _serve_dir(root: str, handler_cls):
    server = ThreadingHTTPServer(
        ("127.0.0.1", 0), functools.partial(handler_cls, root)
    )
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server


class _DirHandler(BaseHTTPRequestHandler):
    def __init__(self, root, *args, **kwargs):
        self.root = root
        super().__init__(*args, **kwargs)

    def log_message(self, *a):  # quiet
        pass

    def _reply(self, code, body=b""):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        import os
        import urllib.parse

        rel = urllib.parse.unquote(self.path.lstrip("/"))
        if ".." in rel:
            return self._reply(400)
        path = os.path.join(self.root, rel)
        if not os.path.isfile(path):
            return self._reply(404)
        with open(path, "rb") as f:
            return self._reply(200, f.read())


class _FakeS3Handler(_DirHandler):
    """Path-style S3: /bucket/key. Verifies the SigV4 signature with
    the known secret — a wrong signature is a 403, proving the client
    signs correctly rather than the server ignoring auth. Honors
    (signed) Range headers with 206 answers, like real S3."""

    bucket = "test-bucket"

    def do_GET(self):
        auth = self.headers.get("Authorization", "")
        m = re.match(
            r"AWS4-HMAC-SHA256 Credential=([^/]+)/(\d+)/([^/]+)/s3/"
            r"aws4_request, SignedHeaders=([^,]+), Signature=([0-9a-f]+)",
            auth,
        )
        if not m:
            return self._reply(403, b"missing/invalid auth")
        access, _datestamp, region, signed, signature = m.groups()
        if access != ACCESS_KEY:
            return self._reply(403, b"unknown key")
        amz_date = self.headers.get("x-amz-date", "")
        now = datetime.datetime.strptime(
            amz_date, "%Y%m%dT%H%M%SZ"
        ).replace(tzinfo=datetime.timezone.utc)
        rng_header = self.headers.get("Range")
        extra = None
        if rng_header is not None:
            # a ranged GET must SIGN its Range header (the client
            # includes it in SignedHeaders; refuse unsigned ones)
            if "range" not in signed.split(";"):
                return self._reply(403, b"unsigned range header")
            extra = {"range": rng_header}
        expected = sigv4_headers(
            "GET", self.headers["Host"], self.path.split("?")[0],
            region, ACCESS_KEY, SECRET_KEY,
            payload_sha256=self.headers.get(
                "x-amz-content-sha256", ""
            ),
            now=now,
            extra_headers=extra,
        )["authorization"]
        if expected.rsplit("Signature=", 1)[1] != signature:
            return self._reply(403, b"bad signature")
        # strip the bucket segment, serve from the dir
        prefix = f"/{self.bucket}/"
        if not self.path.startswith(prefix):
            return self._reply(404)
        self.path = self.path[len(prefix) - 1 :]
        if rng_header is None:
            return super().do_GET()
        # serve the (verified-signed) range with a 206
        import os
        import urllib.parse

        rel = urllib.parse.unquote(self.path.lstrip("/"))
        path = os.path.join(self.root, rel)
        if ".." in rel or not os.path.isfile(path):
            return self._reply(404)
        with open(path, "rb") as f:
            data = f.read()
        spec = rng_header.split("=", 1)[1]
        if spec.startswith("-"):
            n = int(spec[1:])
            body = data[-n:] if n <= len(data) else data
        else:
            lo_s, _, hi_s = spec.partition("-")
            lo = int(lo_s)
            if lo >= len(data):
                return self._reply(416)
            hi = int(hi_s) + 1 if hi_s else len(data)
            body = data[lo:min(hi, len(data))]
        self.send_response(206)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class TestCodecMatrix:
    @pytest.mark.parametrize(
        "compressor",
        [
            "blosc-lz4",
            pytest.param("blosc-zstd", marks=needs_zstd),
            "blosc-zlib",
            pytest.param("zstd", marks=needs_zstd),
            "lz4",
            "zlib",
        ],
    )
    def test_pixel_exact(self, tmp_path, compressor):
        path = str(tmp_path / f"{compressor}.zarr")
        write_ngff(path, IMG, chunks=(48, 48), compressor=compressor)
        buf = ZarrPixelBuffer(path)
        tile = buf.get_tile_at(0, 1, 1, 0, 8, 16, 64, 48)
        np.testing.assert_array_equal(
            tile, IMG[0, 1, 1, 16 : 16 + 48, 8 : 8 + 64]
        )

    def test_pyramid_level_with_blosc(self, ngff_root):
        buf = ZarrPixelBuffer(ngff_root)
        assert buf.resolution_levels == 2
        tile = buf.get_tile_at(1, 0, 0, 0, 0, 0, 30, 20)
        np.testing.assert_array_equal(
            tile, IMG[0, 0, 0, ::2, ::2][:20, :30]
        )


class TestZarrV3:
    """Zarr v3 / NGFF 0.5: zarr.json metadata, c/-prefixed chunk keys,
    codec pipelines (bytes endian + gzip/zstd/blosc + crc32c)."""

    @pytest.mark.parametrize(
        "compressor",
        [
            None,
            "zlib",
            pytest.param("zstd", marks=needs_zstd),
            "blosc-lz4",
            pytest.param("blosc-zstd", marks=needs_zstd),
        ],
    )
    def test_pixel_exact(self, tmp_path, compressor):
        path = str(tmp_path / "v3.zarr")
        write_ngff(path, IMG, chunks=(48, 48), levels=2,
                   compressor=compressor, zarr_format=3)
        buf = ZarrPixelBuffer(path)
        tile = buf.get_tile_at(0, 1, 1, 0, 8, 16, 64, 48)
        np.testing.assert_array_equal(
            tile, IMG[0, 1, 1, 16 : 16 + 48, 8 : 8 + 64]
        )
        assert buf.resolution_levels == 2
        lv = buf.get_tile_at(1, 0, 0, 0, 0, 0, 30, 20)
        np.testing.assert_array_equal(
            lv, IMG[0, 0, 0, ::2, ::2][:20, :30]
        )

    @needs_zstd
    def test_crc32c_detects_corruption(self, tmp_path):
        import os

        path = str(tmp_path / "v3.zarr")
        write_ngff(path, IMG, chunks=(48, 48), compressor="zstd",
                   zarr_format=3)
        chunk = os.path.join(path, "0", "c", "0", "0", "0", "0", "0")
        data = bytearray(open(chunk, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(chunk, "wb").write(bytes(data))
        buf = ZarrPixelBuffer(path)
        from omero_ms_pixel_buffer_tpu.io.zarr import ZarrError

        with pytest.raises(ZarrError):
            buf.levels[0].read_chunk((0, 0, 0, 0, 0))

    def test_missing_chunk_fill_value(self, tmp_path):
        import os
        import shutil

        path = str(tmp_path / "v3.zarr")
        write_ngff(path, IMG, chunks=(48, 48), zarr_format=3)
        shutil.rmtree(os.path.join(path, "0", "c", "0", "1"))
        buf = ZarrPixelBuffer(path)
        tile = buf.get_tile_at(0, 0, 1, 0, 0, 0, 40, 40)
        np.testing.assert_array_equal(tile, np.zeros((40, 40), IMG.dtype))

    def test_v2_key_encoding_default_separator(self, tmp_path):
        # the v2 chunk-key encoding's spec default separator is "."
        # (the default encoding's is "/") — a mixup reads every chunk
        # as absent and silently serves blank tiles
        import json as _json
        import os

        from omero_ms_pixel_buffer_tpu.io.zarr import ZarrArray

        path = str(tmp_path / "v2keys")
        os.makedirs(path)
        meta = {
            "zarr_format": 3, "node_type": "array", "shape": [4, 4],
            "data_type": "uint8",
            "chunk_grid": {"name": "regular",
                           "configuration": {"chunk_shape": [4, 4]}},
            "chunk_key_encoding": {"name": "v2"},  # no configuration
            "fill_value": 0,
            "codecs": [{"name": "bytes",
                        "configuration": {"endian": "little"}}],
        }
        _json.dump(meta, open(os.path.join(path, "zarr.json"), "w"))
        payload = bytes(range(16))
        open(os.path.join(path, "0.0"), "wb").write(payload)
        arr = ZarrArray(path)
        chunk = arr.read_chunk((0, 0))
        np.testing.assert_array_equal(
            chunk, np.frombuffer(payload, np.uint8).reshape(4, 4)
        )

    def test_hex_fill_value(self, tmp_path):
        import json as _json
        import os

        from omero_ms_pixel_buffer_tpu.io.zarr import ZarrArray

        path = str(tmp_path / "hexfill")
        os.makedirs(path)
        meta = {
            "zarr_format": 3, "node_type": "array", "shape": [4, 4],
            "data_type": "float32",
            "chunk_grid": {"name": "regular",
                           "configuration": {"chunk_shape": [4, 4]}},
            "chunk_key_encoding": {"name": "default"},
            "fill_value": "0x7fc00000",  # raw-bits NaN
            "codecs": [{"name": "bytes",
                        "configuration": {"endian": "little"}}],
        }
        _json.dump(meta, open(os.path.join(path, "zarr.json"), "w"))
        arr = ZarrArray(path)
        assert np.isnan(arr.fill_value)
        region = arr.read_region((0, 0), (4, 4))  # no chunk: all fill
        assert np.isnan(region).all()

    def test_sharding_rejected_clearly(self, tmp_path):
        import json as _json
        import os

        from omero_ms_pixel_buffer_tpu.io.zarr import ZarrArray, ZarrError

        path = str(tmp_path / "sharded")
        os.makedirs(path)
        meta = {
            "zarr_format": 3, "node_type": "array", "shape": [8, 8],
            "data_type": "uint8",
            "chunk_grid": {"name": "regular",
                           "configuration": {"chunk_shape": [8, 8]}},
            "chunk_key_encoding": {"name": "default"},
            "fill_value": 0,
            "codecs": [{"name": "sharding_indexed",
                        "configuration": {}}],
        }
        _json.dump(meta, open(os.path.join(path, "zarr.json"), "w"))
        with pytest.raises(ZarrError, match="shard"):
            ZarrArray(path)

    async def test_v3_served_over_http(self, tmp_path, loop):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_pixel_buffer_tpu.auth.stores import (
            MemorySessionStore,
        )
        from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            ImageRegistry,
            PixelsService,
        )
        from omero_ms_pixel_buffer_tpu.utils.config import Config

        path = str(tmp_path / "v3.zarr")
        write_ngff(path, IMG, chunks=(32, 32), compressor="blosc-lz4",
                   zarr_format=3)
        registry = ImageRegistry()
        registry.add(11, path, type="zarr")
        app_obj = PixelBufferApp(
            Config.from_dict({"session-store": {"type": "memory"}}),
            pixels_service=PixelsService(registry),
            session_store=MemorySessionStore({"ck": "key"}),
        )
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        await client.start_server()
        try:
            resp = await client.get(
                "/tile/11/0/1/0?x=10&y=20&w=80&h=60&format=png",
                headers={"Cookie": "sessionid=ck"},
            )
            assert resp.status == 200
            png = np.array(Image.open(io.BytesIO(await resp.read())))
            np.testing.assert_array_equal(
                png, IMG[0, 1, 0, 20:80, 10:90]
            )
        finally:
            await client.close()


class TestHttpStore:
    def test_reads_hierarchy(self, ngff_root):
        import os

        server = _serve_dir(os.path.dirname(ngff_root), _DirHandler)
        try:
            port = server.server_address[1]
            buf = ZarrPixelBuffer(
                f"http://127.0.0.1:{port}/img.zarr"
            )
            tile = buf.get_tile_at(0, 0, 1, 0, 40, 30, 50, 60)
            np.testing.assert_array_equal(
                tile, IMG[0, 1, 0, 30:90, 40:90]
            )
        finally:
            server.shutdown()

    def test_missing_key_is_none_5xx_raises(self, tmp_path):
        server = _serve_dir(str(tmp_path), _DirHandler)
        try:
            port = server.server_address[1]
            store = HTTPStore(f"http://127.0.0.1:{port}")
            assert store.get("nope") is None
        finally:
            server.shutdown()
        with pytest.raises(StoreError):
            HTTPStore("http://127.0.0.1:1/unreachable",
                      timeout_s=0.5).get("x")


class TestS3Store:
    @pytest.fixture
    def s3_env(self, ngff_root, monkeypatch):
        import os

        server = _serve_dir(os.path.dirname(ngff_root), _FakeS3Handler)
        port = server.server_address[1]
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", ACCESS_KEY)
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", SECRET_KEY)
        monkeypatch.setenv("AWS_REGION", "us-east-1")
        monkeypatch.setenv(
            "OMPB_S3_ENDPOINT", f"http://127.0.0.1:{port}"
        )
        yield
        server.shutdown()

    def test_signed_reads_pixel_exact(self, s3_env):
        buf = ZarrPixelBuffer("s3://test-bucket/img.zarr")
        tile = buf.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)
        np.testing.assert_array_equal(tile, IMG[0, 0, 0, :64, :64])

    def test_wrong_secret_rejected(self, s3_env, monkeypatch):
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "wrong")
        store = S3Store("s3://test-bucket/img.zarr")
        with pytest.raises(StoreError):
            store.get(".zattrs")

    def test_missing_chunk_fill_value(self, s3_env):
        store = S3Store("s3://test-bucket/img.zarr")
        assert store.get("0/9.9.9.9.9") is None

    def test_403_as_missing_knob(self, s3_env, monkeypatch):
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "wrong")
        monkeypatch.setenv("OMPB_S3_403_AS_MISSING", "1")
        store = S3Store("s3://test-bucket/img.zarr")
        # opted in: a 403 reads as an absent chunk (fill_value)
        assert store.get(".zgroup") is None

    def test_rotated_credentials_refresh_on_403(
        self, s3_env, monkeypatch
    ):
        # construct with stale creds, rotate the environment, and the
        # next read re-resolves + re-signs instead of failing forever
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "stale")
        store = S3Store("s3://test-bucket/img.zarr")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", SECRET_KEY)
        assert store.get(".zattrs") is not None
        assert store.secret_key == SECRET_KEY

    def test_uri_parse(self):
        s = S3Store("s3://bkt/a/b/c.zarr", endpoint="http://e")
        assert s.bucket == "bkt" and s.prefix == "a/b/c.zarr"
        with pytest.raises(ValueError):
            S3Store("http://not-s3")

    def test_ranged_get_refreshes_rotated_credentials(
        self, s3_env, monkeypatch
    ):
        # the sequential sharded path reads shard indexes through
        # get_range directly — it must run the same rotation protocol
        # as get(), not fail (or read fill_value) until restart
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "stale")
        store = S3Store("s3://test-bucket/img.zarr")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", SECRET_KEY)
        body = store.get_range(".zattrs", 0, 10)
        assert body is not None and len(body) == 10
        assert store.secret_key == SECRET_KEY
        whole = store.get(".zattrs")
        assert body == whole[:10]

    def test_signed_ranged_get(self, s3_env):
        # the fake REFUSES unsigned Range headers, so a passing slice
        # proves the Range header joined the SigV4 signature
        store = S3Store("s3://test-bucket/img.zarr")
        whole = store.get(".zattrs")
        assert store.get_range(".zattrs", 0, 10) == whole[:10]
        assert store.get_range(".zattrs", -7, 7) == whole[-7:]
        assert store.get_range("0/9.9.9.9.9", 0, 4) is None

    def test_sharded_ngff_over_s3(self, tmp_path, monkeypatch):
        import os

        sharded_dir = tmp_path / "s3root"
        sharded_dir.mkdir()
        write_ngff(
            str(sharded_dir / "img.zarr"), IMG, chunks=(32, 32),
            levels=1, zarr_format=3, compressor="zlib",
            shards=(64, 64),
        )
        server = _serve_dir(str(sharded_dir), _FakeS3Handler)
        port = server.server_address[1]
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", ACCESS_KEY)
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", SECRET_KEY)
        monkeypatch.setenv("AWS_REGION", "us-east-1")
        monkeypatch.setenv(
            "OMPB_S3_ENDPOINT", f"http://127.0.0.1:{port}"
        )
        try:
            buf = ZarrPixelBuffer("s3://test-bucket/img.zarr")
            tile = buf.get_tile_at(0, 0, 0, 0, 16, 16, 80, 70)
            np.testing.assert_array_equal(
                tile, IMG[0, 0, 0, 16:86, 16:96]
            )
        finally:
            server.shutdown()


class TestKeyValidation:
    """Hostile hierarchy metadata (NGFF dataset 'path' values) must not
    walk a store outside its root (ADVICE r4)."""

    def test_file_store_rejects_traversal(self, tmp_path):
        from omero_ms_pixel_buffer_tpu.io.stores import validate_key

        (tmp_path / "img").mkdir()
        store = FileStore(str(tmp_path / "img"))
        for key in ("../secret", "a/../../b", "/etc/passwd",
                    "c:\\win", "..\\up"):
            with pytest.raises(StoreError):
                store.get(key)
        # normal relative keys still pass, incl. POSIX-legal colons
        assert validate_key("0/.zarray") == "0/.zarray"
        assert validate_key("a..b/c") == "a..b/c"
        assert validate_key("0:1/.zarray") == "0:1/.zarray"

    def test_http_store_rejects_before_request(self):
        # port 1 is unreachable: rejection must happen before any GET
        store = HTTPStore("http://127.0.0.1:1", timeout_s=0.2)
        with pytest.raises(StoreError, match="traversal"):
            store.get("../secret")

    def test_s3_store_rejects_before_request(self):
        store = S3Store("s3://bkt/p", endpoint="http://127.0.0.1:1")
        with pytest.raises(StoreError, match="traversal"):
            store.get("../secret")


class TestSharedCredentials:
    def test_loaded_from_files(self, tmp_path, monkeypatch):
        from omero_ms_pixel_buffer_tpu.io.stores import (
            load_shared_credentials,
        )

        cred = tmp_path / "credentials"
        cred.write_text(
            "[default]\n"
            "aws_access_key_id = AKIAFILE\n"
            "aws_secret_access_key = filesecret\n"
            "[other]\n"
            "aws_access_key_id = AKIAOTHER\n"
            "aws_secret_access_key = othersecret\n"
            "aws_session_token = tok\n"
        )
        conf = tmp_path / "config"
        conf.write_text(
            "[default]\nregion = eu-west-1\n"
            "[profile other]\nregion = ap-south-1\n"
        )
        monkeypatch.setenv("AWS_SHARED_CREDENTIALS_FILE", str(cred))
        monkeypatch.setenv("AWS_CONFIG_FILE", str(conf))
        monkeypatch.delenv("AWS_PROFILE", raising=False)
        assert load_shared_credentials() == (
            "AKIAFILE", "filesecret", None, "eu-west-1"
        )
        assert load_shared_credentials("other") == (
            "AKIAOTHER", "othersecret", "tok", "ap-south-1"
        )

    def test_s3_store_picks_up_file_creds(self, tmp_path, monkeypatch):
        cred = tmp_path / "credentials"
        cred.write_text(
            "[default]\naws_access_key_id = AKIAFILE\n"
            "aws_secret_access_key = filesecret\n"
        )
        monkeypatch.setenv("AWS_SHARED_CREDENTIALS_FILE", str(cred))
        monkeypatch.setenv(
            "AWS_CONFIG_FILE", str(tmp_path / "missing-config")
        )
        monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
        monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
        store = S3Store("s3://b/k", endpoint="http://e")
        assert store.access_key == "AKIAFILE"
        assert store.secret_key == "filesecret"


class TestRetry:
    def test_transient_5xx_retries_then_succeeds(self, tmp_path):
        attempts = []

        class Flaky(_DirHandler):
            def do_GET(self):
                attempts.append(1)
                if len(attempts) <= 2:
                    return self._reply(503)
                return self._reply(200, b"payload")

        server = _serve_dir(str(tmp_path), Flaky)
        try:
            port = server.server_address[1]
            store = HTTPStore(f"http://127.0.0.1:{port}")
            assert store.get("whatever") == b"payload"
            assert len(attempts) == 3
        finally:
            server.shutdown()

    def test_4xx_never_retries(self, tmp_path):
        attempts = []

        class Denier(_DirHandler):
            def do_GET(self):
                attempts.append(1)
                return self._reply(404)

        server = _serve_dir(str(tmp_path), Denier)
        try:
            port = server.server_address[1]
            store = HTTPStore(f"http://127.0.0.1:{port}")
            assert store.get("missing") is None
            assert len(attempts) == 1
        finally:
            server.shutdown()


class TestMakeStore:
    def test_dispatch(self, tmp_path):
        assert isinstance(make_store(str(tmp_path)), FileStore)
        assert isinstance(make_store("http://x/y"), HTTPStore)
        assert isinstance(
            make_store("s3://b/k"), S3Store
        )


class TestEndToEndHttpServing:
    """A blosc-lz4 NGFF image through the complete tile surface
    (registry URI -> ZarrPixelBuffer -> pipeline -> HTTP)."""

    async def test_served_pixel_exact(self, ngff_root, loop):
        from aiohttp.test_utils import TestClient, TestServer

        from omero_ms_pixel_buffer_tpu.auth.stores import (
            MemorySessionStore,
        )
        from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            ImageRegistry,
            PixelsService,
        )
        from omero_ms_pixel_buffer_tpu.utils.config import Config

        registry = ImageRegistry()
        registry.add(7, ngff_root, type="zarr")
        app_obj = PixelBufferApp(
            Config.from_dict({"session-store": {"type": "memory"}}),
            pixels_service=PixelsService(registry),
            session_store=MemorySessionStore({"ck": "key"}),
        )
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        await client.start_server()
        try:
            resp = await client.get(
                "/tile/7/1/0/0?x=16&y=8&w=80&h=72&format=png",
                headers={"Cookie": "sessionid=ck"},
            )
            assert resp.status == 200
            png = await resp.read()
            decoded = np.array(Image.open(io.BytesIO(png)))
            np.testing.assert_array_equal(
                decoded, IMG[0, 0, 1, 8 : 8 + 72, 16 : 16 + 80]
            )
        finally:
            await client.close()
