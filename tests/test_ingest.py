"""Ingest plane (ingest/, r24): Zarr shard write/append while serving.

The contracts the write path must hold:

- **Write -> read byte identity**: the big-endian bytes a client PUTs
  come back exactly from the raw /tile surface, and every derived
  surface (render, DZI, IIIF, the pyramid levels) reflects the write
  the moment the response returns — no TTL wait, no restart.
- **Read-modify-write**: a tile write never clobbers neighboring
  pixels in partially-covered chunks, and untouched inner chunks of a
  rewritten shard carry over byte-for-byte (sentinels included, both
  ``index_location`` spellings).
- **Epoch ordering**: a read racing a commit sees fully-old or
  fully-new bytes, never a mix — commit publishes whole objects
  atomically and the epoch bump precedes every purge.
- **Torn-write chaos**: a fault at ``ingest.commit`` / ``ingest.index``
  aborts before anything becomes visible; concurrent readers keep
  serving the old bytes and the write surfaces a 5xx, not silence.
- **Stale-index-memo regression** (the r14 gap): the per-array shard
  index memo is epoch-keyed — after a commit, a reader holding the
  same open buffer misses its memo instead of serving pre-commit
  offsets, with the TTL clock frozen to prove TTL is uninvolved.
- **Scheduler pin**: writes acquire non-degradable, release without
  training the read EWMA, and never feed the sweep detector.
- **Cross-replica** (``-m resilience``): a write on replica A
  invalidates replica B's tiers via the epoch fan-out and lands as a
  delta frame on B's live channels.
"""

import asyncio
import json
import os
import shutil
import socket

import numpy as np
import pytest
from aiohttp import ClientSession, WSMsgType, web
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.ingest import (
    IngestError,
    IngestPlane,
    ShardAssembler,
)
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.io.zarr import (
    ZarrPixelBuffer,
    write_ngff,
)
from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
    INJECTOR,
    Fail,
    always,
    first_n,
)
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

rng = np.random.default_rng(24)
IMG = rng.integers(0, 4096, (1, 2, 2, 96, 128), dtype=np.uint16)
AUTH = {"Cookie": "sessionid=ck"}


@pytest.fixture(autouse=True)
def _clean_injector():
    INJECTOR.clear()
    yield
    INJECTOR.clear()


def _write_zarr(tmp_path, name="img.zarr", shards=(64, 64), levels=2):
    root = str(tmp_path / name)
    write_ngff(
        root, IMG, chunks=(32, 32), levels=levels, zarr_format=3,
        shards=shards,
    )
    return root


def _wire(arr2d):
    """A tile body in the ingest wire format: raw big-endian pixels —
    the same byte order the raw read surface serves, so PUT and GET
    bodies compare directly."""
    return np.asarray(arr2d).astype(">u2").tobytes()


async def _make_app(tmp_path, config_extra=None, registry=None,
                    image_path=None):
    if registry is None:
        registry = ImageRegistry()
        registry.add(1, image_path or _write_zarr(tmp_path))
    raw = {
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 1.0}},
        "ingest": {"enabled": True},
    }
    if config_extra:
        raw.update(config_extra)
    config = Config.from_dict(raw)
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=MemorySessionStore({"ck": "omero-key-1"}),
    )
    client = TestClient(
        TestServer(app_obj.make_app()), loop=asyncio.get_running_loop()
    )
    await client.start_server()
    return app_obj, client


async def _get_raw(client, image_id, z, c, t, x, y, w, h, extra=""):
    r = await client.get(
        f"/tile/{image_id}/{z}/{c}/{t}?x={x}&y={y}&w={w}&h={h}{extra}",
        headers=AUTH,
    )
    assert r.status == 200, (r.status, await r.text())
    return await r.read()


# ---------------------------------------------------------------------------
# config: the ingest: block
# ---------------------------------------------------------------------------

class TestIngestConfig:
    BASE = {"session-store": {"type": "memory"}}

    def test_defaults_off(self):
        cfg = Config.from_dict(dict(self.BASE))
        assert cfg.ingest.enabled is False
        assert cfg.ingest.max_inflight_shards == 64
        assert cfg.ingest.staging_bytes == 256 << 20

    def test_unknown_key_fails_startup(self):
        with pytest.raises(ConfigError, match="ingest"):
            Config.from_dict({
                **self.BASE, "ingest": {"enabled": True, "max-shards": 2},
            })

    def test_bad_values_fail(self):
        with pytest.raises(ConfigError):
            Config.from_dict({
                **self.BASE, "ingest": {"max-inflight-shards": "lots"},
            })
        with pytest.raises(ConfigError):
            Config.from_dict({
                **self.BASE, "ingest": {"staging-bytes": 0},
            })

    async def test_disabled_removes_routes(self, tmp_path):
        _app, client = await _make_app(
            tmp_path, config_extra={"ingest": {"enabled": False}}
        )
        try:
            # 405, not 404: the catch-all OPTIONS route owns every
            # unmatched path — either way, no write handler is bound
            r = await client.put(
                "/image/1/tile/0/0/0?x=0&y=0&w=32&h=32",
                data=b"\0" * 2048, headers=AUTH,
            )
            assert r.status in (404, 405)
            r = await client.post(
                "/image/1/planes?planes=0:0:0", data=b"\0",
                headers=AUTH,
            )
            assert r.status in (404, 405)
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# auth matrix
# ---------------------------------------------------------------------------

class _DenyWriteRegistry(ImageRegistry):
    """A metadata plane with a write surface that always refuses —
    the permission-scoped resolver shape (db/metadata can_write_image)
    without a database. The scoped ``get_pixels`` signature is what
    promotes it to the service's metadata plane."""

    def get_pixels(self, image_id, session_key=None):
        return super().get_pixels(image_id)

    def can_write_image(self, image_id, session_key):
        return False


class TestIngestAuth:
    async def test_unauthenticated_403(self, tmp_path):
        _app, client = await _make_app(tmp_path)
        try:
            r = await client.put(
                "/image/1/tile/0/0/0?x=0&y=0&w=32&h=32",
                data=_wire(np.zeros((32, 32), np.uint16)),
            )
            assert r.status == 403
            r = await client.post("/image/1/planes?planes=0:0:0", data=b"")
            assert r.status == 403
        finally:
            await client.close()

    async def test_write_denied_resolver_403(self, tmp_path):
        registry = _DenyWriteRegistry()
        registry.add(1, _write_zarr(tmp_path))
        _app, client = await _make_app(tmp_path, registry=registry)
        try:
            r = await client.put(
                "/image/1/tile/0/0/0?x=0&y=0&w=32&h=32",
                data=_wire(np.zeros((32, 32), np.uint16)), headers=AUTH,
            )
            assert r.status == 403
            assert "Cannot write" in await r.text()
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# write -> read byte identity
# ---------------------------------------------------------------------------

class TestWriteReadIdentity:
    async def test_put_bytes_equal_get_bytes(self, tmp_path):
        app_obj, client = await _make_app(tmp_path)
        try:
            tile = rng.integers(0, 4096, (40, 48), dtype=np.uint16)
            wire = _wire(tile)
            r = await client.put(
                "/image/1/tile/0/0/0?x=16&y=16&w=48&h=40",
                data=wire, headers=AUTH,
            )
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["tiles"] == 1 and body["objects"] >= 1
            back = await _get_raw(client, 1, 0, 0, 0, 16, 16, 48, 40)
            assert back == wire  # THE acceptance bytes
            # neighbors preserved (read-modify-write on shared chunks)
            north = await _get_raw(client, 1, 0, 0, 0, 0, 0, 128, 16)
            assert north == IMG[0, 0, 0, :16, :].astype(">u2").tobytes()
        finally:
            await client.close()

    async def test_pyramid_levels_follow_the_write(self, tmp_path):
        app_obj, client = await _make_app(tmp_path)
        try:
            tile = rng.integers(0, 4096, (40, 48), dtype=np.uint16)
            r = await client.put(
                "/image/1/tile/0/0/0?x=16&y=16&w=48&h=40",
                data=_wire(tile), headers=AUTH,
            )
            assert r.status == 200
            expect = IMG[0, 0, 0].copy()
            expect[16:56, 16:64] = tile
            got = np.frombuffer(
                await _get_raw(
                    client, 1, 0, 0, 0, 0, 0, 64, 48,
                    extra="&resolution=1",
                ),
                dtype=">u2",
            ).reshape(48, 64)
            # the same stride-2 law write_ngff uses for its pyramid
            assert np.array_equal(got, expect[::2, ::2])
        finally:
            await client.close()

    async def test_planes_batch_append(self, tmp_path):
        app_obj, client = await _make_app(tmp_path)
        try:
            planes = rng.integers(0, 4096, (2, 96, 128), dtype=np.uint16)
            r = await client.post(
                "/image/1/planes?planes=1:0:0,1:1:0",
                data=planes.astype(">u2").tobytes(), headers=AUTH,
            )
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["tiles"] == 2
            for c in (0, 1):
                got = await _get_raw(client, 1, 1, c, 0, 0, 0, 128, 96)
                assert got == planes[c].astype(">u2").tobytes()
        finally:
            await client.close()

    async def test_every_read_surface_serves_the_new_bytes(
        self, tmp_path
    ):
        """render + DZI + IIIF after a write: 200s with CHANGED bodies
        versus the pre-write responses — the caches did not serve the
        old rendering (no TTL involved; the test completes in far less
        than the default TTL)."""
        _app, client = await _make_app(tmp_path)
        try:
            urls = [
                "/tile/1/0/0/0?x=0&y=0&w=64&h=64&format=png",
                "/render/1/0/0/0?x=0&y=0&w=64&h=64",
                "/iiif/1/full/128,96/0/default.png",
            ]
            # DZI deepest level = full resolution
            r = await client.get("/dzi/1.dzi", headers=AUTH)
            assert r.status == 200
            urls.append("/dzi/1_files/7/0_0.png")
            before = {}
            for url in urls:
                r = await client.get(url, headers=AUTH)
                assert r.status == 200, (url, r.status, await r.text())
                before[url] = await r.read()
            tile = np.full((64, 64), 4095, dtype=np.uint16)
            r = await client.put(
                "/image/1/tile/0/0/0?x=0&y=0&w=64&h=64",
                data=_wire(tile), headers=AUTH,
            )
            assert r.status == 200
            for url in urls:
                r = await client.get(url, headers=AUTH)
                assert r.status == 200, (url, r.status)
                after = await r.read()
                assert after != before[url], url
        finally:
            await client.close()

    async def test_engines_serve_identical_written_bytes(self, tmp_path):
        """Two service processes — host engine and jax engine — over
        the store one of them wrote: raw readback is byte-identical."""
        root = _write_zarr(tmp_path)
        app_w, client_w = await _make_app(tmp_path, image_path=root)
        app_h, client_h = await _make_app(
            tmp_path, image_path=root,
            config_extra={"backend": {
                "engine": "host",
                "batching": {"coalesce-window-ms": 1.0},
            }},
        )
        try:
            tile = rng.integers(0, 4096, (64, 64), dtype=np.uint16)
            r = await client_w.put(
                "/image/1/tile/0/1/0?x=32&y=16&w=64&h=64",
                data=_wire(tile), headers=AUTH,
            )
            assert r.status == 200
            a = await _get_raw(client_w, 1, 0, 1, 0, 32, 16, 64, 64)
            b = await _get_raw(client_h, 1, 0, 1, 0, 32, 16, 64, 64)
            assert a == b == _wire(tile)
        finally:
            await client_w.close()
            await client_h.close()


# ---------------------------------------------------------------------------
# shard append edge cases
# ---------------------------------------------------------------------------

def _open_buffer(root):
    # cache_bytes=0: direct shard tests must observe the STORE, not a
    # per-instance decoded-chunk cache
    return ZarrPixelBuffer(root, image_id=1, cache_bytes=0)


class TestShardEdgeCases:
    def test_partial_edge_shard(self, tmp_path):
        """96x128 with 64x64 shards: the bottom and right shards are
        partial (out-of-grid inner positions must stay sentinels)."""
        root = _write_zarr(tmp_path)
        buf = _open_buffer(root)
        asm = ShardAssembler(buf)
        tile = rng.integers(0, 4096, (32, 64), dtype=np.uint16)
        # lands in the bottom-right partial shard
        asm.stage_tile(0, 0, 0, 64, 64, 64, 32, tile)
        asm.commit()
        buf2 = _open_buffer(root)
        got = buf2.get_tile_at(0, 0, 0, 0, 64, 64, 64, 32)
        assert np.array_equal(got, tile)
        # the rest of the plane is untouched
        full = buf2.get_tile_at(0, 0, 0, 0, 0, 0, 128, 96)
        expect = IMG[0, 0, 0].copy()
        expect[64:96, 64:128] = tile
        assert np.array_equal(full, expect)

    def test_sentinels_preserved_in_sparse_shard(self, tmp_path):
        """Writing ONE chunk of an otherwise-absent shard leaves every
        other index entry at the absent sentinel — a reader of those
        positions gets fill_value, not garbage offsets."""
        root = _write_zarr(tmp_path, levels=1)
        # wipe the chunk objects: all-absent array, metadata intact
        shutil.rmtree(os.path.join(root, "0", "c"))
        buf = _open_buffer(root)
        asm = ShardAssembler(buf)
        tile = rng.integers(0, 4096, (32, 32), dtype=np.uint16)
        asm.stage_tile(0, 0, 0, 0, 0, 32, 32, tile)
        asm.commit()
        buf2 = _open_buffer(root)
        got = buf2.get_tile_at(0, 0, 0, 0, 0, 0, 32, 32)
        assert np.array_equal(got, tile)
        # unwritten chunk inside the SAME shard: absent -> fill_value
        other = buf2.get_tile_at(0, 0, 0, 0, 32, 32, 32, 32)
        assert (other == buf2.levels[0].fill_value).all()

    def test_index_location_start_spelling(self, tmp_path):
        """A shard layout with the index at the FRONT: offsets are
        index-relative on disk; the assembler writes them the same way
        the reader parses them."""
        root = _write_zarr(tmp_path, levels=1)
        zmeta = os.path.join(root, "0", "zarr.json")
        doc = json.loads(open(zmeta).read())
        changed = False
        for codec in doc["codecs"]:
            if codec.get("name") == "sharding_indexed":
                codec["configuration"]["index_location"] = "start"
                changed = True
        assert changed
        open(zmeta, "w").write(json.dumps(doc))
        # the existing objects are end-spelled: drop them so the array
        # is empty under the new spelling
        shutil.rmtree(os.path.join(root, "0", "c"))
        buf = _open_buffer(root)
        assert buf.levels[0].sharding.index_at_end is False
        asm = ShardAssembler(buf)
        tile = rng.integers(0, 4096, (48, 80), dtype=np.uint16)
        asm.stage_tile(0, 1, 0, 16, 8, 80, 48, tile)
        asm.commit()
        # second write to the SAME shard must parse the start-spelled
        # index it just wrote (carry-over path)
        asm2 = ShardAssembler(_open_buffer(root))
        patch = rng.integers(0, 4096, (8, 8), dtype=np.uint16)
        asm2.stage_tile(0, 1, 0, 0, 0, 8, 8, patch)
        asm2.commit()
        got = _open_buffer(root).get_tile_at(0, 0, 1, 0, 16, 8, 80, 48)
        assert np.array_equal(got, tile)
        got2 = _open_buffer(root).get_tile_at(0, 0, 1, 0, 0, 0, 8, 8)
        assert np.array_equal(got2, patch)

    def test_unsharded_and_v2_arrays_write_too(self, tmp_path):
        root = str(tmp_path / "v2.zarr")
        write_ngff(root, IMG, chunks=(32, 32), levels=1, zarr_format=2)
        buf = _open_buffer(root)
        asm = ShardAssembler(buf)
        tile = rng.integers(0, 4096, (40, 40), dtype=np.uint16)
        asm.stage_tile(1, 0, 0, 24, 24, 40, 40, tile)
        asm.commit()
        got = _open_buffer(root).get_tile_at(0, 1, 0, 0, 24, 24, 40, 40)
        assert np.array_equal(got, tile)

    def test_non_zarr_image_409(self, tmp_path):
        path = str(tmp_path / "img.ome.tiff")
        write_ome_tiff(path, IMG, tile_size=(64, 64))
        registry = ImageRegistry()
        registry.add(1, path)
        plane = IngestPlane(PixelsService(registry))
        with pytest.raises(IngestError) as ei:
            plane.write_tiles(
                1, [(0, 0, 0, 0, 0, 8, 8, b"\0" * 128)]
            )
        assert ei.value.code == 409

    def test_staging_and_shard_bounds_413(self, tmp_path):
        root = _write_zarr(tmp_path)
        registry = ImageRegistry()
        registry.add(1, root)
        svc = PixelsService(registry)
        tiny = IngestPlane(svc, staging_bytes=1024)
        body = _wire(np.zeros((32, 32), np.uint16))
        with pytest.raises(IngestError) as ei:
            tiny.write_tiles(1, [(0, 0, 0, 0, 0, 32, 32, body)])
        assert ei.value.code == 413
        narrow = IngestPlane(svc, max_inflight_shards=1)
        wide = _wire(np.zeros((96, 128), np.uint16))
        with pytest.raises(IngestError) as ei:
            narrow.write_tiles(1, [(0, 0, 0, 0, 0, 128, 96, wide)])
        assert ei.value.code == 413


# ---------------------------------------------------------------------------
# request validation
# ---------------------------------------------------------------------------

class TestIngestValidation:
    async def test_client_errors(self, tmp_path):
        _app, client = await _make_app(tmp_path)
        try:
            cases = [
                # missing query params
                ("PUT", "/image/1/tile/0/0/0", b""),
                # out-of-bounds tile
                ("PUT", "/image/1/tile/0/0/0?x=100&y=0&w=64&h=64",
                 b"\0" * 8192),
                # body length mismatch
                ("PUT", "/image/1/tile/0/0/0?x=0&y=0&w=32&h=32",
                 b"\0" * 7),
                # out-of-bounds plane
                ("PUT", "/image/1/tile/9/0/0?x=0&y=0&w=32&h=32",
                 b"\0" * 2048),
                # malformed planes spec
                ("POST", "/image/1/planes?planes=zebra", b"\0" * 16),
                # body not divisible into the listed planes
                ("POST", "/image/1/planes?planes=0:0:0,1:0:0",
                 b"\0" * 7),
            ]
            for method, url, body in cases:
                r = await client.request(
                    method, url, data=body, headers=AUTH
                )
                assert r.status == 400, (url, r.status, await r.text())
            r = await client.put(
                "/image/99/tile/0/0/0?x=0&y=0&w=32&h=32",
                data=b"\0" * 2048, headers=AUTH,
            )
            assert r.status == 404
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# stale shard-index memo (the r14 gap, closed in r24)
# ---------------------------------------------------------------------------

class TestShardIndexMemoEpoch:
    def test_memo_is_epoch_keyed_with_frozen_clock(self, tmp_path):
        """TTL uninvolved by construction: the memo clock is frozen,
        so only the epoch stamp can explain the refresh."""
        root = _write_zarr(tmp_path, levels=1)
        reader = _open_buffer(root)
        arr = reader.levels[0]
        arr._shard_clock = lambda: 1000.0  # frozen: TTL never expires
        before = reader.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)
        assert np.array_equal(before, IMG[0, 0, 0, :64, :64])
        assert arr._shard_indexes  # footer memoized
        # a second process-side writer rewrites the shard
        writer = _open_buffer(root)
        asm = ShardAssembler(writer)
        tile = rng.integers(0, 4096, (64, 64), dtype=np.uint16)
        asm.stage_tile(0, 0, 0, 0, 0, 64, 64, tile)
        asm.commit()
        # the reader's open buffer: same memo, same frozen clock.
        # note_epoch purges exactly once per new epoch value.
        assert reader.note_epoch(7) > 0
        after = reader.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)
        assert np.array_equal(after, tile)
        assert reader.note_epoch(7) == 0  # same epoch: no re-purge

    def test_pixels_service_note_epoch_reaches_open_buffer(
        self, tmp_path
    ):
        root = _write_zarr(tmp_path, levels=1)
        registry = ImageRegistry()
        registry.add(1, root)
        svc = PixelsService(registry)
        buf = svc.get_pixel_buffer(1)
        for arr in buf.levels:
            arr._shard_clock = lambda: 1000.0
        buf.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)
        assert buf.levels[0]._shard_indexes
        svc.note_epoch(1, 3)
        assert not buf.levels[0]._shard_indexes
        # unknown image / closed buffer: silently a no-op
        svc.note_epoch(999, 3)

    async def test_http_write_purges_reader_memo(self, tmp_path):
        """End to end: a PUT through the service invalidates the open
        buffer the read path is already holding — the follow-up read
        serves the new bytes with the memo TTL frozen."""
        app_obj, client = await _make_app(tmp_path)
        try:
            old = await _get_raw(client, 1, 0, 0, 0, 0, 0, 64, 64)
            buf = app_obj.pixels_service.get_pixel_buffer(1)
            for arr in buf.levels:
                arr._shard_clock = lambda: 1000.0
            tile = rng.integers(0, 4096, (64, 64), dtype=np.uint16)
            r = await client.put(
                "/image/1/tile/0/0/0?x=0&y=0&w=64&h=64",
                data=_wire(tile), headers=AUTH,
            )
            assert r.status == 200
            new = await _get_raw(client, 1, 0, 0, 0, 0, 0, 64, 64)
            assert new == _wire(tile)
            assert new != old
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# scheduler pin
# ---------------------------------------------------------------------------

class TestSchedulerPin:
    async def test_writes_acquire_nondegradable_and_never_train(
        self, tmp_path
    ):
        app_obj, client = await _make_app(tmp_path)
        try:
            sched = app_obj.scheduler
            assert sched is not None
            acquired, released, observed = [], [], []
            real_acquire, real_release = sched.acquire, sched.release

            async def spy_acquire(priority, deadline, degradable=True):
                acquired.append(degradable)
                return await real_acquire(
                    priority, deadline, degradable=degradable
                )

            def spy_release(permit, train=True):
                released.append(train)
                return real_release(permit, train=train)

            sched.acquire, sched.release = spy_acquire, spy_release
            real_observe = app_obj.sweep_detector.observe
            app_obj.sweep_detector.observe = (
                lambda *a, **k: observed.append(a) or real_observe(*a, **k)
            )
            tile = _wire(np.zeros((32, 32), np.uint16))
            for i in range(4):
                r = await client.put(
                    f"/image/1/tile/0/0/0?x={i * 32}&y=0&w=32&h=32",
                    data=tile, headers=AUTH,
                )
                assert r.status == 200
            assert acquired == [False] * 4   # never degradable
            assert released == [False] * 4   # never trains the EWMA
            assert observed == []            # never a sweep sample
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# torn-write chaos + epoch ordering
# ---------------------------------------------------------------------------

class TestTornWriteChaos:
    async def test_commit_fault_serves_zero_mixed_reads(self, tmp_path):
        """A fault at the publish point: the write 503s and every
        byte of the image still reads as the ORIGINAL fixture — the
        fault fired before anything became visible."""
        _app, client = await _make_app(tmp_path)
        try:
            before = await _get_raw(client, 1, 0, 0, 0, 0, 0, 128, 96)
            INJECTOR.install(
                "ingest.commit", first_n(1, RuntimeError("disk died"))
            )
            tile = rng.integers(0, 4096, (64, 64), dtype=np.uint16)
            r = await client.put(
                "/image/1/tile/0/0/0?x=0&y=0&w=64&h=64",
                data=_wire(tile), headers=AUTH,
            )
            assert r.status == 503
            assert INJECTOR.calls("ingest.commit") == 1
            after = await _get_raw(client, 1, 0, 0, 0, 0, 0, 128, 96)
            assert after == before  # fully old — not one byte moved
            # healed: the same write lands
            r = await client.put(
                "/image/1/tile/0/0/0?x=0&y=0&w=64&h=64",
                data=_wire(tile), headers=AUTH,
            )
            assert r.status == 200
            got = await _get_raw(client, 1, 0, 0, 0, 0, 0, 64, 64)
            assert got == _wire(tile)
        finally:
            await client.close()

    async def test_index_fault_aborts_before_publish(self, tmp_path):
        _app, client = await _make_app(tmp_path)
        try:
            before = await _get_raw(client, 1, 0, 0, 0, 0, 0, 128, 96)
            INJECTOR.install(
                "ingest.index", always(RuntimeError("index torn"))
            )
            r = await client.put(
                "/image/1/tile/0/0/0?x=0&y=0&w=64&h=64",
                data=_wire(np.zeros((64, 64), np.uint16)), headers=AUTH,
            )
            assert r.status == 503
            after = await _get_raw(client, 1, 0, 0, 0, 0, 0, 128, 96)
            assert after == before
        finally:
            await client.close()

    async def test_reads_racing_commits_never_mix(self, tmp_path):
        """Epoch-ordering drive: a reader hammering one region while a
        writer alternates two known patterns — every read is entirely
        pattern A or entirely pattern B (or the original), never a
        blend. Chaos on every third commit keeps failed writes in the
        mix; they must read as the previous state."""
        _app, client = await _make_app(tmp_path)
        try:
            a = np.full((64, 64), 1111, dtype=np.uint16)
            b = np.full((64, 64), 2222, dtype=np.uint16)
            legal = {
                _wire(a), _wire(b),
                IMG[0, 0, 0, :64, :64].astype(">u2").tobytes(),
            }
            INJECTOR.install(
                "ingest.commit",
                lambda n: (
                    Fail(RuntimeError("chaos")) if n % 3 == 2 else None
                ),
            )
            stop = asyncio.Event()
            mixed = []

            async def reader():
                while not stop.is_set():
                    got = await _get_raw(
                        client, 1, 0, 0, 0, 0, 0, 64, 64
                    )
                    if got not in legal:
                        mixed.append(got)
                    await asyncio.sleep(0)

            task = asyncio.create_task(reader())
            for i in range(12):
                pattern = a if i % 2 == 0 else b
                r = await client.put(
                    "/image/1/tile/0/0/0?x=0&y=0&w=64&h=64",
                    data=_wire(pattern), headers=AUTH,
                )
                assert r.status in (200, 503)
            stop.set()
            await task
            assert mixed == []  # zero mixed-bytes reads
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# cross-replica: write on A, B invalidates + delta frame
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _boot_replica(img_path, members, self_url, port, l2_uri):
    registry = ImageRegistry()
    registry.add(1, img_path)
    config = Config.from_dict({
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 1.0}},
        "cache": {"prefetch": {"enabled": False}},
        "ingest": {"enabled": True},
        "cluster": {
            "members": members,
            "self": self_url,
            "peer-timeout-ms": 3000,
            "l2": {"uri": l2_uri},
        },
    })
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=MemorySessionStore({"ck": "omero-key-1"}),
    )
    runner = web.AppRunner(app_obj.make_app())
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    return app_obj, runner


class TestCrossReplica:
    @pytest.mark.resilience
    async def test_write_on_a_invalidates_b_and_pushes_delta(
        self, tmp_path
    ):
        """THE r24 acceptance drive: a tile written on replica A is
        served fresh by replica B immediately — B's RAM/L2 entries die
        via the epoch bump and purge fan-out, not a TTL — and a live
        channel held open on B receives the invalidation frame."""
        from omero_ms_pixel_buffer_tpu.cache.plane.resp_stub import (
            InMemoryRespServer,
        )

        img_path = _write_zarr(tmp_path)
        resp = InMemoryRespServer()
        await resp.start()
        ports = [_free_port() for _ in range(2)]
        members = [f"http://127.0.0.1:{p}" for p in ports]
        nodes = []
        for i, port in enumerate(ports):
            nodes.append(await _boot_replica(
                img_path, members, members[i], port, resp.uri
            ))
        try:
            (app_a, _), (app_b, _) = nodes
            url_a, url_b = members
            async with ClientSession() as http:
                # warm B's caches with the old bytes
                r = await http.get(
                    url_b + "/tile/1/0/0/0?x=0&y=0&w=64&h=64",
                    headers=AUTH,
                )
                assert r.status == 200
                old = await r.read()
                # hold a live channel on B
                ws = await asyncio.wait_for(
                    http.ws_connect(
                        url_b + "/session/1/live", headers=AUTH
                    ),
                    10,
                )
                hello = json.loads(
                    (await asyncio.wait_for(ws.receive(), 10)).data
                )
                assert hello["type"] == "hello"
                # write on A
                tile = np.full((64, 64), 3333, dtype=np.uint16)
                r = await http.put(
                    url_a + "/image/1/tile/0/0/0?x=0&y=0&w=64&h=64",
                    data=_wire(tile), headers=AUTH,
                )
                assert r.status == 200, await r.text()
                # the delta frame reaches B's channel as a push (the
                # ping interval and TTL are both far longer)
                frame = None
                for _ in range(10):
                    msg = await asyncio.wait_for(ws.receive(), 10)
                    assert msg.type == WSMsgType.TEXT
                    frame = json.loads(msg.data)
                    if frame.get("type") == "invalidate":
                        break
                assert frame is not None
                assert frame["type"] == "invalidate"
                assert frame["image"] == 1
                # B serves the NEW bytes now — no TTL wait
                deadline = asyncio.get_event_loop().time() + 10
                fresh = None
                while asyncio.get_event_loop().time() < deadline:
                    r = await http.get(
                        url_b + "/tile/1/0/0/0?x=0&y=0&w=64&h=64",
                        headers=AUTH,
                    )
                    assert r.status == 200
                    fresh = await r.read()
                    if fresh == _wire(tile):
                        break
                    await asyncio.sleep(0.1)
                assert fresh == _wire(tile)
                assert fresh != old
                await ws.close()
        finally:
            for _app, runner in nodes:
                await runner.cleanup()
            await resp.close()
