"""RGB PNG lanes on the device bucket path (VERDICT r3 item 6).

Whole-slide RGB pyramids (BASELINE config 4) deliver (h, w, 3) tiles;
the filter math is identical to grayscale with a 3-byte filter unit, so
RGB buckets must ride the accelerator (and the mesh) instead of always
falling back to the host engine. Pixel equality is the contract —
decoded via PIL against the source and against the host engine.
"""

import io

import numpy as np
import pytest
from PIL import Image

from omero_ms_pixel_buffer_tpu.io.pixel_buffer import (
    PixelBuffer,
    PixelsMeta,
)
from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

rng = np.random.default_rng(53)


class RgbPlaneBuffer(PixelBuffer):
    """An interleaved-RGB plane source (the shape whole-slide readers
    deliver when samples live inside the plane): tiles come back
    (h, w, 3) uint8."""

    def __init__(self, plane: np.ndarray, image_id: int = 1):
        h, w, s = plane.shape
        assert s == 3
        self.plane = plane
        self.samples = 3
        super().__init__(
            PixelsMeta(
                image_id=image_id, size_x=w, size_y=h,
                size_z=1, size_c=3, size_t=1,
                pixels_type="uint8", image_name="rgb",
            )
        )

    def get_tile_at(self, level, z, c, t, x, y, w, h):
        if level != 0:
            raise ValueError("single level")
        if x + w > self.plane.shape[1] or y + h > self.plane.shape[0]:
            raise ValueError("out of bounds")
        return self.plane[y : y + h, x : x + w]

    def read_tiles(self, coords, level=0):
        return [self.get_tile_at(level, *co) for co in coords]


class RgbService:
    def __init__(self, plane):
        self.buffer = RgbPlaneBuffer(plane)

    def get_pixels(self, image_id, session_key=None):
        return self.buffer.meta if image_id == 1 else None

    def get_pixel_buffer(self, image_id):
        return self.buffer if image_id == 1 else None


PLANE = rng.integers(0, 255, (300, 300, 3), dtype=np.uint8)


def _ctxs():
    return [
        TileCtx(image_id=1, z=0, c=0, t=0,
                region=RegionDef(x, y, w, h), format="png",
                omero_session_key="k")
        for x, y, w, h in [
            (0, 0, 64, 64), (64, 64, 64, 64),
            (128, 0, 100, 80),    # padded lane
            (0, 128, 256, 128),   # larger bucket
        ]
    ]


def _check(results):
    assert all(r is not None for r in results)
    for ctx, png in zip(_ctxs(), results):
        decoded = np.array(Image.open(io.BytesIO(png)))
        r = ctx.region
        np.testing.assert_array_equal(
            decoded, PLANE[r.y : r.y + r.height, r.x : r.x + r.width]
        )


class TestRgbDeviceLanes:
    @pytest.mark.parametrize("device_deflate", [False, True])
    def test_bucket_path_single_device(self, device_deflate):
        pipe = TilePipeline(
            RgbService(PLANE), engine="device",
            device_deflate=device_deflate,
        )
        pipe.mesh = None
        _check(pipe.handle_batch(_ctxs()))

    def test_bucket_path_rides_mesh(self):
        import jax

        assert len(jax.devices()) == 8
        pipe = TilePipeline(RgbService(PLANE), engine="device")
        assert pipe._get_mesh() is not None
        _check(pipe.handle_batch(_ctxs()))

    def test_rgb_lanes_are_device_lanes(self, monkeypatch):
        """The gate itself: RGB lanes must reach _device_png_lanes, not
        the host fallback."""
        pipe = TilePipeline(RgbService(PLANE), engine="device")
        pipe.mesh = None
        seen = {}
        orig = TilePipeline._device_png_lanes

        def spy(self, lanes, *a, **k):
            seen.setdefault("lanes", []).extend(lanes)
            return orig(self, lanes, *a, **k)

        monkeypatch.setattr(TilePipeline, "_device_png_lanes", spy)
        pipe.handle_batch(_ctxs())
        assert sorted(seen["lanes"]) == [0, 1, 2, 3]

    def test_matches_host_engine_pixels(self):
        dev = TilePipeline(RgbService(PLANE), engine="device")
        dev.mesh = None
        host = TilePipeline(RgbService(PLANE), engine="host")
        for d, h in zip(dev.handle_batch(_ctxs()),
                        host.handle_batch(_ctxs())):
            np.testing.assert_array_equal(
                np.array(Image.open(io.BytesIO(d))),
                np.array(Image.open(io.BytesIO(h))),
            )

    def test_rgb16_bucket_path(self):
        plane16 = rng.integers(
            0, 60000, (128, 128, 3), dtype=np.uint16
        )

        class Rgb16Buffer(RgbPlaneBuffer):
            def __init__(self, plane):
                PixelBuffer.__init__(
                    self,
                    PixelsMeta(
                        image_id=1, size_x=128, size_y=128,
                        size_z=1, size_c=3, size_t=1,
                        pixels_type="uint16",
                    ),
                )
                self.plane = plane
                self.samples = 3

        svc = RgbService.__new__(RgbService)
        svc.buffer = Rgb16Buffer(plane16)
        pipe = TilePipeline(svc, engine="device", device_deflate=True)
        pipe.mesh = None
        ctx = TileCtx(image_id=1, z=0, c=0, t=0,
                      region=RegionDef(0, 0, 100, 90), format="png",
                      omero_session_key="k")
        (png,) = pipe.handle_batch([ctx])
        # PIL truncates 16-bit-per-channel RGB to 8-bit; use the
        # package's own decoder for the golden comparison
        from omero_ms_pixel_buffer_tpu.ops.png import decode_png

        decoded = decode_png(png)
        np.testing.assert_array_equal(decoded, plane16[:90, :100])
