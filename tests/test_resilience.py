"""Chaos suite for the unified resilience layer.

Deterministic by construction: breaker transitions drive off injected
clocks, retry jitter off seeded RNGs, and outages off fault-injection
schedules that are pure functions of the call index
(resilience/faultinject.py) — the same seed reproduces the same outage
on every run. Covers the acceptance bar end to end: breakers trip and
recover via half-open probes, expired deadlines answer 504 without
occupying workers past their budget, overload sheds 503 + Retry-After
while admitted p50 stays bounded, /healthz + /metrics expose it all,
and a flapping Postgres cannot take down the healthy Zarr lane
(fault isolation, not global outage).
"""

import asyncio
import random
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.db.metadata import (
    OmeroPostgresMetadataResolver,
)
from omero_ms_pixel_buffer_tpu.errors import ServiceUnavailableError
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.io.stores import (
    HTTPStore,
    S3Store,
    StoreError,
    StoreUnavailableError,
)
from omero_ms_pixel_buffer_tpu.io.zarr import write_ngff
from omero_ms_pixel_buffer_tpu.resilience import (
    BOARD,
    INJECTOR,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    configure as configure_resilience,
    current_deadline,
    deadline_scope,
    retry_call,
    set_default_policy,
)
from omero_ms_pixel_buffer_tpu.resilience.breaker import BreakerOpenError
from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
    Latency,
    first_n,
    flap,
    latency,
    seeded,
)
from omero_ms_pixel_buffer_tpu.resilience.retry import DEFAULT_POLICY
from omero_ms_pixel_buffer_tpu.utils.config import Config

from test_postgres import FakePg, pixels_row

pytestmark = pytest.mark.resilience

rng = np.random.default_rng(11)
IMG = rng.integers(0, 60000, (1, 1, 1, 64, 64), dtype=np.uint16)

AUTH = {"Cookie": "sessionid=ck"}


@pytest.fixture(autouse=True)
def _clean_resilience():
    """Every test starts with chaos off and stock policy, and leaves
    it that way."""
    saved_policy = DEFAULT_POLICY
    yield
    INJECTOR.clear()
    BOARD.reset()  # breakers are held strongly, keyed by dependency
    BOARD.configure(enabled=True)
    set_default_policy(saved_policy)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# circuit breaker state machine
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def _breaker(self, clock, **kw):
        kw.setdefault("failure_threshold", 3)
        kw.setdefault("open_duration_s", 10.0)
        kw.setdefault("min_calls", 100)  # isolate consecutive rule
        return CircuitBreaker("dep", clock=clock, **kw)

    def test_opens_after_consecutive_failures(self):
        clock = FakeClock()
        b = self._breaker(clock)
        for _ in range(2):
            b.allow()
            b.record_failure()
        assert b.state == "closed"
        b.allow()
        b.record_failure()
        assert b.state == "open"
        with pytest.raises(BreakerOpenError) as ei:
            b.allow()
        assert ei.value.retry_after_s == pytest.approx(10.0)

    def test_success_resets_consecutive_count(self):
        clock = FakeClock()
        b = self._breaker(clock)
        for _ in range(2):
            b.allow()
            b.record_failure()
        b.allow()
        b.record_success()
        for _ in range(2):
            b.allow()
            b.record_failure()
        assert b.state == "closed"

    def test_half_open_probe_recovers(self):
        clock = FakeClock()
        b = self._breaker(clock)
        for _ in range(3):
            b.record_failure()
        assert b.state == "open"
        clock.advance(10.1)  # open duration elapses
        assert b.state == "half_open"
        b.allow()  # the probe is admitted
        b.record_success()
        assert b.state == "closed"
        b.allow()  # and traffic flows again

    def test_half_open_failure_reopens(self):
        clock = FakeClock()
        b = self._breaker(clock)
        for _ in range(3):
            b.record_failure()
        clock.advance(10.1)
        b.allow()
        b.record_failure()
        assert b.state == "open"
        with pytest.raises(BreakerOpenError):
            b.allow()
        # a second open period must elapse before the next probe
        clock.advance(10.1)
        b.allow()
        b.record_success()
        assert b.state == "closed"

    def test_half_open_bounds_concurrent_probes(self):
        clock = FakeClock()
        b = self._breaker(clock, half_open_probes=1)
        for _ in range(3):
            b.record_failure()
        clock.advance(10.1)
        b.allow()  # probe slot taken, outcome pending
        with pytest.raises(BreakerOpenError):
            b.allow()

    def test_abandoned_half_open_probe_self_heals(self):
        """A gated call can exit without reporting an outcome (caller
        cancelled, deadline expired first). The probe slot must not
        leak forever — after a full open period with no outcome a
        fresh probe is admitted."""
        clock = FakeClock()
        b = self._breaker(clock)
        for _ in range(3):
            b.record_failure()
        clock.advance(10.1)
        b.allow()  # probe admitted... and then abandoned
        with pytest.raises(BreakerOpenError):
            b.allow()  # slot taken, stale-window not yet elapsed
        clock.advance(10.1)  # full open period, probe never reported
        b.allow()  # self-heal: fresh probe admitted
        b.record_success()
        assert b.state == "closed"

    def test_failure_rate_window_trips(self):
        clock = FakeClock()
        b = CircuitBreaker(
            "dep", clock=clock, failure_threshold=100,
            failure_rate_threshold=0.5, window=10, min_calls=10,
            open_duration_s=10.0,
        )
        # alternate: 50% failures over the window, never consecutive
        for i in range(10):
            b.allow()
            (b.record_failure if i % 2 else b.record_success)()
        assert b.state == "open"

    def test_snapshot_shape(self):
        b = self._breaker(FakeClock())
        b.record_failure()
        snap = b.snapshot()
        assert snap["state"] == "closed"
        assert snap["consecutive_failures"] == 1
        assert {"window_failures", "window_slow", "rejected_total",
                "opened_total"} <= set(snap)


class TestSlowCallRule:
    """Latency-based trips (KNOWN_GAPS r6 closed): a dependency that
    answers correctly but at outage latency opens the breaker."""

    def _breaker(self, clock, **kw):
        kw.setdefault("failure_threshold", 100)  # isolate the rule
        kw.setdefault("slow_call_duration_s", 0.5)
        kw.setdefault("slow_call_rate_threshold", 0.5)
        kw.setdefault("window", 10)
        kw.setdefault("min_calls", 4)
        kw.setdefault("open_duration_s", 10.0)
        return CircuitBreaker("slowdep", clock=clock, **kw)

    def test_slow_successes_trip(self):
        b = self._breaker(FakeClock())
        for _ in range(4):
            b.allow()
            b.record_success(duration_s=1.0)  # 2x the threshold
        assert b.state == "open"
        assert b.snapshot()["window_slow"] == 4

    def test_fast_successes_stay_closed(self):
        b = self._breaker(FakeClock())
        for _ in range(20):
            b.allow()
            b.record_success(duration_s=0.01)
        assert b.state == "closed"

    def test_rate_below_threshold_stays_closed(self):
        b = self._breaker(FakeClock())
        # 1-in-4 slow: the windowed rate never reaches 0.5 at any
        # evaluation point (evaluations happen on slow outcomes once
        # min_calls outcomes exist)
        for i in range(12):
            b.allow()
            b.record_success(duration_s=1.0 if i % 4 == 0 else 0.01)
        assert b.state == "closed"

    def test_disabled_by_default(self):
        b = CircuitBreaker(
            "dep", clock=FakeClock(), failure_threshold=100,
            min_calls=2, window=4,
        )
        for _ in range(8):
            b.allow()
            b.record_success(duration_s=100.0)
        assert b.state == "closed"  # slow_call_duration_s=0: off

    def test_unmeasured_calls_never_count_slow(self):
        b = self._breaker(FakeClock())
        for _ in range(10):
            b.allow()
            b.record_success()  # call site doesn't time: no verdict
        assert b.state == "closed"

    def test_half_open_slow_probe_reopens(self):
        clock = FakeClock()
        b = self._breaker(clock)
        for _ in range(4):
            b.allow()
            b.record_success(duration_s=1.0)
        assert b.state == "open"
        clock.advance(10.1)
        b.allow()  # probe admitted
        b.record_success(duration_s=1.0)  # answered... at outage latency
        assert b.state == "open"  # NOT healed
        clock.advance(10.1)
        b.allow()
        b.record_success(duration_s=0.01)  # a genuinely fast probe
        assert b.state == "closed"

    def test_call_convenience_measures_duration(self):
        clock = FakeClock()
        b = self._breaker(clock, min_calls=2, window=4)

        def slow_fn():
            clock.advance(1.0)  # the call itself burns injected time
            return "ok"

        for _ in range(2):
            assert b.call(slow_fn) == "ok"
        assert b.state == "open"

    def test_store_get_trips_on_injected_latency(self):
        """End to end through the store wrapper: chaos latency on the
        injection point counts as dependency latency, and a uniformly
        slow store opens its breaker -> fail-fast
        StoreUnavailableError."""
        from omero_ms_pixel_buffer_tpu.io.stores import _get_with_retry

        INJECTOR.install("store.s3", latency(0.03))
        b = CircuitBreaker(
            "s3-slow", failure_threshold=100, min_calls=2, window=4,
            slow_call_duration_s=0.01, slow_call_rate_threshold=0.5,
        )
        for _ in range(2):
            status, _body = _get_with_retry(
                lambda: (200, b"chunk"), breaker=b, point="store.s3",
            )
            assert status == 200
        assert b.state == "open"
        with pytest.raises(StoreUnavailableError):
            _get_with_retry(
                lambda: (200, b"chunk"), breaker=b, point="store.s3",
            )

    def test_config_knobs_flow_to_board(self):
        config = Config.from_dict({
            "session-store": {"type": "memory"},
            "resilience": {"breaker": {
                "slow-call-duration-ms": 250,
                "slow-call-rate-threshold": 0.6,
            }},
        })
        assert config.resilience.breaker.slow_call_duration_ms == 250
        assert (
            config.resilience.breaker.slow_call_rate_threshold == 0.6
        )
        configure_resilience(config.resilience)
        b = BOARD.create("slow-configured")
        assert b.slow_call_duration_s == pytest.approx(0.25)
        assert b.slow_call_rate_threshold == pytest.approx(0.6)

    def test_config_rejects_bad_rate(self):
        from omero_ms_pixel_buffer_tpu.utils.config import ConfigError

        with pytest.raises(ConfigError):
            Config.from_dict({
                "session-store": {"type": "memory"},
                "resilience": {"breaker": {
                    "slow-call-rate-threshold": 1.5,
                }},
            })


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------


class TestDeadline:
    def test_remaining_expired_check(self):
        clock = FakeClock()
        d = Deadline.after(1.0, clock=clock)
        assert d.remaining() == pytest.approx(1.0)
        assert not d.expired
        clock.advance(0.6)
        assert d.remaining() == pytest.approx(0.4)
        clock.advance(0.5)
        assert d.expired and d.remaining() == 0.0
        with pytest.raises(DeadlineExceeded):
            d.check("unit")

    def test_cap_bounds_timeouts(self):
        clock = FakeClock()
        d = Deadline.after(2.0, clock=clock)
        assert d.cap(15.0) == pytest.approx(2.0)
        assert d.cap(0.5) == pytest.approx(0.5)
        assert d.cap(None) == pytest.approx(2.0)

    def test_json_round_trip_charges_transit(self):
        d = Deadline.after(5.0)
        d2 = Deadline.from_json(d.to_json())
        assert d2 is not None
        assert 0 < d2.remaining() <= 5.0
        assert Deadline.from_json(None) is None
        assert Deadline.from_json({}) is None

    def test_ambient_scope(self):
        assert current_deadline() is None
        d = Deadline.after(1.0)
        with deadline_scope(d):
            assert current_deadline() is d
        assert current_deadline() is None


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


class TestRetry:
    def test_deterministic_with_seed(self):
        def delays_for(seed):
            sleeps = []
            calls = {"n": 0}

            def fn():
                calls["n"] += 1
                if calls["n"] < 4:
                    raise IOError("flaky")
                return "ok"

            out = retry_call(
                fn,
                policy=RetryPolicy(max_attempts=4, base_delay_s=0.1,
                                   jitter=0.5, budget_s=10.0),
                retryable=(IOError,),
                rng=random.Random(seed),
                sleep=sleeps.append,
            )
            assert out == "ok"
            return sleeps

        a, b = delays_for(7), delays_for(7)
        assert a == b and len(a) == 3
        assert delays_for(8) != a
        # exponential shape survives the jitter (jitter only shrinks)
        assert a[0] <= 0.1 and a[1] <= 0.2 and a[2] <= 0.4
        assert a[1] >= 0.1 and a[2] >= 0.2

    def test_exhausts_attempts(self):
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise IOError("down")

        with pytest.raises(IOError):
            retry_call(
                fn,
                policy=RetryPolicy(max_attempts=3, base_delay_s=0.001),
                retryable=(IOError,),
                sleep=lambda s: None,
            )
        assert calls["n"] == 3

    def test_budget_stops_retrying(self):
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise IOError("down")

        with pytest.raises(IOError):
            retry_call(
                fn,
                policy=RetryPolicy(
                    max_attempts=10, base_delay_s=1.0, jitter=0.0,
                    budget_s=2.5,
                ),
                retryable=(IOError,),
                sleep=lambda s: None,
            )
        # sleeps 1 + 2 = 3 > 2.5 budget -> stops after the 2nd delay
        # would overflow: attempts = 2
        assert calls["n"] == 2

    def test_deadline_cuts_backoff(self):
        """The invariant: a retry sequence NEVER sleeps past the
        request deadline — it surfaces 504 instead."""
        clock = FakeClock()
        d = Deadline.after(0.15, clock=clock)

        def sleeping(s):
            clock.advance(s)

        def fn():
            clock.advance(0.01)  # each attempt costs a little
            raise IOError("down")

        with pytest.raises(DeadlineExceeded):
            retry_call(
                fn,
                policy=RetryPolicy(max_attempts=10, base_delay_s=0.1,
                                   jitter=0.0, budget_s=60.0),
                retryable=(IOError,),
                deadline=d,
                sleep=sleeping,
            )
        assert not clock.t - 1000.0 > 0.15 + 0.11  # never slept past

    def test_should_retry_filter(self):
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise IOError("permanent")

        with pytest.raises(IOError):
            retry_call(
                fn,
                policy=RetryPolicy(max_attempts=5, base_delay_s=0.001),
                retryable=(IOError,),
                should_retry=lambda e: "transient" in str(e),
                sleep=lambda s: None,
            )
        assert calls["n"] == 1


# ---------------------------------------------------------------------------
# fault injection determinism
# ---------------------------------------------------------------------------


class TestFaultInjection:
    def test_flap_schedule(self):
        s = flap(2, 3, IOError)
        pattern = [isinstance(s(n), type(None)) for n in range(10)]
        assert pattern == [False, False, True, True, True] * 2

    def test_first_n_heals(self):
        s = first_n(3, IOError)
        assert [s(n) is None for n in range(5)] == (
            [False, False, False, True, True]
        )

    def test_seeded_reproducible(self):
        a = [seeded(42, 0.5, IOError)(n) is None for n in range(50)]
        b = [seeded(42, 0.5, IOError)(n) is None for n in range(50)]
        c = [seeded(43, 0.5, IOError)(n) is None for n in range(50)]
        assert a == b and a != c and 5 < sum(a) < 45

    def test_latency_schedule(self):
        s = latency(0.25, every=2)
        assert isinstance(s(0), Latency) and s(0).seconds == 0.25
        assert s(1) is None and isinstance(s(2), Latency)

    def test_injector_fire_counts_and_clear(self):
        INJECTOR.install("p", first_n(1, lambda: IOError("boom")))
        with pytest.raises(IOError):
            INJECTOR.fire("p")
        INJECTOR.fire("p")  # healed
        assert INJECTOR.calls("p") == 2
        INJECTOR.clear()
        INJECTOR.fire("p")  # no schedule: no-op, not counted
        assert INJECTOR.calls("p") == 0


# ---------------------------------------------------------------------------
# store edges: breaker trips, fails fast, recovers
# ---------------------------------------------------------------------------


class TestStoreBreaker:
    def test_http_store_breaker_opens_and_recovers(self, tmp_path):
        from test_zarr_stores import _DirHandler, _serve_dir

        (tmp_path / "key").write_bytes(b"payload")
        server = _serve_dir(str(tmp_path), _DirHandler)
        try:
            port = server.server_address[1]
            store = HTTPStore(f"http://127.0.0.1:{port}")
            clock = FakeClock()
            store.breaker = CircuitBreaker(
                "store", failure_threshold=3, open_duration_s=5.0,
                min_calls=100, clock=clock,
            )
            set_default_policy(
                RetryPolicy(max_attempts=1)  # isolate breaker math
            )
            assert store.get("key") == b"payload"

            INJECTOR.install(
                "store.http", first_n(3, StoreError("injected outage"))
            )
            for _ in range(3):
                with pytest.raises(StoreError):
                    store.get("key")
            assert store.breaker.state == "open"

            # open: fails fast WITHOUT touching the dependency
            fired = INJECTOR.calls("store.http")
            with pytest.raises(StoreUnavailableError):
                store.get("key")
            assert INJECTOR.calls("store.http") == fired

            # half-open probe heals (schedule already exhausted)
            clock.advance(5.1)
            assert store.get("key") == b"payload"
            assert store.breaker.state == "closed"
        finally:
            server.shutdown()

    def test_store_retries_respect_ambient_deadline(self, tmp_path):
        """A GET under an (almost-spent) request budget must not sit
        in backoff: it aborts with DeadlineExceeded quickly."""
        store = HTTPStore("http://127.0.0.1:1", timeout_s=0.2)
        set_default_policy(
            RetryPolicy(max_attempts=5, base_delay_s=0.5, jitter=0.0,
                        budget_s=30.0)
        )
        t0 = time.monotonic()
        with deadline_scope(Deadline.after(0.25)):
            with pytest.raises((StoreError, DeadlineExceeded)):
                store.get("x")
        assert time.monotonic() - t0 < 1.0  # never 4 x 0.5s backoffs


class TestCredentialRotation:
    def test_file_rotation_supersedes_stale_env(
        self, tmp_path, monkeypatch
    ):
        """ADVICE r5: launched with (now-stale) STS creds in env, the
        403 refresh path must pick up rotated ~/.aws file credentials
        — the FakeS3 answers 403 until the signature matches the
        rotated secret, then 200."""
        from test_zarr_stores import (
            ACCESS_KEY,
            SECRET_KEY,
            _FakeS3Handler,
            _serve_dir,
        )

        root = tmp_path / "bucket"
        root.mkdir()
        (root / "img.zarr").mkdir()
        (root / "img.zarr" / ".zattrs").write_bytes(b"{}")
        server = _serve_dir(str(root), _FakeS3Handler)
        try:
            port = server.server_address[1]
            monkeypatch.setenv(
                "OMPB_S3_ENDPOINT", f"http://127.0.0.1:{port}"
            )
            monkeypatch.setenv("AWS_REGION", "us-east-1")
            # env carries STALE credentials (expired STS)
            monkeypatch.setenv("AWS_ACCESS_KEY_ID", ACCESS_KEY)
            monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "stale-secret")
            cred = tmp_path / "credentials"
            monkeypatch.setenv(
                "AWS_SHARED_CREDENTIALS_FILE", str(cred)
            )
            monkeypatch.setenv(
                "AWS_CONFIG_FILE", str(tmp_path / "no-config")
            )
            store = S3Store("s3://test-bucket/img.zarr")
            # before rotation: 403 forever (refresh finds nothing
            # fresher than the stale env)
            with pytest.raises(StoreError):
                store.get(".zattrs")
            # operator rotates the shared credentials file
            cred.write_text(
                f"[default]\naws_access_key_id = {ACCESS_KEY}\n"
                f"aws_secret_access_key = {SECRET_KEY}\n"
            )
            store._last_refresh_mono = float("-inf")  # pass throttle
            assert store.get(".zattrs") == b"{}"
            assert store.secret_key == SECRET_KEY  # files won
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# glacier2 validator: breaker-open -> 503, never 403
# ---------------------------------------------------------------------------


class TestIceBreaker:
    async def test_unreachable_router_opens_breaker_503(self):
        from omero_ms_pixel_buffer_tpu.auth.ice import (
            IceSessionValidator,
        )

        v = IceSessionValidator(
            "127.0.0.1", port=1, secure=False, timeout_s=0.2,
            cache_ttl_s=0,
        )
        v.breaker = CircuitBreaker(
            "glacier2", failure_threshold=2, open_duration_s=60.0,
            min_calls=100,
        )
        for _ in range(2):
            with pytest.raises(OSError):
                await v.validate("key")
        with pytest.raises(ServiceUnavailableError) as ei:
            await v.validate("key")
        assert ei.value.code == 503 and ei.value.retry_after_s > 0


# ---------------------------------------------------------------------------
# HTTP front: healthz, shedding, deadlines
# ---------------------------------------------------------------------------


async def _make_app(tmp_path, *, resilience=None, config_extra=None,
                    slow_s=0.0, workers=4):
    """A served zarr image behind the full app, with an optionally
    slowed pipeline (deterministic busy-time per tile)."""
    path = str(tmp_path / "img.zarr")
    write_ngff(path, IMG, chunks=(32, 32))
    registry = ImageRegistry()
    registry.add(1, path, type="zarr")
    raw = {
        "session-store": {"type": "memory"},
        "worker_pool_size": workers,
        "backend": {"batching": {"max-batch": 1,
                                 "coalesce-window-ms": 0.0}},
        # the chaos suite measures the PIPELINE path: identical-tile
        # requests must each execute, not hit the result cache or
        # coalesce into one flight (the cache has its own suite,
        # tests/test_tile_cache.py)
        "cache": {"enabled": False},
    }
    if resilience:
        raw["resilience"] = resilience
    if config_extra:
        raw.update(config_extra)
    config = Config.from_dict(raw)
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=MemorySessionStore({"ck": "key"}),
    )
    if slow_s:
        inner = app_obj.pipeline.handle

        def slowed(ctx):
            time.sleep(slow_s)
            return inner(ctx)

        app_obj.pipeline.handle = slowed
    client = TestClient(
        TestServer(app_obj.make_app()), loop=asyncio.get_running_loop()
    )
    await client.start_server()
    return app_obj, client


class TestHealthz:
    async def test_schema_and_degraded_transition(self, tmp_path, loop):
        app_obj, client = await _make_app(tmp_path)
        try:
            resp = await client.get("/healthz")  # unauthenticated
            assert resp.status == 200
            body = await resp.json()
            assert body["status"] == "ok"
            assert {"breakers", "admission", "queue_depth",
                    "uptime_s"} <= set(body)
            assert body["admission"]["max_inflight"] == 256

            # an open breaker flips the status to degraded
            from omero_ms_pixel_buffer_tpu.resilience import (
                for_dependency,
            )

            b = for_dependency(
                "store:s3:chaos", failure_threshold=1, min_calls=100
            )
            b.record_failure()
            resp = await client.get("/healthz")
            body = await resp.json()
            assert body["status"] == "degraded"
            assert body["breakers"]["store:s3:chaos"]["state"] == "open"
            del b
        finally:
            await client.close()

    async def test_metrics_expose_resilience_counters(
        self, tmp_path, loop
    ):
        _, client = await _make_app(tmp_path)
        try:
            text = await (await client.get("/metrics")).text()
            for name in (
                "resilience_breaker_state",
                "resilience_breaker_transitions_total",
                "resilience_shed_total",
                "resilience_deadline_exceeded_total",
                "resilience_retries_total",
            ):
                assert name in text, name
        finally:
            await client.close()


class TestLoadShedding:
    async def test_overload_sheds_503_with_retry_after(
        self, tmp_path, loop
    ):
        """2x capacity synthetic load against a BOUNDED SLO queue
        (r13: the scheduler queues deadline-ordered past the in-flight
        gate; only queue overflow sheds): 4 execute, 2 wait, the
        excess sheds 503 + Retry-After; executed requests stay near
        the unloaded latency."""
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 4,
                                      "retry-after-s": 2}},
            config_extra={"slo": {"queue-size": 2, "degrade": False}},
            slow_s=0.1, workers=4,
        )
        try:
            # unloaded baseline
            unloaded = []
            for _ in range(3):
                t0 = time.monotonic()
                r = await client.get("/tile/1/0/0/0?w=32&h=32",
                                     headers=AUTH)
                unloaded.append(time.monotonic() - t0)
                assert r.status == 200
            unloaded_p50 = sorted(unloaded)[1]

            async def fetch():
                t0 = time.monotonic()
                r = await client.get("/tile/1/0/0/0?w=32&h=32",
                                     headers=AUTH)
                return r, time.monotonic() - t0

            results = await asyncio.gather(*(fetch() for _ in range(8)))
            admitted = [(r, dt) for r, dt in results if r.status == 200]
            shed = [r for r, _ in results if r.status == 503]
            assert admitted and shed  # both behaviors under overload
            # 4 slots + 2 queued may succeed; the overflow sheds
            assert len(admitted) <= 6 and len(shed) >= 2
            for r in shed:
                assert r.headers["Retry-After"] == "2"
            lat = sorted(dt for _, dt in admitted)
            admitted_p50 = lat[len(lat) // 2]
            assert admitted_p50 <= 2 * unloaded_p50 + 0.1
            assert app_obj.admission.shed_total == len(shed)
            assert app_obj.scheduler.snapshot()["shed"][
                "interactive"
            ] == len(shed)

            # load gone: the gate reopens
            r = await client.get("/tile/1/0/0/0?w=32&h=32",
                                 headers=AUTH)
            assert r.status == 200
        finally:
            await client.close()

    async def test_healthz_reachable_under_saturation(
        self, tmp_path, loop
    ):
        app_obj, client = await _make_app(
            tmp_path,
            resilience={"admission": {"max-inflight": 1}},
            slow_s=0.2, workers=1,
        )
        try:
            tile = asyncio.ensure_future(
                client.get("/tile/1/0/0/0?w=32&h=32", headers=AUTH)
            )
            await asyncio.sleep(0.05)  # tile in flight, gate full
            r = await client.get("/healthz")
            assert r.status == 200  # never shed
            assert (await r.json())["admission"]["inflight"] == 1
            assert (await tile).status == 200
        finally:
            await client.close()


class TestDeadline504:
    async def test_expired_budget_is_504_and_prompt(
        self, tmp_path, loop
    ):
        """Pipeline busy-time (0.5 s) far exceeds the 100 ms request
        budget: the front answers 504 at ~the budget, not after the
        full pipeline time — the caller is never parked behind the
        straggler."""
        _, client = await _make_app(
            tmp_path,
            resilience={"request-budget-ms": 100},
            slow_s=0.5, workers=1,
        )
        try:
            t0 = time.monotonic()
            r = await client.get("/tile/1/0/0/0?w=32&h=32",
                                 headers=AUTH)
            elapsed = time.monotonic() - t0
            assert r.status == 504
            assert elapsed < 0.4  # answered at the budget, not 0.5s+
            text = await (await client.get("/metrics")).text()
            assert "resilience_deadline_exceeded_total" in text
            assert 'stage="bus"' in text
        finally:
            await client.close()

    async def test_queued_expired_lane_never_reaches_executor(
        self, tmp_path, loop
    ):
        """Lanes that expire while queued are failed at dispatch (504)
        instead of occupying a worker."""
        from omero_ms_pixel_buffer_tpu.dispatch.bus import (
            GET_TILE_EVENT,
        )

        app_obj, client = await _make_app(tmp_path, slow_s=0.0)
        try:
            ctx_calls = []
            inner = app_obj.pipeline.handle

            def counting(ctx):
                ctx_calls.append(ctx.image_id)
                return inner(ctx)

            app_obj.pipeline.handle = counting
            from omero_ms_pixel_buffer_tpu.tile_ctx import TileCtx

            ctx = TileCtx.from_params(
                {"imageId": "1", "z": "0", "c": "0", "t": "0",
                 "w": "32", "h": "32"}, "key",
            )
            clock = FakeClock()
            ctx.deadline = Deadline(clock() - 1.0, clock)  # born dead
            with pytest.raises(Exception) as ei:
                await app_obj.bus.request(GET_TILE_EVENT, ctx,
                                          timeout_ms=2000)
            assert getattr(ei.value, "code", None) == 504
            assert ctx_calls == []  # pipeline never touched
        finally:
            await client.close()


# ---------------------------------------------------------------------------
# chaos: postgres flaps, zarr lane keeps serving (fault isolation)
# ---------------------------------------------------------------------------


class _SplitResolver:
    """Scoped metadata façade: image 2's metadata comes from the OMERO
    Postgres resolver (the flapping dependency); everything else from
    the file-backed registry (no DB on its path)."""

    def __init__(self, registry, db_resolver):
        self.registry = registry
        self.db = db_resolver

    def get_pixels(self, image_id, session_key=None):
        if int(image_id) == 2:
            return self.db.get_pixels(
                image_id, session_key=session_key
            )
        return self.registry.get_pixels(image_id)


class TestPostgresFlapIsolation:
    @pytest.fixture
    def chaos_app(self, tmp_path, loop):
        """Two images: 1 = zarr straight off the registry (healthy S3/
        filesystem analog), 2 = zarr whose *metadata* rides the
        Postgres resolver against a live FakePg."""
        for img_id in (1, 2):
            write_ngff(
                str(tmp_path / f"{img_id}.zarr"), IMG, chunks=(32, 32)
            )
        registry = ImageRegistry()
        registry.add(1, str(tmp_path / "1.zarr"), type="zarr")
        registry.add(2, str(tmp_path / "2.zarr"), type="zarr")

        def rows_for(sql, params):
            if params and params[0] == "2":
                return [pixels_row()]
            return []

        pg = FakePg(rows_for=rows_for)
        loop.run_until_complete(pg.__aenter__())

        raw = {
            "session-store": {"type": "memory"},
            "worker_pool_size": 4,
            "backend": {"batching": {"max-batch": 1,
                                     "coalesce-window-ms": 0.0}},
            # cache off: a warm result cache (rightly) serves repeated
            # tiles THROUGH a Postgres outage; this suite measures the
            # pipeline's breaker behavior, so every request must reach it
            "cache": {"enabled": False},
            "resilience": {
                # open duration far beyond the test's runtime so the
                # open -> half_open promotion never races the asserts;
                # the heal step force-elapses it instead of sleeping
                "breaker": {"failure-threshold": 3, "window": 100,
                            "min-calls": 100,
                            "open-duration-ms": 60000},
                "retry": {"max-attempts": 1},
                "request-budget-ms": 2000,
            },
        }
        config = Config.from_dict(raw)
        configure_resilience(config.resilience)  # before the resolver
        db_resolver = OmeroPostgresMetadataResolver(
            f"postgresql://omero:pw@127.0.0.1:{pg.port}/omero",
            cache_ttl_s=0.0,  # no caching: every request hits the DB
        )
        pixels_service = PixelsService(
            registry,
            metadata_resolver=_SplitResolver(registry, db_resolver),
        )
        app_obj = PixelBufferApp(
            config,
            pixels_service=pixels_service,
            session_store=MemorySessionStore({"ck": "key"}),
        )
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        loop.run_until_complete(client.start_server())
        yield app_obj, client, db_resolver
        loop.run_until_complete(client.close())
        db_resolver.close_sync()
        loop.run_until_complete(pg.__aexit__(None, None, None))

    async def test_flap_isolated_and_recovers(self, chaos_app):
        app_obj, client, db_resolver = chaos_app
        breaker = db_resolver._client.breaker

        # healthy: both lanes serve
        for img in (1, 2):
            r = await client.get(f"/tile/{img}/0/0/0?w=32&h=32",
                                 headers=AUTH)
            assert r.status == 200, img

        # postgres goes down hard (connection errors, deterministic)
        INJECTOR.install(
            "db.postgres",
            first_n(50, ConnectionError("pg flapping")),
        )
        statuses = []
        for _ in range(5):
            r = await client.get("/tile/2/0/0/0?w=32&h=32",
                                 headers=AUTH)
            statuses.append(r.status)
        # transport errors before the trip read as 404/500; once the
        # breaker opens the lane answers a typed 503
        assert all(s in (404, 500, 503, 504) for s in statuses)
        assert statuses[-1] == 503  # breaker open -> unavailable
        assert breaker.state == "open"  # trip after 3 failures

        # FAULT ISOLATION: the zarr registry lane keeps serving, fast
        t0 = time.monotonic()
        for _ in range(5):
            r = await client.get("/tile/1/0/0/0?w=32&h=32",
                                 headers=AUTH)
            assert r.status == 200
        assert time.monotonic() - t0 < 2.0

        # and the sick lane fails FAST (breaker, not timeout): the
        # postgres edge is not consulted while open
        fired = INJECTOR.calls("db.postgres")
        t0 = time.monotonic()
        r = await client.get("/tile/2/0/0/0?w=32&h=32", headers=AUTH)
        assert r.status != 200
        assert time.monotonic() - t0 < 0.5
        assert INJECTOR.calls("db.postgres") == fired

        # /healthz names the open breaker
        body = await (await client.get("/healthz")).json()
        assert body["status"] == "degraded"
        open_deps = [
            n for n, b in body["breakers"].items()
            if b["state"] == "open"
        ]
        assert any(n.startswith("postgres:") for n in open_deps)

        # heal: chaos off + open period force-elapsed (no wall-clock
        # wait) -> the half-open probe recovers the lane end to end
        INJECTOR.clear()
        breaker._opened_at = float("-inf")
        r = await client.get("/tile/2/0/0/0?w=32&h=32", headers=AUTH)
        assert r.status == 200
        assert breaker.state == "closed"

    async def test_deadline_cuts_slow_postgres(self, chaos_app):
        """Breaker + deadline interplay: a *slow* (not failing)
        Postgres can't park the caller — the 2 s request budget is
        the worst case, not the dependency's timeout."""
        app_obj, client, db_resolver = chaos_app
        INJECTOR.install("db.postgres", latency(5.0))
        t0 = time.monotonic()
        r = await client.get("/tile/2/0/0/0?w=32&h=32", headers=AUTH)
        elapsed = time.monotonic() - t0
        assert r.status == 504
        assert elapsed < 3.5  # budget 2s + slack, never the 5s latency
        # the healthy lane is untouched while the slow call drains
        r = await client.get("/tile/1/0/0/0?w=32&h=32", headers=AUTH)
        assert r.status == 200


# ---------------------------------------------------------------------------
# session store unavailability: 503, never 403
# ---------------------------------------------------------------------------


class TestSessionStore503:
    async def test_breaker_open_maps_to_503_not_403(
        self, tmp_path, loop
    ):
        from omero_ms_pixel_buffer_tpu.auth.stores import (
            RedisSessionStore,
        )

        store = RedisSessionStore("redis://127.0.0.1:1/0")
        store.breaker = CircuitBreaker(
            "session-store", failure_threshold=1, open_duration_s=60.0,
            min_calls=100,
        )
        path = str(tmp_path / "img.zarr")
        write_ngff(path, IMG, chunks=(32, 32))
        registry = ImageRegistry()
        registry.add(1, path, type="zarr")
        config = Config.from_dict({"session-store": {"type": "memory"}})
        app_obj = PixelBufferApp(
            config,
            pixels_service=PixelsService(registry),
            session_store=store,
        )
        client = TestClient(TestServer(app_obj.make_app()), loop=loop)
        await client.start_server()
        try:
            # first hit: connection refused -> 503 (store down != auth
            # denied), breaker records the outage
            r = await client.get("/tile/1/0/0/0?w=8&h=8", headers=AUTH)
            assert r.status == 503
            # breaker now open: still 503, with Retry-After, fast
            r = await client.get("/tile/1/0/0/0?w=8&h=8", headers=AUTH)
            assert r.status == 503
            assert "Retry-After" in r.headers
        finally:
            await client.close()
