"""Super-tile plane suite (render/supertile + the r19 wiring).

Covers: adjacency bucketing (grid hints, pairwise sweep, pixel-budget
splits, coverage, fuse-key isolation), fused-vs-independent byte
identity across host/device engines over a tile grid (uniform and
edge-tile sizes, projection specs), burst-split correctness (an
expired / 404 / chaos-faulted lane leaves its neighbors
byte-identical), degraded-permit isolation (never fuses with
full-res), the r19 satellites — ROI masks through
``submit_render``/the streaming queue (byte-identity pinned against
the host mirror, device path proven by counter) and device-resident
cached-plane projection crops (zero host pulls on the warm pan) —
plus the ``supertile:`` config block, the batcher stamping seam, and
whole-viewport prefetch speculation.
"""

import asyncio

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_pixel_buffer_tpu.auth.omero_session import AllowListValidator
from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.dispatch.batcher import BatchingTileWorker
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
from omero_ms_pixel_buffer_tpu.render import supertile as stile
from omero_ms_pixel_buffer_tpu.render.engine import RENDER_TILES
from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
from omero_ms_pixel_buffer_tpu.render.supertile import (
    BurstHint,
    assign_supertiles,
)
from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
from omero_ms_pixel_buffer_tpu.resilience.deadline import Deadline
from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
    INJECTOR,
    always,
)
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

rng = np.random.default_rng(23)
AUTH = {"Cookie": "sessionid=ck"}

# (T, C, Z, Y, X) — two channels, four z planes
IMG = rng.integers(0, 4096, (1, 2, 4, 96, 128), dtype=np.uint16)


@pytest.fixture(autouse=True)
def _clean_chaos():
    INJECTOR.clear()
    yield
    INJECTOR.clear()
    BOARD.reset()


def _write_fixture(tmp_path):
    path = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(path, IMG, tile_size=(64, 64))
    registry = ImageRegistry()
    registry.add(1, path)
    return registry


@pytest.fixture
def service(tmp_path):
    svc = PixelsService(_write_fixture(tmp_path))
    yield svc
    svc.close()


def _spec(**extra):
    params = {"c": "1|0:4095$FF0000,2|0:4095$00FF00"}
    params.update(extra)
    return RenderSpec.from_params(params)


def _ctx(spec, x, y, w=32, h=32, z=1, burst=None, **kw):
    return TileCtx(
        image_id=1, z=z, c=0, t=0, region=RegionDef(x, y, w, h),
        format=spec.format, omero_session_key="k", render=spec,
        burst=burst, **kw,
    )


def _grid(spec, tile=32, cols=3, rows=2, **kw):
    return [
        _ctx(spec, tile * c, tile * r, tile, tile, **kw)
        for r in range(rows) for c in range(cols)
    ]


# ---------------------------------------------------------------------------
# Adjacency bucketing (the pure planner)
# ---------------------------------------------------------------------------


class TestAdjacencyBucketing:
    def test_grid_burst_forms_one_group(self):
        ctxs = _grid(_spec())
        assert assign_supertiles(ctxs) == 6
        tokens = {id(c.supertile) for c in ctxs}
        assert len(tokens) == 1 and None not in tokens

    def test_non_adjacent_lane_falls_through(self):
        spec = _spec()
        ctxs = _grid(spec, cols=2, rows=1)
        # far corner: not touching the 2x1 run
        ctxs.append(_ctx(spec, 96, 64))
        assign_supertiles(ctxs)
        assert ctxs[0].supertile is ctxs[1].supertile is not None
        assert ctxs[2].supertile is None

    def test_pixel_budget_splits_groups(self):
        ctxs = _grid(_spec(), cols=4, rows=1)
        # budget fits exactly two 32x32 tiles side by side
        assign_supertiles(ctxs, max_pixels=2 * 32 * 32)
        tokens = [id(c.supertile) for c in ctxs]
        assert None not in [c.supertile for c in ctxs]
        assert len(set(tokens)) == 2
        # every group respects the budget
        for ctx in ctxs:
            assert ctx.supertile.n == 2

    def test_min_lanes_and_singletons(self):
        ctxs = [_ctx(_spec(), 0, 0)]
        assert assign_supertiles(ctxs) == 0
        assert ctxs[0].supertile is None

    def test_degraded_masked_analysis_never_stamp(self):
        spec = _spec()
        roi = '[{"type":"rect","x":0,"y":0,"w":30,"h":20}]'
        masked = _spec(roi=roi)
        ctxs = _grid(spec, cols=2, rows=1)
        ctxs.append(_ctx(spec, 64, 0, degraded=1))
        ctxs.append(_ctx(masked, 0, 32))
        ctxs.append(_ctx(masked, 32, 32))
        assign_supertiles(ctxs)
        assert ctxs[0].supertile is not None
        assert ctxs[2].supertile is None  # degraded
        assert ctxs[3].supertile is None  # masked
        assert ctxs[4].supertile is None

    def test_expired_deadline_never_stamps(self):
        ctxs = _grid(_spec(), cols=2, rows=1)
        ctxs[1].deadline = Deadline.after(0)
        assign_supertiles(ctxs)
        assert ctxs[0].supertile is None  # partner expired: < min lanes
        assert ctxs[1].supertile is None

    def test_fuse_key_isolates_spec_image_plane(self):
        a, b = _spec(), _spec(m="g")
        ctxs = (
            _grid(a, cols=2, rows=1)
            + [_ctx(b, 64, 0), _ctx(b, 96, 0)]
            + [_ctx(a, 0, 32, z=2), _ctx(a, 32, 32, z=2)]
        )
        assign_supertiles(ctxs)
        groups = {id(c.supertile) for c in ctxs}
        assert len(groups) == 3  # one per (spec, z) bucket

    def test_grid_hint_matches_sweep_clusters(self):
        hint = BurstHint(32, 32)
        hinted = _grid(_spec(), burst=hint) + [
            _ctx(_spec(), 96, 96, burst=hint)
        ]
        plain = _grid(_spec()) + [_ctx(_spec(), 96, 96)]
        assign_supertiles(hinted)
        assign_supertiles(plain)
        for h, p in zip(hinted, plain):
            assert (h.supertile is None) == (p.supertile is None)
        assert hinted[-1].supertile is None

    def test_coverage_bound_rejects_sparse_diagonal(self):
        spec = _spec()
        # corner-touching diagonal: bounding rect 64x64, covered 1/2
        # at two tiles — with coverage 0.9 nothing fuses
        ctxs = [_ctx(spec, 0, 0), _ctx(spec, 32, 32)]
        assign_supertiles(ctxs, min_coverage=0.9)
        assert all(c.supertile is None for c in ctxs)


# ---------------------------------------------------------------------------
# Fused vs independent byte identity
# ---------------------------------------------------------------------------


def _independent(service, ctxs_fn):
    pipe = TilePipeline(service, engine="host")
    try:
        return [pipe.handle(c) for c in ctxs_fn()]
    finally:
        pipe.close()


class TestFusedByteIdentity:
    def test_host_engine_grid(self, service):
        spec = _spec()
        ref = _independent(service, lambda: _grid(spec))
        assert all(b is not None for b in ref)
        pipe = TilePipeline(service, engine="host")
        try:
            ctxs = _grid(spec)
            assert assign_supertiles(ctxs) == 6
            assert pipe.handle_batch(ctxs) == ref
        finally:
            pipe.close()

    def test_device_engine_grid(self, service):
        spec = _spec()
        ref = _independent(service, lambda: _grid(spec))
        pipe = TilePipeline(service, engine="device", device_deflate=True)
        pipe.mesh = None
        try:
            before = dict(stile.SUPERTILE_LANES._values)
            ctxs = _grid(spec)
            assign_supertiles(ctxs)
            assert pipe.handle_batch(ctxs) == ref
            after = dict(stile.SUPERTILE_LANES._values)
            key = (("path", "device"),)
            assert after.get(key, 0) - before.get(key, 0) == 6
        finally:
            pipe.close()

    def test_edge_tiles_mixed_sizes(self, service):
        """A DZI-style edge row: rightmost/bottom tiles are smaller —
        the fused carve sub-groups by real size and every lane stays
        byte-identical."""
        spec = _spec()

        def ctxs_fn():
            out = []
            for y, h in ((0, 48), (48, 48)):
                for x, w in ((0, 48), (48, 48), (96, 32)):
                    out.append(_ctx(spec, x, y, w, h))
            return out

        ref = _independent(service, ctxs_fn)
        assert all(b is not None for b in ref)
        for engine, dd in (("host", False), ("device", True)):
            pipe = TilePipeline(
                service, engine=engine, device_deflate=dd, buckets=(64,),
            )
            pipe.mesh = None
            try:
                ctxs = ctxs_fn()
                assert assign_supertiles(ctxs) == 6
                assert pipe.handle_batch(ctxs) == ref, engine
            finally:
                pipe.close()

    def test_projection_spec_fused(self, service):
        spec = _spec(p="intmax|0:3")

        def ctxs_fn():
            return _grid(spec, cols=2, rows=2, z=0)

        ref = _independent(service, ctxs_fn)
        assert all(b is not None for b in ref)
        pipe = TilePipeline(service, engine="device", device_deflate=True)
        pipe.mesh = None
        try:
            ctxs = ctxs_fn()
            assign_supertiles(ctxs)
            assert pipe.handle_batch(ctxs) == ref
        finally:
            pipe.close()

    def test_jpeg_burst_carves_host_side(self, service):
        spec = _spec(format="jpeg", q="0.9")
        ref = _independent(service, lambda: _grid(spec))
        assert all(b is not None and b[:2] == b"\xff\xd8" for b in ref)
        pipe = TilePipeline(service, engine="host")
        try:
            ctxs = _grid(spec)
            assert assign_supertiles(ctxs) == 6
            assert pipe.handle_batch(ctxs) == ref
        finally:
            pipe.close()


# ---------------------------------------------------------------------------
# Burst-split correctness: one bad lane never poisons its neighbors
# ---------------------------------------------------------------------------


class TestBurstSplit:
    def test_expired_lane_splits_out(self, service):
        spec = _spec()
        ref = _independent(service, lambda: _grid(spec))
        pipe = TilePipeline(service, engine="host")
        try:
            ctxs = _grid(spec)
            assign_supertiles(ctxs)
            assert all(c.supertile is not None for c in ctxs)
            ctxs[2].deadline = Deadline.after(0)  # expires post-stamp
            out = pipe.handle_batch(ctxs)
            assert out[2] is None  # -> 504 at the dispatch layer
            for i in (0, 1, 3, 4, 5):
                assert out[i] == ref[i]
        finally:
            pipe.close()

    def test_404_lane_splits_out(self, service):
        spec = _spec()
        ref = _independent(service, lambda: _grid(spec, cols=2, rows=1))
        pipe = TilePipeline(service, engine="host")
        try:
            ctxs = _grid(spec, cols=2, rows=1)
            # adjacent but off the 128px plane: resolve fails -> 404
            ctxs.append(_ctx(spec, 64, 0, 96, 32))
            assign_supertiles(ctxs)
            assert all(c.supertile is not None for c in ctxs)
            out = pipe.handle_batch(ctxs)
            assert out[:2] == ref and out[2] is None
        finally:
            pipe.close()

    @pytest.mark.resilience
    def test_supertile_fault_neighbors_identical(self, service):
        """The chaos lane: the fused super-tile dispatch down -> the
        whole group serves through the host carve, byte-identical."""
        spec = _spec()
        ref = _independent(service, lambda: _grid(spec))
        pipe = TilePipeline(service, engine="device", device_deflate=True)
        pipe.mesh = None
        try:
            INJECTOR.install(
                "render.supertile", always(RuntimeError("fused down"))
            )
            ctxs = _grid(spec)
            assign_supertiles(ctxs)
            assert pipe.handle_batch(ctxs) == ref
            assert INJECTOR.calls("render.supertile") >= 1
        finally:
            pipe.close()

    @pytest.mark.resilience
    def test_stale_stamp_falls_back(self, service):
        """A stamp whose partner lanes vanished (all but one filtered
        out) re-validates down to the independent path."""
        spec = _spec()
        ref = _independent(service, lambda: _grid(spec, cols=2, rows=1))
        pipe = TilePipeline(service, engine="host")
        try:
            ctxs = _grid(spec, cols=2, rows=1)
            assign_supertiles(ctxs)
            ctxs[1].deadline = Deadline.after(0)
            out = pipe.handle_batch(ctxs)
            assert out[0] == ref[0] and out[1] is None
        finally:
            pipe.close()


class TestGridAlignedSplit:
    def test_budget_split_prefers_grid_rows(self):
        """With a burst grid hint, a pixel-budget split cuts BETWEEN
        grid rows instead of slicing greedily through one."""
        hint = BurstHint(32, 32)
        ctxs = _grid(_spec(), cols=3, rows=2, burst=hint)
        # budget: exactly one 3-tile grid row
        assign_supertiles(ctxs, max_pixels=3 * 32 * 32)
        groups = {}
        for c in ctxs:
            assert c.supertile is not None
            groups.setdefault(id(c.supertile), []).append(c.region.y)
        assert len(groups) == 2
        for ys in groups.values():
            assert len(set(ys)) == 1, "split cut through a grid row"

    def test_over_budget_row_recurses_greedy(self):
        """A single grid row larger than the budget still splits
        (hintless recursion) instead of dropping the lanes."""
        hint = BurstHint(32, 32)
        ctxs = _grid(_spec(), cols=4, rows=1, burst=hint)
        assign_supertiles(ctxs, max_pixels=2 * 32 * 32)
        tokens = {id(c.supertile) for c in ctxs}
        assert None not in [c.supertile for c in ctxs]
        assert len(tokens) == 2

    def test_split_fragments_carve_byte_identical(self, service):
        """Pin: grid-aligned split fragments still carve bytes equal
        to independent tiles, host and device engines."""
        spec = _spec()
        hint = BurstHint(32, 32)
        ref = _independent(service, lambda: _grid(spec))
        for engine, dd in (("host", False), ("device", True)):
            pipe = TilePipeline(service, engine=engine, device_deflate=dd)
            pipe.mesh = None
            try:
                ctxs = _grid(spec, burst=hint)
                assign_supertiles(ctxs, max_pixels=3 * 32 * 32)
                assert len({id(c.supertile) for c in ctxs}) == 2
                assert pipe.handle_batch(ctxs) == ref, engine
            finally:
                pipe.close()


class TestDegradedFusion:
    def test_degraded_lanes_fuse_with_each_other(self, service):
        """Degraded lanes fuse per pyramid level (the r23 satellite):
        the fused coarse gather + single upscale serves bytes equal to
        per-lane degraded reads, and the group genuinely fused."""
        spec = _spec()
        ref = _independent(
            service, lambda: _grid(spec, cols=2, rows=2, degraded=1)
        )
        assert all(b is not None for b in ref)
        pipe = TilePipeline(service, engine="host")
        try:
            ctxs = _grid(spec, cols=2, rows=2, degraded=1)
            assert assign_supertiles(ctxs) == 4
            assert pipe.handle_batch(ctxs) == ref
        finally:
            pipe.close()

    def test_degraded_never_mixes_with_full_res(self):
        spec = _spec()
        ctxs = _grid(spec, cols=2, rows=1) + _grid(
            spec, cols=2, rows=1, degraded=1
        )
        assign_supertiles(ctxs)
        full = {id(c.supertile) for c in ctxs[:2]}
        deg = {id(c.supertile) for c in ctxs[2:]}
        assert None not in [c.supertile for c in ctxs]
        assert full.isdisjoint(deg)

    def test_degraded_device_fused_identical(self, service):
        spec = _spec()
        ref = _independent(
            service, lambda: _grid(spec, cols=2, rows=2, degraded=1)
        )
        pipe = TilePipeline(service, engine="device", device_deflate=True)
        pipe.mesh = None
        try:
            ctxs = _grid(spec, cols=2, rows=2, degraded=1)
            assign_supertiles(ctxs)
            assert pipe.handle_batch(ctxs) == ref
        finally:
            pipe.close()


class TestDegradedIsolation:
    def test_degraded_lane_never_fuses_and_serves_degraded_bytes(
        self, service
    ):
        spec = _spec()
        host = TilePipeline(service, engine="host")
        try:
            deg_ref = host.handle(_ctx(spec, 0, 0, 64, 64, degraded=1))
            full_ref = [
                host.handle(_ctx(spec, x, 0, 32, 32)) for x in (0, 32)
            ]
            assert deg_ref is not None and deg_ref not in full_ref
            ctxs = [
                _ctx(spec, 0, 0, 32, 32),
                _ctx(spec, 32, 0, 32, 32),
                _ctx(spec, 0, 0, 64, 64, degraded=1),
            ]
            assign_supertiles(ctxs)
            assert ctxs[0].supertile is not None
            assert ctxs[2].supertile is None
            out = host.handle_batch(ctxs)
            assert out[0] == full_ref[0] and out[1] == full_ref[1]
            assert out[2] == deg_ref
        finally:
            host.close()


# ---------------------------------------------------------------------------
# Satellite: ROI masks through submit_render / the streaming queue
# ---------------------------------------------------------------------------


class TestMaskQueueWiring:
    def test_masked_lane_takes_device_path_byte_identical(self, service):
        roi = (
            '[{"type":"rect","x":8,"y":8,"w":30,"h":20},'
            '{"type":"ellipse","cx":40,"cy":24,"rx":12,"ry":9}]'
        )
        spec = _spec(roi=roi)
        host = TilePipeline(service, engine="host")
        dev = TilePipeline(service, engine="device", device_deflate=True)
        dev.mesh = None
        try:
            ref = host.handle(_ctx(spec, 0, 0, 64, 48))
            assert ref is not None
            before = dict(RENDER_TILES._values)
            out = dev.handle_batch([_ctx(spec, 0, 0, 64, 48)])[0]
            after = dict(RENDER_TILES._values)
            assert out == ref
            key = (("format", "png"), ("path", "device"))
            assert after.get(key, 0) > before.get(key, 0), (
                "masked lane detoured to the host mirror"
            )
        finally:
            host.close()
            dev.close()

    def test_masked_and_unmasked_lanes_share_a_batch(self, service):
        roi = '[{"type":"rect","x":0,"y":0,"w":20,"h":20}]'
        masked, plain = _spec(roi=roi), _spec()
        host = TilePipeline(service, engine="host")
        dev = TilePipeline(service, engine="device", device_deflate=True)
        dev.mesh = None
        try:
            ref = [
                host.handle(_ctx(masked, 0, 0, 64, 48)),
                host.handle(_ctx(plain, 0, 0, 64, 48)),
            ]
            out = dev.handle_batch([
                _ctx(masked, 0, 0, 64, 48),
                _ctx(plain, 0, 0, 64, 48),
            ])
            assert out == ref
        finally:
            host.close()
            dev.close()

    @pytest.mark.resilience
    def test_masked_device_fault_falls_back_identical(self, service):
        roi = '[{"type":"rect","x":4,"y":4,"w":40,"h":30}]'
        spec = _spec(roi=roi)
        dev = TilePipeline(service, engine="device", device_deflate=True)
        dev.mesh = None
        try:
            clean = dev.handle_batch([_ctx(spec, 0, 0, 64, 48)])[0]
            assert clean is not None
            INJECTOR.install(
                "render.engine", always(RuntimeError("engine down"))
            )
            faulted = dev.handle_batch([_ctx(spec, 0, 0, 64, 48)])[0]
            assert faulted == clean
        finally:
            dev.close()


# ---------------------------------------------------------------------------
# Satellite: device-resident cached-plane projection crops
# ---------------------------------------------------------------------------


class TestProjectionResidency:
    def test_warm_projection_pan_zero_host_pulls(self, service):
        """Second pan over plane-cache-resident planes: crops stay
        device-resident through project + composite + deflate — ZERO
        host round trips (the r19 regression pin), bytes identical to
        the host engine."""
        spec = _spec(p="intmax|0:3")
        host = TilePipeline(service, engine="host")
        dev = TilePipeline(
            service, engine="device", device_deflate=True, buckets=(64,),
        )
        dev.mesh = None
        try:
            ref = host.handle(_ctx(spec, 0, 0, 64, 32, z=0))
            # warm: first touches read host-side and admit the planes
            # (admit_after=2), third call serves from HBM
            for _ in range(3):
                out = dev.handle_batch([_ctx(spec, 0, 0, 64, 32, z=0)])[0]
            assert out == ref
            pulls = dev._proj_host_pulls
            out2 = dev.handle_batch([_ctx(spec, 0, 0, 64, 32, z=0)])[0]
            assert out2 == ref
            assert dev._proj_host_pulls == pulls, (
                "warm projection pan round-tripped through the host"
            )
            assert dev.render_snapshot()["projection_host_pulls"] == pulls
        finally:
            host.close()
            dev.close()


# ---------------------------------------------------------------------------
# Config + batcher stamping seam
# ---------------------------------------------------------------------------


def _cfg(extra=None):
    raw = {"session-store": {"type": "memory"}}
    raw.update(extra or {})
    return Config.from_dict(raw)


class TestSupertileConfig:
    def test_defaults(self):
        cfg = _cfg()
        st = cfg.supertile
        assert st.enabled and st.max_pixels == 4 << 20
        assert st.min_lanes == 2 and st.coverage == 0.5
        assert cfg.cache.prefetch.viewport_span == 1

    def test_unknown_key_fails_startup(self):
        with pytest.raises(ConfigError):
            _cfg({"supertile": {"max-pixel": 1 << 20}})

    @pytest.mark.parametrize("block", [
        {"max-pixels": "many"},
        {"max-pixels": 1024},  # below the one-tile floor
        {"min-lanes": 1},
        {"coverage": 1.5},
        {"coverage": "-"},
    ])
    def test_invalid_values_fail_startup(self, block):
        with pytest.raises(ConfigError):
            _cfg({"supertile": block})

    def test_viewport_span_validated(self):
        cfg = _cfg({"cache": {"prefetch": {"viewport-span": 3}}})
        assert cfg.cache.prefetch.viewport_span == 3
        with pytest.raises(ConfigError):
            _cfg({"cache": {"prefetch": {"viewport-span": -1}}})


class TestBatcherStamping:
    def test_worker_stamps_adjacent_render_lanes(self, loop):
        """The dispatch seam: a coalesced batch of adjacent render
        lanes reaches the pipeline already stamped (adjacency
        detection lives in the batcher, not the pipeline)."""
        seen = {}

        class Recorder:
            def handle(self, ctx):
                return b"x"

            def handle_batch(self, ctxs):
                seen["stamped"] = [
                    c.supertile is not None for c in ctxs
                ]
                return [b"x"] * len(ctxs)

        cfg = _cfg()
        worker = BatchingTileWorker(
            Recorder(), AllowListValidator(), max_batch=8,
            coalesce_window_ms=20.0, workers=1,
            supertile=cfg.supertile,
        )
        spec = _spec()

        async def run():
            await worker.start()
            await asyncio.gather(*[
                worker.handle(c) for c in _grid(spec, cols=2, rows=2)
            ])
            await worker.close()

        loop.run_until_complete(run())
        assert seen["stamped"] == [True] * 4

    def test_disabled_config_never_stamps(self, loop):
        seen = {}

        class Recorder:
            def handle(self, ctx):
                return b"x"

            def handle_batch(self, ctxs):
                seen["stamped"] = [
                    c.supertile is not None for c in ctxs
                ]
                return [b"x"] * len(ctxs)

        cfg = _cfg({"supertile": {"enabled": False}})
        worker = BatchingTileWorker(
            Recorder(), AllowListValidator(), max_batch=8,
            coalesce_window_ms=20.0, workers=1,
            supertile=cfg.supertile,
        )
        spec = _spec()

        async def run():
            await worker.start()
            await asyncio.gather(*[
                worker.handle(c) for c in _grid(spec, cols=2, rows=1)
            ])
            await worker.close()

        loop.run_until_complete(run())
        assert seen["stamped"] == [False, False]


# ---------------------------------------------------------------------------
# Whole-viewport prefetch speculation
# ---------------------------------------------------------------------------


class _Admission:
    def has_headroom(self, fraction=0.5):
        return True


class TestViewportSpeculation:
    def _prefetcher(self, span):
        from omero_ms_pixel_buffer_tpu.cache.prefetch import (
            ViewportPrefetcher,
        )

        return ViewportPrefetcher(
            lambda ctx, key: None, cache=None, admission=_Admission(),
            lookahead=2, viewport_span=span,
        )

    def test_band_predicted_at_every_step(self):
        pre = self._prefetcher(span=1)
        spec = _spec()
        pre.observe(_ctx(spec, 0, 32, 32, 32))
        pre.observe(_ctx(spec, 32, 32, 32, 32))
        regions = [
            (c.region.x, c.region.y) for c, _ in pre._queue._queue
        ]
        # two lookahead steps, each a full 3-tile perpendicular band
        expected = {
            (64, 32), (64, 0), (64, 64),
            (96, 32), (96, 0), (96, 64),
        }
        assert expected == set(regions)

    def test_span_zero_restores_linear_prediction(self):
        pre = self._prefetcher(span=0)
        spec = _spec()
        pre.observe(_ctx(spec, 0, 32, 32, 32))
        pre.observe(_ctx(spec, 32, 32, 32, 32))
        regions = {
            (c.region.x, c.region.y) for c, _ in pre._queue._queue
        }
        assert regions == {(64, 32), (96, 32), (64, 0), (64, 64)}

    def test_predictions_carry_burst_geometry(self):
        pre = self._prefetcher(span=1)
        spec = _spec()
        hint = BurstHint(32, 32)
        pre.observe(_ctx(spec, 0, 32, 32, 32, burst=hint))
        pre.observe(_ctx(spec, 32, 32, 32, 32, burst=hint))
        assert pre._queue.qsize() > 0
        for c, _ in pre._queue._queue:
            assert c.burst is hint
        # hintless native pans synthesize the grid from the tile size
        pre2 = self._prefetcher(span=1)
        pre2.observe(_ctx(spec, 0, 32, 32, 32))
        pre2.observe(_ctx(spec, 32, 32, 32, 32))
        for c, _ in pre2._queue._queue:
            assert c.burst == BurstHint(32, 32)


# ---------------------------------------------------------------------------
# HTTP end to end: a DZI burst shares bytes + ETags with native /render
# ---------------------------------------------------------------------------


async def _make_app(tmp_path, config_extra=None):
    registry = _write_fixture(tmp_path)
    raw = {
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 5.0}},
        "protocols": {
            "dzi": {"tile-size": 32},
            "iiif": {"tile-size": 32},
            "iris": {"tile-size": 32},
        },
    }
    if config_extra:
        raw.update(config_extra)
    config = Config.from_dict(raw)
    app_obj = PixelBufferApp(
        config,
        pixels_service=PixelsService(registry),
        session_store=MemorySessionStore({"ck": "omero-key-1"}),
    )
    client = TestClient(
        TestServer(app_obj.make_app()), loop=asyncio.get_running_loop()
    )
    await client.start_server()
    return app_obj, client


class TestHttpBurst:
    async def test_dzi_burst_matches_native_bytes_and_etags(
        self, tmp_path
    ):
        """A concurrent DZI row burst (the batcher fuses what
        coalesces) serves bytes + ETags identical to sequential
        native /render requests for the same tiles."""
        app_obj, client = await _make_app(tmp_path)
        try:
            c = "1|0:4095$FF0000,2|0:4095$00FF00"
            native = {}
            for col in range(4):
                r = await client.get(
                    f"/render/1/1/0/0?x={32*col}&y=0&w=32&h=32&c={c}",
                    headers=AUTH,
                )
                assert r.status == 200
                native[col] = (await r.read(), r.headers.get("ETag"))
            # max level for 128x96 is 7; level 7 = resolution 0
            burst = await asyncio.gather(*[
                client.get(
                    f"/dzi/1_files/7/{col}_0.png?c={c}&z=1",
                    headers=AUTH,
                )
                for col in range(4)
            ])
            for col, resp in enumerate(burst):
                assert resp.status == 200
                body = await resp.read()
                assert body == native[col][0]
                assert resp.headers.get("ETag") == native[col][1]
        finally:
            await client.close()
