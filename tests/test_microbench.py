"""Kernel-only microbench (runtime/microbench) — shape/correctness on
the CPU backend with tiny sizes; the real numbers come from bench.py's
bounded device child on TPU."""

import zlib

import numpy as np
import pytest

from omero_ms_pixel_buffer_tpu.runtime.microbench import (
    project_throughput,
    run_microbench,
    synth_tiles,
)


@pytest.fixture(scope="module")
def micro():
    # iters >= 3: _time_steady takes the MEDIAN, so one scheduler
    # hiccup can't masquerade as the kernel cost — with a single
    # iteration a ~17 ms stall on this 8 KB payload rounds the GB/s
    # metric to 0.0 and flakes the positivity assertion below
    return run_microbench(
        batch=4, tile=32, plane=128, iters_filter=3, iters_deflate=3
    )


class TestRunMicrobench:
    def test_metrics_present_and_positive(self, micro):
        for key in (
            "filter_gbps",         # 32x32 u16 fits the Pallas cap
            "filter_gbps_xla",
            "deflate_gbps",
            "deflate_ms_per_batch",
            "deflate_ratio_vs_host",
            "device_bytes_per_tile",
            "host_bytes_per_tile",
            "batch_ms_steady",
            "chain_tiles_per_sec_compute",
            "pack_gbps",
        ):
            assert micro[key] > 0, key

    def test_stage_breakdown_present(self, micro):
        sb = micro["stage_breakdown"]
        for key in ("h2d_ms", "compute_ms", "d2h_ms", "pack_gbps"):
            assert key in sb, key
            assert sb[key] >= 0
        assert sb["compute_ms"] > 0


class TestPinnedPackerComparison:
    """The acceptance pin for the packer replacement: on THIS backend
    (CPU in CI), the scan packer must beat the legacy gather packer it
    replaced — the algorithmic gap (no argsort, no 24-wide windows per
    128 output bits) shows on every backend."""

    def test_scan_packer_faster_than_gather(self):
        import time

        import jax
        import numpy as np

        from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
            _lane_tokens,
            _pack_bits_gather,
            _pack_bits_scan,
            _packing_maxbits,
        )

        rng = np.random.default_rng(7)
        payloads = rng.integers(0, 256, (2, 65536)).astype(np.uint8)
        bits, nbits = jax.jit(jax.vmap(_lane_tokens))(payloads)
        jax.block_until_ready((bits, nbits))
        maxbits = _packing_maxbits(payloads.shape[1])

        def timed(pack):
            fn = jax.jit(jax.vmap(lambda b, n: pack(b, n, maxbits)))
            jax.block_until_ready(fn(bits, nbits))  # compile
            samples = []
            for _ in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(bits, nbits))
                samples.append(time.perf_counter() - t0)
            return sorted(samples)[1]

        t_scan = timed(_pack_bits_scan)
        t_gather = timed(_pack_bits_gather)
        assert t_scan < t_gather, (
            f"scan {t_scan * 1e3:.1f} ms not faster than "
            f"gather {t_gather * 1e3:.1f} ms"
        )

    def test_device_streams_decode_and_ratio_is_honest(self, micro):
        # the ratio must come from real, decodable streams: rebuild the
        # same payloads and pin one lane end-to-end
        from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
            deflate_filtered_batch,
        )
        from omero_ms_pixel_buffer_tpu.ops.pallas.filter import (
            filter_tiles,
        )

        tiles = synth_tiles(4, 32, 32, seed=5)
        filtered = filter_tiles(tiles, "up")
        streams, lengths = deflate_filtered_batch(filtered, 32, 1 + 64)
        streams, lengths = np.asarray(streams), np.asarray(lengths)
        payload = np.asarray(filtered)[0, :32, : 1 + 64].tobytes()
        assert zlib.decompress(
            streams[0][: lengths[0]].tobytes()
        ) == payload
        # device fixed-Huffman RLE trails host dynamic Huffman but must
        # stay in the same ballpark on run-heavy filtered content
        assert 0.5 < micro["deflate_ratio_vs_host"] < 4.0

    def test_compression_on_run_heavy_content(self):
        # noisy 16-bit content defeats RLE at tiny tiles (honest, and
        # recorded as-is in the ratio); run-heavy content must compress
        from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
            deflate_filtered_batch,
        )
        from omero_ms_pixel_buffer_tpu.ops.pallas.filter import (
            filter_tiles,
        )

        tiles = np.full((4, 32, 32), 777, np.uint16)  # flat field
        filtered = filter_tiles(tiles, "up")
        _, lengths = deflate_filtered_batch(filtered, 32, 1 + 64)
        assert np.asarray(lengths).mean() < 0.2 * 32 * (1 + 64)


class TestProjection:
    def test_compute_and_link_bound_projections(self, micro):
        proj = project_throughput(micro, link_mbps=10.0)
        colo = proj["projected_colocated_tiles_per_sec"]
        tun = proj["projected_tunnel_tiles_per_sec"]
        assert 0 < tun <= colo  # a 10 MB/s link can only slow it down
        compute_bound = micro["chain_tiles_per_sec_compute"]
        assert colo <= compute_bound * 1.01 + 0.2  # rounding slack

    def test_no_link_means_no_tunnel_projection(self, micro):
        proj = project_throughput(micro, link_mbps=None)
        assert "projected_tunnel_tiles_per_sec" not in proj
        assert proj["projected_colocated_tiles_per_sec"] > 0

    def test_incomplete_micro_yields_empty(self):
        assert project_throughput({"batch": 4}, 10.0) == {}


class TestDynamicHuffmanMetrics:
    """r12: the dynamic-Huffman ratio pin and the emit op-count
    comparison ride the microbench so BENCH records them per round."""

    def test_dynamic_ratio_present_and_bounded(self, micro):
        # the acceptance pin, asserted at the test fixture's size too:
        # <= 1.10x host zlib-6 on the rendered-RGB fixture (the
        # fixed-Huffman stream pays ~1.4x there, recorded alongside)
        assert micro["deflate_ratio_vs_host_dynamic"] <= 1.10
        assert (
            micro["deflate_ratio_vs_host_rle_rgb"]
            > micro["deflate_ratio_vs_host_dynamic"]
        )
        assert micro["deflate_dynamic_gbps"] > 0

    def test_emit_op_counts_pinned(self, micro):
        ops = micro["emit_ops_per_token"]
        assert ops["dense"] > ops["sp"]
        assert ops["reduction_x"] >= 4
