"""One-time operator warning when LZW tiles decode in pure Python
(VERDICT r2 ask #8 / r3 weak #7): without the native engine the
sequential LZW path is a seconds-per-tile cliff that must be loud
exactly once, not silent and not per-block."""

import logging

import numpy as np

from omero_ms_pixel_buffer_tpu.io import ometiff
from omero_ms_pixel_buffer_tpu.io.ometiff import (
    OmeTiffPixelBuffer,
    write_ome_tiff,
)

rng = np.random.default_rng(83)


def _fixture(tmp_path):
    img = rng.integers(0, 255, (1, 1, 1, 64, 64), dtype=np.uint8)
    path = str(tmp_path / "lzw.ome.tiff")
    write_ome_tiff(path, img, tile_size=(32, 32), compression="lzw")
    return path


def test_warns_once_without_native(tmp_path, monkeypatch, caplog):
    monkeypatch.setattr(ometiff, "_pure_lzw_warned", False)
    monkeypatch.setattr(
        "omero_ms_pixel_buffer_tpu.runtime.native.get_engine",
        lambda: None,  # what OMPB_DISABLE_NATIVE=1 produces
    )
    buf = OmeTiffPixelBuffer(_fixture(tmp_path))
    try:
        with caplog.at_level(logging.WARNING):
            buf.get_tile_at(0, 0, 0, 0, 0, 0, 32, 32)
            buf.get_tile_at(0, 0, 0, 0, 32, 32, 32, 32)
    finally:
        buf.close()
    hits = [r for r in caplog.records if "pure-Python" in r.message]
    assert len(hits) == 1
    assert "LZW" in hits[0].message


def test_silent_with_native(tmp_path, monkeypatch, caplog):
    monkeypatch.setattr(ometiff, "_pure_lzw_warned", False)
    monkeypatch.setattr(
        "omero_ms_pixel_buffer_tpu.runtime.native.get_engine",
        lambda: object(),  # engine present
    )
    buf = OmeTiffPixelBuffer(_fixture(tmp_path))
    try:
        with caplog.at_level(logging.WARNING):
            buf.get_tile_at(0, 0, 0, 0, 0, 0, 32, 32)
    finally:
        buf.close()
    assert not [r for r in caplog.records if "pure-Python" in r.message]
