"""Permission-scoped metadata (db/metadata.py): OMERO's read ACL
applied host-side, matching the reference's HQL-inside-the-session
behavior (TileRequestHandler.java:220-241) — an unauthorized image
resolves to None → 404 exactly like a nonexistent one.

Fixture model: two users in group 3 ('lab'), one image owned by user 2.
The group's permission long selects the scenario (-120 private, -104
read-only, ...); sessions map key → user via the ``session`` table.
"""

import pytest

from omero_ms_pixel_buffer_tpu.db.metadata import (
    GROUP_READ,
    PIXELS_QUERY,
    SESSION_USER_QUERY,
    USER_GROUPS_QUERY,
    USER_READ,
    WORLD_READ,
    OmeroPostgresMetadataResolver,
    can_read,
)

from test_postgres import FakePg, pixels_row

PRIVATE, READ_ONLY, READ_ANNOTATE, READ_WRITE = -120, -104, -72, -40


class TestPermissionBits:
    def test_canonical_group_longs(self):
        """The four documented OMERO group-permission values decode to
        the expected read grants."""
        for perms in (PRIVATE, READ_ONLY, READ_ANNOTATE, READ_WRITE):
            assert perms & USER_READ  # owner always reads
            assert not perms & WORLD_READ  # none are public
        assert not PRIVATE & GROUP_READ
        for perms in (READ_ONLY, READ_ANNOTATE, READ_WRITE):
            assert perms & GROUP_READ

    def test_can_read_matrix(self):
        owner_ctx = (2, {3: False}, False)
        member_ctx = (5, {3: False}, False)
        leader_ctx = (6, {3: True}, False)
        admin_ctx = (9, {0: False}, True)
        outsider_ctx = (7, {4: False}, False)
        for perms, member_reads in (
            (PRIVATE, False), (READ_ONLY, True),
            (READ_ANNOTATE, True), (READ_WRITE, True),
        ):
            assert can_read(owner_ctx, 2, 3, perms)
            assert can_read(leader_ctx, 2, 3, perms)
            assert can_read(admin_ctx, 2, 3, perms)
            assert can_read(member_ctx, 2, 3, perms) == member_reads
            assert not can_read(outsider_ctx, 2, 3, perms)
        assert not can_read(None, 2, 3, READ_WRITE)  # dead session

    def test_world_readable(self):
        public = READ_ONLY | WORLD_READ
        assert can_read((7, {4: False}, False), 2, 3, public)


def _fake_omero(group_perms, sessions=None, closed=()):
    """rows_for covering the three ACL queries + the pixels row.

    ``sessions``: key -> user id. user 2 owns image 1 in group 3;
    users 2 and 5 are members of group 3 (5 not a leader), user 6
    leads group 3, user 9 is in 'system'."""
    sessions = sessions or {"alice-key": 2, "bob-key": 5,
                            "lead-key": 6, "admin-key": 9}
    memberships = {
        2: [("3", "f", "lab")],
        5: [("3", "f", "lab")],
        6: [("3", "t", "lab")],
        9: [("0", "f", "system")],
    }

    def rows_for(sql, params):
        if sql == PIXELS_QUERY:
            if params == ["1"]:
                return [pixels_row(owner="2", group="3",
                                   perms=str(group_perms))]
            return []
        if sql == SESSION_USER_QUERY:
            key = params[0]
            if key in closed or key not in sessions:
                return []
            return [(str(sessions[key]),)]
        if sql == USER_GROUPS_QUERY:
            return memberships.get(int(params[0]), [])
        raise AssertionError(f"unexpected SQL: {sql}")

    return rows_for


def _resolver(pg, **kw):
    kw.setdefault("enforce_permissions", True)
    return OmeroPostgresMetadataResolver(
        f"postgresql://omero:pw@127.0.0.1:{pg.port}/omero", **kw
    )


class TestScopedResolution:
    async def test_private_image_cross_user_404(self, loop):
        """The VERDICT 'done' bar: two users, one private image,
        cross-user request → None (404)."""
        async with FakePg(rows_for=_fake_omero(PRIVATE)) as pg:
            r = _resolver(pg)
            assert (
                await r.get_pixels_async(1, session_key="alice-key")
            ) is not None  # owner reads
            assert (
                await r.get_pixels_async(1, session_key="bob-key")
            ) is None  # same group, private -> 404
            await r.close()

    async def test_read_only_group_member_reads(self, loop):
        async with FakePg(rows_for=_fake_omero(READ_ONLY)) as pg:
            r = _resolver(pg)
            assert (
                await r.get_pixels_async(1, session_key="bob-key")
            ) is not None
            await r.close()

    async def test_leader_and_admin_read_private(self, loop):
        async with FakePg(rows_for=_fake_omero(PRIVATE)) as pg:
            r = _resolver(pg)
            for key in ("lead-key", "admin-key"):
                assert (
                    await r.get_pixels_async(1, session_key=key)
                ) is not None
            await r.close()

    async def test_unknown_or_absent_session_denied(self, loop):
        async with FakePg(rows_for=_fake_omero(READ_WRITE)) as pg:
            r = _resolver(pg)
            assert (
                await r.get_pixels_async(1, session_key="nope")
            ) is None
            assert await r.get_pixels_async(1) is None  # keyless
            await r.close()

    async def test_closed_session_denied_within_ttl(self, loop):
        """A destroyed OMERO session (session.closed set) stops
        resolving within session_cache_ttl_s — the revocation bound."""
        async with FakePg(
            rows_for=_fake_omero(READ_WRITE, closed=("alice-key",))
        ) as pg:
            r = _resolver(pg, session_cache_ttl_s=0.0)
            assert (
                await r.get_pixels_async(1, session_key="alice-key")
            ) is None
            await r.close()

    async def test_enforcement_off_preserves_old_contract(self, loop):
        async with FakePg(rows_for=_fake_omero(PRIVATE)) as pg:
            r = _resolver(pg, enforce_permissions=False)
            assert await r.get_pixels_async(1) is not None
            await r.close()

    async def test_unchecked_bypasses_acl_for_buffer_plane(self, loop):
        async with FakePg(rows_for=_fake_omero(PRIVATE)) as pg:
            r = _resolver(pg)
            try:
                # prime the row cache on this loop (get_pixels_unchecked
                # blocks the calling thread, which IS the FakePg loop in
                # this async test)
                assert await r.get_pixels_async(1) is None  # ACL denies
                meta = r.get_pixels_unchecked(1)  # cache, no roundtrip
                assert meta is not None and meta.size_x == 4096
            finally:
                # close on THIS loop (the client's connection lives
                # here; close_sync would leave it open and FakePg's
                # wait_closed() then never returns)
                await r.close()


class TestServiceAutoScoping:
    def test_scoped_registry_becomes_the_metadata_plane(self):
        """PixelsService(OmeroImageSource(...)) alone must not bypass
        ACLs: a registry with a scoped get_pixels is auto-promoted to
        the metadata resolver and receives the session key."""
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            PixelsService,
        )

        calls = []

        class ScopedRegistry:
            def entry(self, image_id):
                return None

            def resolve_path(self, entry):
                return entry["path"]

            def get_pixels(self, image_id, session_key=None):
                calls.append(session_key)
                return None

        svc = PixelsService(ScopedRegistry())
        assert svc.get_pixels(1, session_key="user-key") is None
        assert calls == ["user-key"]
        svc.close()


class TestSyncScopedPath:
    def test_sync_adapter_enforces_and_caches(self):
        """The sync surface (the pipeline's path): verdicts differ per
        caller on the same cached row, and cached ctx+row answer
        without a DB roundtrip."""
        import asyncio
        import threading

        counted = {"n": 0}
        base = _fake_omero(PRIVATE)

        def rows_for(sql, params):
            counted["n"] += 1
            return base(sql, params)

        results = {}
        started = threading.Event()
        stop = threading.Event()

        def server_thread():
            srv_loop = asyncio.new_event_loop()
            asyncio.set_event_loop(srv_loop)

            async def run():
                async with FakePg(rows_for=rows_for) as pg:
                    results["port"] = pg.port
                    started.set()
                    while not stop.is_set():
                        await asyncio.sleep(0.05)

            try:
                srv_loop.run_until_complete(run())
            finally:
                srv_loop.close()

        t = threading.Thread(target=server_thread, daemon=True)
        t.start()
        assert started.wait(5)
        r = OmeroPostgresMetadataResolver(
            f"postgresql://omero:pw@127.0.0.1:{results['port']}/omero",
            enforce_permissions=True,
        )
        try:
            assert r.get_pixels(1, session_key="alice-key") is not None
            assert r.get_pixels(1, session_key="bob-key") is None
            before = counted["n"]
            # cached row + cached session ctx: no further roundtrips
            assert r.get_pixels(1, session_key="alice-key") is not None
            assert r.get_pixels(1, session_key="bob-key") is None
            assert counted["n"] == before
        finally:
            r.close_sync()
            stop.set()
            t.join(timeout=5)
