"""Zarr v3 ``sharding_indexed`` (r14): the shard writer fixture, the
ranged/coalesced read path, byte-identity against unsharded ground
truth through ``read_region`` AND the full tile pipeline, strict
corrupt/truncated-index errors, partial edge shards, and the
one-coalesced-GET-per-shard batched access shape.
"""

import json
import os
import struct

import numpy as np
import pytest

from omero_ms_pixel_buffer_tpu.io import fetch
from omero_ms_pixel_buffer_tpu.io.stores import HTTPStore
from omero_ms_pixel_buffer_tpu.io.zarr import (
    ZarrArray,
    ZarrError,
    ZarrPixelBuffer,
    crc32c,
    write_ngff,
)
from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
    INJECTOR,
    always,
)

from test_io_fetch import RangeHandler, serve

rng = np.random.default_rng(41)
# deliberately NOT shard-aligned: 300x280 with 128x128 shards leaves
# partial edge shards in both axes
IMG = rng.integers(0, 60000, (1, 2, 2, 300, 280), dtype=np.uint16)

CHUNKS = (64, 64)
SHARDS = (128, 128)


@pytest.fixture(autouse=True)
def _clean():
    yield
    INJECTOR.clear()
    BOARD.reset()
    fetch.CONFIG.parallel = True


@pytest.fixture(scope="module")
def roots(tmp_path_factory):
    base = tmp_path_factory.mktemp("sharded_ngff")
    unsharded = str(base / "plain.zarr")
    sharded = str(base / "sharded.zarr")
    write_ngff(unsharded, IMG, chunks=CHUNKS, levels=2,
               zarr_format=3, compressor="zlib")
    write_ngff(sharded, IMG, chunks=CHUNKS, levels=2,
               zarr_format=3, compressor="zlib", shards=SHARDS)
    return unsharded, sharded


REGIONS = [
    (0, 0, 0, 0, 0, 0, 280, 300),        # full plane
    (0, 1, 1, 0, 33, 47, 200, 100),      # unaligned interior
    (1, 0, 0, 0, 10, 10, 100, 80),       # pyramid level
    (0, 0, 1, 0, 250, 280, 30, 20),      # edge-shard corner
    (0, 1, 0, 0, 127, 127, 2, 2),        # shard boundary straddle
]


class TestShardedReads:
    def test_metadata_parses(self, roots):
        _, sharded = roots
        arr = ZarrArray(os.path.join(sharded, "0"))
        assert arr.sharding is not None
        assert arr.chunks == (1, 1, 1) + CHUNKS
        assert arr.sharding.shard_shape == (1, 1, 1) + SHARDS
        assert arr.sharding.ratio == (1, 1, 1, 2, 2)
        assert arr.sharding.index_nbytes == 4 * 16 + 4

    @pytest.mark.parametrize("region", REGIONS)
    def test_byte_identity_vs_unsharded(self, roots, region):
        unsharded, sharded = roots
        bu = ZarrPixelBuffer(unsharded)
        bs = ZarrPixelBuffer(sharded)
        lv, z, c, t, x, y, w, h = region
        a = bu.get_tile_at(lv, z, c, t, x, y, w, h)
        b = bs.get_tile_at(lv, z, c, t, x, y, w, h)
        assert a.tobytes() == b.tobytes()

    def test_read_tiles_batch_identity(self, roots):
        unsharded, sharded = roots
        bu = ZarrPixelBuffer(unsharded)
        bs = ZarrPixelBuffer(sharded)
        coords = [
            (0, 0, 0, 0, 0, 140, 150),
            (0, 1, 0, 140, 0, 140, 150),
            (1, 0, 0, 0, 150, 140, 150),
            (0, 0, 0, 0, 0, 140, 150),  # duplicate lane
        ]
        for a, b in zip(
            bu.read_tiles(coords), bs.read_tiles(coords)
        ):
            assert a.tobytes() == b.tobytes()

    def test_sequential_escape_identity(self, roots):
        _, sharded = roots
        want = ZarrPixelBuffer(sharded).get_tile_at(
            0, 1, 1, 0, 33, 47, 200, 100
        )
        fetch.CONFIG.parallel = False
        got = ZarrPixelBuffer(sharded).get_tile_at(
            0, 1, 1, 0, 33, 47, 200, 100
        )
        assert want.tobytes() == got.tobytes()

    def test_absent_shard_reads_fill_value(self, roots, tmp_path):
        _, sharded = roots
        import shutil

        clone = str(tmp_path / "clone.zarr")
        shutil.copytree(sharded, clone)
        os.remove(os.path.join(clone, "0", "c", "0", "0", "0", "0", "0"))
        buf = ZarrPixelBuffer(clone)
        tile = buf.get_tile_at(0, 0, 0, 0, 0, 0, 128, 128)
        assert (tile == 0).all()
        # neighbours in OTHER shards are untouched
        other = buf.get_tile_at(0, 0, 0, 0, 128, 0, 128, 128)
        assert np.array_equal(other, IMG[0, 0, 0, 0:128, 128:256])

    def test_missing_inner_chunk_sentinel(self, roots, tmp_path):
        _, sharded = roots
        import shutil

        clone = str(tmp_path / "clone2.zarr")
        shutil.copytree(sharded, clone)
        shard = os.path.join(clone, "0", "c", "0", "0", "0", "0", "0")
        blob = open(shard, "rb").read()
        idx_nb = 4 * 16 + 4
        body, index = blob[:-idx_nb], blob[-idx_nb:-4]
        entries = list(
            struct.unpack("<8Q", index)
        )
        entries[0] = entries[1] = (1 << 64) - 1  # chunk 0 -> absent
        new_index = struct.pack("<8Q", *entries)
        new_index += struct.pack("<I", crc32c(new_index))
        open(shard, "wb").write(body + new_index)
        buf = ZarrPixelBuffer(clone)
        tile = buf.get_tile_at(0, 0, 0, 0, 0, 0, 128, 128)
        # inner chunk (0,0) filled; the shard's other chunks intact
        assert (tile[:64, :64] == 0).all()
        assert np.array_equal(
            tile[:64, 64:128], IMG[0, 0, 0, 0:64, 64:128]
        )

    def test_served_over_http_ranged(self, roots):
        unsharded, sharded = roots
        server = serve(os.path.dirname(sharded), RangeHandler)
        try:
            url = (
                f"http://127.0.0.1:{server.server_address[1]}/"
                f"{os.path.basename(sharded)}"
            )
            buf = ZarrPixelBuffer(url)
            tile = buf.get_tile_at(0, 0, 0, 0, 33, 47, 200, 100)
            assert np.array_equal(
                tile, IMG[0, 0, 0, 47:147, 33:233]
            )
            # the shard bodies were fetched with RANGED requests
            ranged = [r for _, r in RangeHandler.requests if r]
            assert len(ranged) >= 2  # index footers + inner spans
        finally:
            server.shutdown()

    def test_one_coalesced_get_per_shard(self, roots):
        _, sharded = roots
        server = serve(os.path.dirname(sharded), RangeHandler)
        try:
            url = (
                f"http://127.0.0.1:{server.server_address[1]}/"
                f"{os.path.basename(sharded)}"
            )
            buf = ZarrPixelBuffer(url)
            RangeHandler.reset()
            # one 128x128 tile == one full shard == 4 inner chunks,
            # written contiguously -> ONE index GET + ONE body GET
            buf.get_tile_at(0, 0, 0, 0, 0, 0, 128, 128)
            shard_reqs = [
                (p, r) for p, r in RangeHandler.requests
                if p.endswith("/c/0/0/0/0/0")
            ]
            assert len(shard_reqs) == 2
            kinds = sorted(
                "suffix" if r.startswith("bytes=-") else "span"
                for _, r in shard_reqs
            )
            assert kinds == ["span", "suffix"]
        finally:
            server.shutdown()


class TestStrictIndexErrors:
    def _mini_sharded(self, tmp_path, mutate=None, index_tail=True):
        root = str(tmp_path / "mini.zarr")
        img = rng.integers(0, 255, (1, 1, 1, 64, 64), dtype=np.uint8)
        write_ngff(root, img, chunks=(32, 32), levels=1,
                   zarr_format=3, compressor=None, shards=(64, 64))
        shard = os.path.join(root, "0", "c", "0", "0", "0", "0", "0")
        if mutate is not None:
            blob = bytearray(open(shard, "rb").read())
            mutate(blob)
            open(shard, "wb").write(bytes(blob))
        return root, img

    def test_round_trip_uncompressed(self, tmp_path):
        root, img = self._mini_sharded(tmp_path)
        buf = ZarrPixelBuffer(root)
        assert np.array_equal(
            buf.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64),
            img[0, 0, 0],
        )

    def test_corrupt_index_crc_raises(self, tmp_path):
        def flip(blob):
            blob[-6] ^= 0xFF  # inside the index body

        root, _ = self._mini_sharded(tmp_path, mutate=flip)
        buf = ZarrPixelBuffer(root)
        with pytest.raises(ZarrError, match="crc32c"):
            buf.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)

    def test_truncated_shard_raises(self, tmp_path):
        def chop(blob):
            del blob[50:]  # shorter than the 68-byte index itself

        root, _ = self._mini_sharded(tmp_path, mutate=chop)
        buf = ZarrPixelBuffer(root)
        with pytest.raises(ZarrError, match="[Tt]runcated"):
            buf.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)

    def test_partially_chopped_shard_fails_crc(self, tmp_path):
        def chop(blob):
            # still longer than the index: the suffix window shifts
            # onto chunk bytes, which the index checksum catches
            del blob[-10:]

        root, _ = self._mini_sharded(tmp_path, mutate=chop)
        buf = ZarrPixelBuffer(root)
        with pytest.raises(ZarrError, match="crc32c"):
            buf.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)

    def test_implausible_entry_raises(self, tmp_path):
        def lie(blob):
            # inner chunk 0 claims a gigabyte
            idx_nb = 4 * 16 + 4
            index = bytearray(blob[-idx_nb:-4])
            index[8:16] = struct.pack("<Q", 1 << 30)
            index += struct.pack("<I", crc32c(bytes(index)))
            blob[-idx_nb:] = index

        root, _ = self._mini_sharded(tmp_path, mutate=lie)
        buf = ZarrPixelBuffer(root)
        with pytest.raises(ZarrError, match="implausible"):
            buf.get_tile_at(0, 0, 0, 0, 0, 0, 64, 64)

    def test_truncated_inner_span_raises(self, tmp_path):
        def lie(blob):
            # inner chunk 0's nbytes exceeds the shard body by a bit
            idx_nb = 4 * 16 + 4
            index = bytearray(blob[-idx_nb:-4])
            (nb,) = struct.unpack("<Q", index[8:16])
            index[8:16] = struct.pack("<Q", nb + 64)
            index += struct.pack("<I", crc32c(bytes(index)))
            blob[-idx_nb:] = index

        root, _ = self._mini_sharded(tmp_path, mutate=lie)
        buf = ZarrPixelBuffer(root)
        with pytest.raises(ZarrError):
            buf.get_tile_at(0, 0, 0, 0, 0, 0, 32, 32)

    def _meta(self, tmp_path, codecs):
        path = str(tmp_path / "arr")
        os.makedirs(path)
        meta = {
            "zarr_format": 3, "node_type": "array", "shape": [64, 64],
            "data_type": "uint8",
            "chunk_grid": {"name": "regular",
                           "configuration": {"chunk_shape": [64, 64]}},
            "chunk_key_encoding": {"name": "default"},
            "fill_value": 0,
            "codecs": codecs,
        }
        json.dump(meta, open(os.path.join(path, "zarr.json"), "w"))
        return path

    def test_malformed_config_rejected(self, tmp_path):
        path = self._meta(tmp_path, [
            {"name": "sharding_indexed", "configuration": {}}
        ])
        with pytest.raises(ZarrError, match="shard"):
            ZarrArray(path)

    def test_non_dividing_inner_rejected(self, tmp_path):
        path = self._meta(tmp_path, [
            {"name": "sharding_indexed",
             "configuration": {"chunk_shape": [48, 48]}}
        ])
        with pytest.raises(ZarrError, match="divide"):
            ZarrArray(path)

    def test_nested_sharding_rejected(self, tmp_path):
        path = self._meta(tmp_path, [
            {"name": "sharding_indexed",
             "configuration": {
                 "chunk_shape": [32, 32],
                 "codecs": [{"name": "sharding_indexed",
                             "configuration": {"chunk_shape": [16, 16]}}],
             }}
        ])
        with pytest.raises(ZarrError, match="nested"):
            ZarrArray(path)

    def test_compressed_index_rejected(self, tmp_path):
        path = self._meta(tmp_path, [
            {"name": "sharding_indexed",
             "configuration": {
                 "chunk_shape": [32, 32],
                 "index_codecs": [{"name": "bytes"},
                                  {"name": "gzip"}],
             }}
        ])
        with pytest.raises(ZarrError, match="index_codecs"):
            ZarrArray(path)

    def test_index_location_start_reads(self, tmp_path):
        """A hand-packed START-located shard (the in-tree writer only
        emits 'end'): index first, inner-chunk offsets ABSOLUTE
        within the object (so they include the index bytes)."""
        img = rng.integers(0, 255, (64, 64), dtype=np.uint8)
        path = self._meta(tmp_path, [
            {"name": "sharding_indexed",
             "configuration": {
                 "chunk_shape": [32, 32],
                 "codecs": [{"name": "bytes",
                             "configuration": {"endian": "little"}}],
                 "index_codecs": [
                     {"name": "bytes",
                      "configuration": {"endian": "little"}},
                     {"name": "crc32c"},
                 ],
                 "index_location": "start",
             }}
        ])
        idx_nb = 4 * 16 + 4
        chunks = []
        entries = []
        off = idx_nb
        for iy in range(2):
            for ix in range(2):
                raw = img[iy * 32:(iy + 1) * 32,
                          ix * 32:(ix + 1) * 32].tobytes()
                entries.append((off, len(raw)))
                chunks.append(raw)
                off += len(raw)
        index = b"".join(
            struct.pack("<QQ", o, n) for o, n in entries
        )
        index += struct.pack("<I", crc32c(index))
        cdir = os.path.join(path, "c", "0")
        os.makedirs(cdir)
        with open(os.path.join(cdir, "0"), "wb") as f:
            f.write(index + b"".join(chunks))
        arr = ZarrArray(path)
        assert not arr.sharding.index_at_end
        out = arr.read_region((0, 0), (64, 64))
        assert np.array_equal(out, img)

    def test_bad_index_location_rejected(self, tmp_path):
        path = self._meta(tmp_path, [
            {"name": "sharding_indexed",
             "configuration": {"chunk_shape": [32, 32],
                               "index_location": "middle"}}
        ])
        with pytest.raises(ZarrError, match="index_location"):
            ZarrArray(path)


class TestFullTilePath:
    """Sharded and unsharded images are indistinguishable through the
    COMPLETE pipeline (resolve -> batched read -> encode)."""

    def _pipe(self, root):
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            ImageRegistry,
            PixelsService,
        )
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        registry = ImageRegistry()
        registry.add(1, root)
        service = PixelsService(registry)
        return TilePipeline(service, use_device=False)

    def _ctx(self, fmt="png", x=64, y=32, w=160, h=144):
        from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

        return TileCtx(
            image_id=1, z=1, c=1, t=0,
            region=RegionDef(x, y, w, h), format=fmt,
        )

    @pytest.mark.parametrize("fmt", [None, "png", "tif"])
    def test_pipeline_bytes_identical(self, roots, fmt):
        unsharded, sharded = roots
        a = self._pipe(unsharded).handle(self._ctx(fmt))
        b = self._pipe(sharded).handle(self._ctx(fmt))
        assert a is not None
        assert a == b

    def test_batch_path_identical(self, roots):
        unsharded, sharded = roots
        ctxs = [
            self._ctx("png", x=0, y=0, w=128, h=128),
            self._ctx("png", x=128, y=128, w=128, h=128),
            self._ctx(None, x=32, y=32, w=200, h=200),
        ]
        pa = self._pipe(unsharded)
        pb = self._pipe(sharded)
        ra = pa.handle_batch(ctxs)
        ctxs2 = [
            self._ctx("png", x=0, y=0, w=128, h=128),
            self._ctx("png", x=128, y=128, w=128, h=128),
            self._ctx(None, x=32, y=32, w=200, h=200),
        ]
        rb = pb.handle_batch(ctxs2)
        assert all(r is not None for r in ra)
        assert ra == rb


@pytest.mark.resilience
class TestShardedChaos:
    def test_range_fault_falls_back_byte_identical(self, roots):
        _, sharded = roots
        server = serve(os.path.dirname(sharded), RangeHandler)
        try:
            url = (
                f"http://127.0.0.1:{server.server_address[1]}/"
                f"{os.path.basename(sharded)}"
            )
            from omero_ms_pixel_buffer_tpu.io.stores import StoreError

            want = IMG[0, 0, 0, 0:128, 0:128]
            INJECTOR.install("io.range-get", always(
                lambda: StoreError("injected range outage")
            ))
            buf = ZarrPixelBuffer(url)
            tile = buf.get_tile_at(0, 0, 0, 0, 0, 0, 128, 128)
            # every ranged read (index + inner spans) degraded to
            # whole-shard GETs; pixels identical
            assert np.array_equal(tile, want)
            whole = [r for _, r in RangeHandler.requests if r is None]
            assert len(whole) >= 1
        finally:
            server.shutdown()

    def test_dead_store_surfaces_unavailable(self):
        import socket

        from omero_ms_pixel_buffer_tpu.io.stores import (
            StoreError,
            StoreUnavailableError,
        )

        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        with pytest.raises(
            (StoreError, StoreUnavailableError)
        ) as ei:
            for _ in range(30):
                try:
                    ZarrPixelBuffer(f"http://127.0.0.1:{port}/x.zarr")
                except StoreUnavailableError:
                    raise
                except StoreError:
                    continue
        assert isinstance(ei.value, StoreUnavailableError)

    def test_hung_store_bounded(self, roots):
        import time as _time

        _, sharded = roots
        server = serve(os.path.dirname(sharded), RangeHandler)
        RangeHandler.delay_s = 5.0
        try:
            url = (
                f"http://127.0.0.1:{server.server_address[1]}/"
                f"{os.path.basename(sharded)}"
            )
            from omero_ms_pixel_buffer_tpu.io.stores import StoreError

            store = HTTPStore(url, timeout_s=0.3)
            t0 = _time.monotonic()
            with pytest.raises(StoreError):
                ZarrArray(store, "0")
            assert _time.monotonic() - t0 < 4.0
        finally:
            RangeHandler.delay_s = 0.0
            server.shutdown()
