"""The serving mesh: TilePipeline.handle_batch end-to-end on the
8-virtual-device CPU mesh (conftest), byte-identical to single-device.

VERDICT r2 item 3: the mesh must actually serve tiles — device-PNG
bucket groups ride ``sharded_batch_filter`` (data parallel over the
mesh) and plane-sized PNG lanes ride ``distributed_filter_plane``
(rows sharded, one-row halo exchange), replacing the reference's
worker-pool parallelism (PixelBufferMicroserviceVerticle.java:224-233)
with ICI-resident parallelism."""

import io

import numpy as np
import pytest
from PIL import Image

from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

rng = np.random.default_rng(29)

# 1200 wide: wider than the largest default bucket (1024), so a
# full-plane PNG request cannot take the bucket path and must go
# space-parallel when a mesh is present
IMG = rng.integers(0, 60000, (1, 1, 2, 160, 1200), dtype=np.uint16)


def _ctx(z=0, x=0, y=0, w=64, h=64, fmt="png"):
    return TileCtx(
        image_id=1, z=z, c=0, t=0, region=RegionDef(x, y, w, h),
        format=fmt, omero_session_key="k",
    )


@pytest.fixture(scope="module")
def service(tmp_path_factory):
    root = tmp_path_factory.mktemp("mesh-serving")
    path = str(root / "img.ome.tiff")
    write_ome_tiff(path, IMG, tile_size=(64, 64))
    registry = ImageRegistry()
    registry.add(1, path)
    svc = PixelsService(registry)
    yield svc
    svc.close()


@pytest.fixture
def pipes(service):
    import jax

    assert len(jax.devices()) == 8, "conftest should provide 8 devices"
    multi = TilePipeline(service, engine="device")
    single = TilePipeline(service, engine="device")
    single.mesh = None  # force the single-device stages
    return multi, single


BATCH = [
    _ctx(x=0, y=0, w=64, h=64),
    _ctx(x=128, y=32, w=100, h=80),   # non-bucket-aligned
    _ctx(z=1, x=1150, y=110, w=50, h=50),  # edge tile
    _ctx(x=0, y=0, w=256, h=128),     # larger bucket
    _ctx(x=64, y=0, w=64, h=64, fmt=None),   # raw lane
    _ctx(x=64, y=64, w=64, h=64, fmt="tif"),  # tif lane
    _ctx(w=0, h=0),                   # full plane -> space parallel
]


class TestMeshServing:
    def test_mesh_auto_builds(self, pipes):
        multi, single = pipes
        assert multi._get_mesh() is not None
        assert dict(multi._get_mesh().shape) == {"data": 8}
        assert single._get_mesh() is None

    def test_batch_byte_identical_to_single_device(self, pipes):
        multi, single = pipes
        out_multi = multi.handle_batch([_c for _c in BATCH])
        out_single = single.handle_batch([_c for _c in BATCH])
        assert all(o is not None for o in out_multi)
        # bucketed/raw/tif lanes: identical stages -> identical bytes
        for i in range(6):
            assert out_multi[i] == out_single[i], f"lane {i} differs"

    def test_full_plane_pixels_exact(self, pipes):
        multi, _ = pipes
        out = multi.handle_batch([_ctx(w=0, h=0)])
        png = np.array(Image.open(io.BytesIO(out[0])))
        np.testing.assert_array_equal(png, IMG[0, 0, 0])

    def test_bucketed_pixels_exact(self, pipes):
        multi, _ = pipes
        out = multi.handle_batch([_ctx(x=128, y=32, w=100, h=80)])
        png = np.array(Image.open(io.BytesIO(out[0])))
        np.testing.assert_array_equal(
            png, IMG[0, 0, 0, 32:112, 128:228]
        )

    def test_plane_cache_superseded_by_mesh(self, service):
        """With a mesh the DP bucket path must serve lanes the plane
        cache would otherwise claim (single-chip residency would idle
        the other chips)."""
        multi = TilePipeline(service, engine="device", use_plane_cache=True)
        assert multi._get_mesh() is not None
        out = multi.handle_batch([_ctx(x=0, y=0, w=64, h=64)])
        assert out[0] is not None
        assert multi._plane_cache is None  # never built

    def test_odd_batch_padding(self, pipes):
        """Lane counts not divisible by the mesh size pad and slice."""
        multi, single = pipes
        ctxs = [
            _ctx(x=64 * i, y=0, w=64, h=64) for i in range(13)
        ]
        out_multi = multi.handle_batch(list(ctxs))
        out_single = single.handle_batch(list(ctxs))
        assert out_multi == out_single


# ---------------------------------------------------------------------------
# background mesh health probe (config mesh.probe-interval-ms)
# ---------------------------------------------------------------------------

class TestBackgroundMeshProbe:
    """A recovered chip must rejoin the mesh BEFORE the next dispatch
    has to fail — probe_open/MeshProber close the reactive-only
    degradation gap."""

    def _run(self, mesh):
        import jax
        import jax.numpy as jnp

        from omero_ms_pixel_buffer_tpu.parallel.sharding import (
            shard_batch,
        )

        n = mesh.shape["data"]
        x = jnp.arange(n * 4, dtype=jnp.int32).reshape(n, 4)
        return jax.block_until_ready(shard_batch(mesh, x) + 1)

    @pytest.mark.resilience
    def test_recovered_chip_rejoins_without_a_failed_batch(self):
        import jax

        from omero_ms_pixel_buffer_tpu.parallel.mesh import MeshManager
        from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
        from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
            INJECTOR,
            first_n,
        )

        devices = jax.devices()
        assert len(devices) == 8
        sick = devices[3]
        INJECTOR.clear()
        try:
            # one dispatch failure triggers the reactive probe; the
            # sick chip fails exactly that one probe, then heals
            INJECTOR.install(
                "device.mesh-dispatch",
                first_n(1, RuntimeError("ICI wedged")),
            )
            INJECTOR.install(
                f"device.chip:{sick.id}",
                first_n(1, RuntimeError("chip down")),
            )
            mgr = MeshManager(devices=devices)
            mgr.dispatch(self._run)  # degrades to the 7 survivors
            assert mgr.last_dispatch["n_devices"] == 7
            assert mgr.mesh().devices.size == 7

            # the background pass probes ONLY the excluded chip,
            # which now answers -> breaker heals -> full width again,
            # and no serving batch ever saw the recovery
            healed = mgr.probe_open()
            assert healed == 1
            assert mgr.mesh().devices.size == 8
            mgr.dispatch(self._run)
            assert mgr.last_dispatch["n_devices"] == 8
        finally:
            INJECTOR.clear()
            BOARD.reset()

    @pytest.mark.resilience
    def test_probe_open_skips_healthy_chips(self):
        import jax

        from omero_ms_pixel_buffer_tpu.parallel.mesh import MeshManager
        from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
        from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
            INJECTOR,
        )

        INJECTOR.clear()
        try:
            mgr = MeshManager(devices=jax.devices())
            assert mgr.probe_open() == 0  # whole mesh: free no-op
            for dev in mgr._devices:
                assert INJECTOR.calls(
                    f"device.chip:{dev.id}"
                ) == 0  # no probe traffic touched healthy chips
        finally:
            INJECTOR.clear()
            BOARD.reset()

    @pytest.mark.resilience
    def test_prober_thread_restores_width(self):
        import time

        import jax

        from omero_ms_pixel_buffer_tpu.parallel.mesh import (
            MeshManager,
            MeshProber,
        )
        from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
        from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
            INJECTOR,
            first_n,
        )

        devices = jax.devices()
        sick = devices[5]
        INJECTOR.clear()
        try:
            INJECTOR.install(
                "device.mesh-dispatch", first_n(1, RuntimeError("down"))
            )
            INJECTOR.install(
                f"device.chip:{sick.id}",
                first_n(1, RuntimeError("down")),
            )
            mgr = MeshManager(devices=devices)
            mgr.dispatch(self._run)
            assert mgr.mesh().devices.size == 7
            prober = MeshProber(lambda: mgr, interval_s=0.02)
            prober.start()
            try:
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    if len(mgr.healthy_devices()) == 8:
                        break
                    time.sleep(0.02)
                assert len(mgr.healthy_devices()) == 8
            finally:
                prober.stop()
            mgr.dispatch(self._run)
            assert mgr.last_dispatch["n_devices"] == 8
        finally:
            INJECTOR.clear()
            BOARD.reset()
