"""OmeroImageSource: imageId → storage path from the OMERO database +
data dir (the OmeroFilePathResolver analog, db/resolver.py) against a
fake Postgres and a synthesized ``omero.data.dir``.

Covers every layout the resolver walks: managed-repository OME-TIFF,
NGFF hierarchy (root and member-file rows), legacy path+name, ROMIO
fan-out plane files, generated pyramids — and the end-to-end claim:
a PixelsService over only (db uri, data dir) serves pixel-exact tiles
with no JSON registry.
"""

import os

import numpy as np
import pytest

from omero_ms_pixel_buffer_tpu.db.resolver import (
    FILESET_FILES_QUERY,
    PIXELS_ID_QUERY,
    REPO_ROOT_QUERY,
    OmeroImageSource,
    pixels_fanout_path,
)
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import PixelsService
from omero_ms_pixel_buffer_tpu.io.zarr import write_ngff

from test_postgres import FakePg

rng = np.random.default_rng(21)
TIFF_IMG = rng.integers(0, 60000, (1, 1, 1, 96, 128), dtype=np.uint16)
ZARR_IMG = rng.integers(0, 60000, (1, 1, 1, 64, 80), dtype=np.uint16)
ROMIO_IMG = rng.integers(0, 60000, (1, 1, 1, 48, 64), dtype=np.uint16)


class TestFanout:
    def test_small_id_is_flat(self):
        assert pixels_fanout_path("/data", 7) == "/data/Pixels/7"
        assert pixels_fanout_path("/data", 999) == "/data/Pixels/999"

    def test_thousands_fanout(self):
        # ome.io.nio.AbstractFileSystemService: one Dir-%03d level per
        # division by 1000
        assert pixels_fanout_path("/data", 1000) == (
            "/data/Pixels/Dir-001/1000"
        )
        assert pixels_fanout_path("/data", 1234567) == (
            "/data/Pixels/Dir-001/Dir-234/1234567"
        )


@pytest.fixture
def data_dir(tmp_path):
    """A synthesized omero.data.dir with one image per layout."""
    d = tmp_path / "OMERO"
    # image 1: FS import, managed repository OME-TIFF
    mrepo = d / "ManagedRepository" / "demo_2" / "2026-07"
    mrepo.mkdir(parents=True)
    write_ome_tiff(
        str(mrepo / "img.ome.tiff"), TIFF_IMG, tile_size=(64, 64)
    )
    # image 2: FS import, NGFF hierarchy in the managed repository
    write_ngff(
        str(mrepo / "plate.ome.zarr"), ZARR_IMG, chunks=(32, 32),
        levels=1,
    )
    # image 3: pre-FS ROMIO plane file (raw big-endian planes)
    romio = d / "Pixels"
    romio.mkdir(parents=True)
    (romio / "301").write_bytes(
        ROMIO_IMG[0, 0, 0].astype(">u2").tobytes()
    )
    # image 4: generated pyramid next to the (absent) ROMIO file
    write_ome_tiff(
        str(romio / "401_pyramid"), TIFF_IMG, tile_size=(64, 64)
    )
    # image 5: legacy (pre-FS) original file under the data dir
    legacy = d / "legacy_user" / "2016-01"
    legacy.mkdir(parents=True)
    write_ome_tiff(
        str(legacy / "old.tiff"), TIFF_IMG, tile_size=(64, 64)
    )
    # image 6: FS-imported non-TIFF original (.czi) — OMERO generated
    # a pyramid for it; the pyramid, not the original, must serve
    # (ADVICE r5 regression)
    (mrepo / "scan.czi").write_bytes(b"ZISRAWFILE not a tiff")
    write_ome_tiff(
        str(romio / "601_pyramid"), TIFF_IMG, tile_size=(64, 64)
    )
    # image 7: non-TIFF original, no pyramid, no ROMIO file —
    # unresolvable, never handed to the TIFF reader
    (mrepo / "slide.ndpi").write_bytes(b"NDPI not a tiff")
    # image 8: TIFF container with a non-.tif suffix (Aperio-style):
    # must serve directly, NOT fall through to pyramid/404
    write_ome_tiff(str(mrepo / "scan.svs"), TIFF_IMG, tile_size=(64, 64))
    return str(d)


def _rows_for(data_dir):
    """The OMERO rows backing the five images in ``data_dir``."""

    def rows(sql, params):
        if sql == FILESET_FILES_QUERY:
            return {
                "1": [("demo_2/2026-07/", "img.ome.tiff", "repo-uuid",
                       "101")],
                # NGFF filesets list every member file; the resolver
                # must walk up to the .zarr root
                "2": [
                    ("demo_2/2026-07/plate.ome.zarr/", ".zattrs",
                     "repo-uuid", "201"),
                    ("demo_2/2026-07/plate.ome.zarr/0/", ".zarray",
                     "repo-uuid", "201"),
                ],
                "5": [("legacy_user/2016-01/", "old.tiff", None,
                       "501")],
                "6": [("demo_2/2026-07/", "scan.czi", "repo-uuid",
                       "601")],
                "7": [("demo_2/2026-07/", "slide.ndpi", "repo-uuid",
                       "701")],
                "8": [("demo_2/2026-07/", "scan.svs", "repo-uuid",
                       "801")],
            }.get(params[0], [])
        if sql == PIXELS_ID_QUERY:
            return {"3": [("301",)], "4": [("401",)]}.get(params[0], [])
        if sql == REPO_ROOT_QUERY:
            return []  # default ManagedRepository convention
        raise AssertionError(f"unexpected SQL: {sql}")

    return rows


class TestResolution:
    def _with_source(self, data_dir, loop, fn, rows_for=None):
        import asyncio
        import threading

        results = {}
        started = threading.Event()
        stop = threading.Event()

        def server_thread():
            srv_loop = asyncio.new_event_loop()
            asyncio.set_event_loop(srv_loop)

            async def run():
                async with FakePg(
                    rows_for=rows_for or _rows_for(data_dir)
                ) as pg:
                    results["port"] = pg.port
                    started.set()
                    while not stop.is_set():
                        await asyncio.sleep(0.05)

            try:
                srv_loop.run_until_complete(run())
            finally:
                srv_loop.close()

        t = threading.Thread(target=server_thread, daemon=True)
        t.start()
        assert started.wait(5)
        source = OmeroImageSource(
            f"postgresql://omero:pw@127.0.0.1:{results['port']}/omero",
            data_dir,
        )
        try:
            return fn(source)
        finally:
            source.close_sync()
            stop.set()
            t.join(timeout=5)

    def test_managed_repo_tiff(self, data_dir, loop):
        def check(source):
            entry = source.entry(1)
            assert entry["type"] == "ometiff"
            assert entry["path"] == os.path.join(
                data_dir, "ManagedRepository", "demo_2", "2026-07",
                "img.ome.tiff",
            )

        self._with_source(data_dir, loop, check)

    def test_ngff_member_files_walk_to_root(self, data_dir, loop):
        def check(source):
            entry = source.entry(2)
            assert entry["type"] == "zarr"
            assert entry["path"].endswith("plate.ome.zarr")

        self._with_source(data_dir, loop, check)

    def test_romio_fanout_and_pyramid(self, data_dir, loop):
        def check(source):
            e3 = source.entry(3)
            assert e3["type"] == "romio"
            assert e3["path"] == os.path.join(data_dir, "Pixels", "301")
            e4 = source.entry(4)
            assert e4["type"] == "ometiff"
            assert e4["path"].endswith("401_pyramid")

        self._with_source(data_dir, loop, check)

    def test_legacy_original_file(self, data_dir, loop):
        def check(source):
            entry = source.entry(5)
            assert entry["type"] == "ometiff"
            assert entry["path"] == os.path.join(
                data_dir, "legacy_user", "2016-01", "old.tiff"
            )

        self._with_source(data_dir, loop, check)

    def test_unknown_image_is_none(self, data_dir, loop):
        def check(source):
            assert source.entry(99) is None  # -> 404

        self._with_source(data_dir, loop, check)

    def test_non_tiff_fileset_serves_generated_pyramid(
        self, data_dir, loop
    ):
        """ADVICE r5 regression: an FS-imported .czi must resolve to
        its generated <pixelsId>_pyramid, not hand the unreadable
        original to the TIFF reader; without a pyramid it resolves to
        nothing (404), never to a doomed 'ometiff' entry."""

        def check(source):
            e6 = source.entry(6)
            assert e6["type"] == "ometiff"
            assert e6["path"].endswith("601_pyramid")
            assert source.entry(7) is None
            # TIFF containers with non-.tif suffixes (.svs) still
            # serve directly
            e8 = source.entry(8)
            assert e8["type"] == "ometiff"
            assert e8["path"].endswith("scan.svs")

        self._with_source(data_dir, loop, check)

    def test_entries_cached(self, data_dir, loop):
        counted = {"n": 0}
        base = _rows_for(data_dir)

        def rows_for(sql, params):
            counted["n"] += 1
            return base(sql, params)

        def check(source):
            e1 = source.entry(1)
            before = counted["n"]
            assert source.entry(1) == e1
            assert counted["n"] == before  # TTL cache hit

        self._with_source(data_dir, loop, check, rows_for=rows_for)


class TestEndToEnd:
    def test_serves_tiles_without_registry(self, data_dir, loop):
        """The VERDICT 'done' bar: only (db uri, data dir), no JSON
        registry — pixel-exact tiles from all three reader kinds."""

        def rows_for(sql, params):
            base = _rows_for(data_dir)
            if "pixelstype" in sql:
                # metadata plane (db/metadata.PIXELS_QUERY)
                dims = {
                    "1": ("101", "128", "96", "uint16", "img"),
                    "2": ("201", "80", "64", "uint16", "plate"),
                    "3": ("301", "64", "48", "uint16", "planes"),
                }.get(params[0])
                if dims is None:
                    return []
                pid, sx, sy, pt, name = dims
                return [(pid, sx, sy, "1", "1", "1", pt, name,
                         "2", "3", "-120", None, None, None, None)]
            return base(sql, params)

        def run(test, source):
            service = PixelsService(
                source, metadata_resolver=source.metadata
            )
            try:
                tile = service.get_pixel_buffer(1).get_tile_at(
                    0, 0, 0, 0, 16, 8, 64, 64
                )
                np.testing.assert_array_equal(
                    tile, TIFF_IMG[0, 0, 0, 8:72, 16:80]
                )
                ztile = service.get_pixel_buffer(2).get_tile_at(
                    0, 0, 0, 0, 0, 0, 40, 40
                )
                np.testing.assert_array_equal(
                    ztile, ZARR_IMG[0, 0, 0, :40, :40]
                )
                rtile = service.get_pixel_buffer(3).get_tile_at(
                    0, 0, 0, 0, 0, 0, 32, 32
                )
                np.testing.assert_array_equal(
                    rtile, ROMIO_IMG[0, 0, 0, :32, :32]
                )
                assert service.get_pixel_buffer(99) is None  # -> 404
            finally:
                service.close()

        TestResolution()._with_source(
            data_dir, loop,
            lambda source: run(self, source),
            rows_for=rows_for,
        )
