"""Auth (Django session decode, stores, validator), dispatch bus
semantics (timeout -> code -1 -> 500), and metrics exposition."""

import base64
import pickle

import pytest

from omero_ms_pixel_buffer_tpu.auth.django import (
    decode_session_payload,
    extract_omero_session_key,
)
from omero_ms_pixel_buffer_tpu.auth.omero_session import AllowListValidator
from omero_ms_pixel_buffer_tpu.auth.stores import (
    MemorySessionStore,
    PostgresSessionStore,
    make_session_store,
)
from omero_ms_pixel_buffer_tpu.dispatch.bus import EventBus
from omero_ms_pixel_buffer_tpu.errors import TileError, http_status_for_failure
from omero_ms_pixel_buffer_tpu.utils.metrics import Registry


class FakeConnector:
    """Stands in for omeroweb.connector.Connector in pickles."""

    def __init__(self, key):
        self.omero_session_key = key
        self.server_id = 1


class TestDjangoDecode:
    def test_raw_pickle_dict(self):
        payload = pickle.dumps({"connector": FakeConnector("abc-123")})
        session = decode_session_payload(payload)
        assert extract_omero_session_key(session) == "abc-123"

    def test_base64_hash_colon_pickle(self):
        inner = pickle.dumps({"connector": FakeConnector("k-9")})
        payload = base64.b64encode(b"fakehash:" + inner)
        session = decode_session_payload(payload)
        assert extract_omero_session_key(session) == "k-9"

    def test_unknown_class_tolerated(self):
        # pickle referencing a class that can't be imported at load time
        # (the omeroweb.connector.Connector situation)
        import sys
        import types

        mod = types.ModuleType("omeroweb_gone")
        class Connector:  # noqa: E306
            def __init__(self, key):
                self.omero_session_key = key
        Connector.__module__ = "omeroweb_gone"
        Connector.__qualname__ = "Connector"
        mod.Connector = Connector
        sys.modules["omeroweb_gone"] = mod
        try:
            raw = pickle.dumps({"connector": Connector("z-1")})
        finally:
            del sys.modules["omeroweb_gone"]
        session = decode_session_payload(raw)
        assert extract_omero_session_key(session) == "z-1"

    def test_garbage_returns_none(self):
        assert decode_session_payload(b"\x00\x01garbage") is None

    def test_missing_connector(self):
        assert extract_omero_session_key({"other": 1}) is None


def _django_signed(obj, compress=True):
    """Build a payload byte-for-byte like django.core.signing.dumps
    (TimestampSigner.sign_object): [.]urlsafe-b64(json|zlib(json)) :
    base62(timestamp) : urlsafe-b64(hmac)."""
    import hashlib
    import hmac as hmac_mod
    import json
    import zlib as zlib_mod

    data = json.dumps(obj, separators=(",", ":")).encode()
    is_compressed = False
    if compress:
        compressed = zlib_mod.compress(data)
        if len(compressed) < (len(data) - 1):
            data = compressed
            is_compressed = True
    b64 = base64.urlsafe_b64encode(data).rstrip(b"=").decode()
    if is_compressed:
        b64 = "." + b64
    ts = "1tPqzV"  # base62 timestamp, opaque to the decoder
    value = f"{b64}:{ts}"
    sig = base64.urlsafe_b64encode(
        hmac_mod.new(b"secret", value.encode(), hashlib.sha256).digest()
    ).rstrip(b"=").decode()
    return f"{value}:{sig}".encode()


class TestDjangoSignedJson:
    """Django >= 3.1 default session encoding (signing.dumps with the
    JSONSerializer); a current OMERO.web stores sessions this way."""

    SESSION = {
        "connector": {
            "omero_session_key": "sj-77",
            "server_id": 1,
            "is_secure": False,
        },
        "_auth_user_id": "2",
    }

    def test_signed_json_compressed(self):
        payload = _django_signed(dict(self.SESSION, pad="x" * 200))
        session = decode_session_payload(payload)
        assert extract_omero_session_key(session) == "sj-77"

    def test_signed_json_uncompressed(self):
        payload = _django_signed(self.SESSION, compress=False)
        assert b"." not in payload.split(b":")[0:1][0][:1]
        session = decode_session_payload(payload)
        assert extract_omero_session_key(session) == "sj-77"

    def test_bare_json_cache_backend(self):
        import json

        payload = json.dumps(self.SESSION).encode()
        session = decode_session_payload(payload)
        assert extract_omero_session_key(session) == "sj-77"

    def test_signed_garbage_returns_none(self):
        assert decode_session_payload(b"abc:def:ghi") is None
        assert decode_session_payload(b"::") is None


class TestStores:
    async def test_memory_store(self):
        store = MemorySessionStore({"sid": "key"})
        assert await store.get_omero_session_key("sid") == "key"
        assert await store.get_omero_session_key("nope") is None

    def test_factory(self):
        assert isinstance(make_session_store("memory", None), MemorySessionStore)
        from omero_ms_pixel_buffer_tpu.auth.stores import PostgresSessionStore

        # accepts both postgresql:// and the reference's jdbc: spelling
        pg = make_session_store("postgres", "jdbc:postgresql://x:5433/db")
        assert isinstance(pg, PostgresSessionStore)
        assert pg._client.host == "x" and pg._client.port == 5433
        assert pg._client.database == "db"
        with pytest.raises(ValueError):
            make_session_store("dynamo", None)

    async def test_validator(self):
        v = AllowListValidator()
        assert await v.validate("any-key")
        assert not await v.validate(None)
        assert not await v.validate("")
        v2 = AllowListValidator(allowed=["k1"])
        assert await v2.validate("k1")
        assert not await v2.validate("k2")


class TestBus:
    async def test_request_reply(self):
        bus = EventBus()

        async def handler(payload):
            return b"data", {"filename": "f.bin"}

        bus.consumer("addr", handler)
        msg = await bus.request("addr", {"x": 1})
        assert msg.body == b"data"
        assert msg.headers["filename"] == "f.bin"

    async def test_timeout_maps_to_500(self):
        import asyncio

        bus = EventBus()

        async def slow(payload):
            await asyncio.sleep(1.0)
            return b"", {}

        bus.consumer("slow", slow)
        with pytest.raises(TileError) as ei:
            await bus.request("slow", None, timeout_ms=30)
        # Vert.x timeout failure code -1 -> HTTP 500
        assert ei.value.code == -1
        assert http_status_for_failure(ei.value) == 500

    async def test_no_handlers(self):
        bus = EventBus()
        with pytest.raises(TileError) as ei:
            await bus.request("nowhere", None)
        assert ei.value.code == -1

    async def test_typed_failure_propagates(self):
        bus = EventBus()

        async def failing(payload):
            raise TileError(404, "Cannot find Image:5")

        bus.consumer("f", failing)
        with pytest.raises(TileError) as ei:
            await bus.request("f", None)
        assert ei.value.code == 404


class TestMetrics:
    def test_exposition_format(self):
        reg = Registry()
        c = reg.counter("requests_total", "Requests")
        c.inc(format="png")
        c.inc(format="png")
        c.inc(format="raw")
        h = reg.histogram("latency_seconds", "Latency", buckets=(0.1, 1.0, float("inf")))
        h.observe(0.05)
        h.observe(0.5)
        text = reg.exposition()
        assert 'requests_total{format="png"} 2' in text
        assert 'requests_total{format="raw"} 1' in text
        assert 'latency_seconds_bucket{le="0.1"} 1' in text
        assert 'latency_seconds_bucket{le="+Inf"} 2' in text
        assert "latency_seconds_count 2" in text
        g = reg.gauge("up", "Up")
        g.set(1.0)
        assert "up 1.0" in reg.exposition()
