"""Mesh fusion plane suite (the r23 tentpole).

Pins the mesh-fused super-tile chain: gather + projection + composite
+ carve + filter + deflate as ONE shard_mapped program over per-chip
overlapped sub-rect windows of the bounding stack. Identity matrix:
host == single-device fused == 2-way mesh == 8-way mesh, bytes, ETags
and result-cache keys all equal. Plus the satellites riding the same
refactor — ROI masks as a sharded operand (masked groups no longer
split to single-device), dynamic-Huffman deflate staying dynamic on
the mesh (byte-exact decode + ratio vs rle), and burst-continuation
batching (window chaining, the deadline bound, and invalidated-mid-
burst lanes splitting out cleanly).
"""

import asyncio
import io
import time
import zlib

import numpy as np
import pytest
from PIL import Image

from omero_ms_pixel_buffer_tpu.auth.omero_session import AllowListValidator
from omero_ms_pixel_buffer_tpu.cache.result_cache import make_etag
from omero_ms_pixel_buffer_tpu.dispatch.batcher import BatchingTileWorker
from omero_ms_pixel_buffer_tpu.errors import GatewayTimeoutError
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
from omero_ms_pixel_buffer_tpu.render import supertile as stile
from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
from omero_ms_pixel_buffer_tpu.render.supertile import (
    BurstHint,
    assign_supertiles,
    plan_mesh_partition,
)
from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
from omero_ms_pixel_buffer_tpu.resilience.deadline import Deadline
from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
    INJECTOR,
    always,
)
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

rng = np.random.default_rng(31)

# (T, C, Z, Y, X) — two channels, four z planes
IMG = rng.integers(0, 4096, (1, 2, 4, 96, 128), dtype=np.uint16)


@pytest.fixture(autouse=True)
def _clean_chaos():
    INJECTOR.clear()
    yield
    INJECTOR.clear()
    BOARD.reset()


@pytest.fixture(scope="module")
def service(tmp_path_factory):
    root = tmp_path_factory.mktemp("mesh-fusion")
    path = str(root / "img.ome.tiff")
    write_ome_tiff(path, IMG, tile_size=(64, 64))
    registry = ImageRegistry()
    registry.add(1, path)
    svc = PixelsService(registry)
    yield svc
    svc.close()


def _spec(**extra):
    params = {"c": "1|0:4095$FF0000,2|0:4095$00FF00"}
    params.update(extra)
    return RenderSpec.from_params(params)


def _ctx(spec, x, y, w=32, h=32, z=1, burst=None, **kw):
    return TileCtx(
        image_id=1, z=z, c=0, t=0, region=RegionDef(x, y, w, h),
        format=spec.format, omero_session_key="k", render=spec,
        burst=burst, **kw,
    )


def _grid(spec, tile=32, cols=3, rows=2, **kw):
    return [
        _ctx(spec, tile * c, tile * r, tile, tile, **kw)
        for r in range(rows) for c in range(cols)
    ]


def _mesh_pipe(service, width, **kw):
    """A device pipeline over the first ``width`` virtual chips;
    ``width=None`` forces single-device stages."""
    pipe = TilePipeline(
        service, engine="device", device_deflate=True, **kw
    )
    if width is None:
        pipe.mesh = None
    else:
        import jax

        from omero_ms_pixel_buffer_tpu.parallel.mesh import make_mesh

        pipe.mesh = make_mesh(("data",), devices=jax.devices()[:width])
    return pipe


def _host_ref(service, ctxs_fn):
    pipe = TilePipeline(service, engine="host")
    try:
        return [pipe.handle(c) for c in ctxs_fn()]
    finally:
        pipe.close()


# ---------------------------------------------------------------------------
# The identity matrix: host == single-device fused == 2-way == 8-way
# ---------------------------------------------------------------------------


class TestFusionIdentityMatrix:
    @pytest.mark.parametrize("width", [1, 2, 8])
    def test_mesh_width_byte_identity(self, service, width):
        """The tentpole pin: the mesh-fused chain at every width
        serves bytes (hence ETags and shared cache entries) identical
        to the host mirror AND the single-device fused path, and the
        dispatch accounting proves the fused supertile program is
        what ran."""
        spec = _spec()
        ref = _host_ref(service, lambda: _grid(spec))
        assert all(b is not None for b in ref)

        single = _mesh_pipe(service, None)
        try:
            ctxs = _grid(spec)
            assign_supertiles(ctxs)
            fused_single = single.handle_batch(ctxs)
            assert fused_single == ref
        finally:
            single.close()

        mesh = _mesh_pipe(service, width)
        try:
            before = dict(stile.SUPERTILE_LANES._values)
            ctxs = _grid(spec)
            assign_supertiles(ctxs)
            out = mesh.handle_batch(ctxs)
            assert out == ref
            after = dict(stile.SUPERTILE_LANES._values)
            key = (("path", "mesh"),)
            assert after.get(key, 0) - before.get(key, 0) == 6, (
                "fused group did not take the mesh path"
            )
            last = mesh.last_mesh_dispatch
            assert last is not None and last["executed"]
            assert last["tag"] == "supertile"
            assert last["n_devices"] == width
        finally:
            mesh.close()
        # identical bytes carry identical strong ETags, and identical
        # ctxs carry identical cache keys — the widths share cache
        # entries end to end
        for a, b in zip(out, ref):
            assert make_etag(a) == make_etag(b)
        assert [c.cache_key() for c in _grid(spec)] == [
            c.cache_key() for c in _grid(spec)
        ]

    def test_mixed_sizes_and_projection(self, service):
        """Edge-row tile sizes (per-size sharded programs) and a
        projection spec, both on the full 8-way mesh."""
        spec = _spec(p="intmax|0:3")

        def ctxs_fn():
            out = []
            for y, h in ((0, 48), (48, 48)):
                for x, w in ((0, 48), (48, 48), (96, 32)):
                    out.append(_ctx(spec, x, y, w, h, z=0))
            return out

        ref = _host_ref(service, ctxs_fn)
        assert all(b is not None for b in ref)
        mesh = _mesh_pipe(service, 8, buckets=(64,))
        try:
            ctxs = ctxs_fn()
            assert assign_supertiles(ctxs) == 6
            assert mesh.handle_batch(ctxs) == ref
            assert mesh.last_mesh_dispatch["tag"] == "supertile"
        finally:
            mesh.close()

    def test_degraded_group_fuses_on_mesh(self, service):
        """Degraded lanes fuse with each other (per pyramid level) and
        the fused coarse-gather+upscale is byte-identical to per-lane
        degraded reads — on the mesh."""
        spec = _spec()

        def ctxs_fn():
            return _grid(spec, cols=2, rows=2, degraded=1)

        ref = _host_ref(service, ctxs_fn)
        assert all(b is not None for b in ref)
        mesh = _mesh_pipe(service, 8)
        try:
            ctxs = ctxs_fn()
            assign_supertiles(ctxs)
            assert all(c.supertile is not None for c in ctxs), (
                "degraded lanes should fuse with each other"
            )
            assert mesh.handle_batch(ctxs) == ref
        finally:
            mesh.close()

    def test_escape_hatch_restores_per_lane_sharding(self, service):
        """``supertile.mesh: false`` — lanes serve per-lane sharded on
        the mesh, byte-identical, no fused supertile dispatch."""
        spec = _spec()
        ref = _host_ref(service, lambda: _grid(spec))
        mesh = _mesh_pipe(service, 8, supertile_mesh=False)
        try:
            before = dict(stile.SUPERTILE_LANES._values)
            ctxs = _grid(spec)
            assign_supertiles(ctxs)
            assert mesh.handle_batch(ctxs) == ref
            after = dict(stile.SUPERTILE_LANES._values)
            key = (("path", "mesh"),)
            assert after.get(key, 0) == before.get(key, 0)
            assert mesh.last_mesh_dispatch["tag"] == "render"
        finally:
            mesh.close()

    @pytest.mark.resilience
    def test_mesh_fusion_fault_falls_back_identical(self, service):
        """Chaos on the fused seam with the mesh active: the group
        serves through the host carve, byte-identical."""
        spec = _spec()
        ref = _host_ref(service, lambda: _grid(spec))
        mesh = _mesh_pipe(service, 8)
        try:
            INJECTOR.install(
                "render.supertile", always(RuntimeError("fused down"))
            )
            ctxs = _grid(spec)
            assign_supertiles(ctxs)
            assert mesh.handle_batch(ctxs) == ref
            assert INJECTOR.calls("render.supertile") >= 1
        finally:
            mesh.close()


class TestMeshPartitionPlanner:
    def test_windows_contain_their_chunks(self):
        rects = [(x * 32, y * 32, 32, 32) for y in range(4) for x in range(4)]
        origins, (sh, sw), coords, rows = plan_mesh_partition(
            rects, 128, 128, 4
        )
        assert len(origins) == 4
        order = sorted(range(16), key=lambda i: (rects[i][1], rects[i][0]))
        per = 4
        for c, (sy, sx) in enumerate(origins):
            assert 0 <= sy <= 128 - sh and 0 <= sx <= 128 - sw
            for slot, i in enumerate(order[c * per : (c + 1) * per]):
                x, y, w, h = rects[i]
                ry, rx = coords[c, slot]
                # the shifted coords land the SAME absolute pixels
                assert (sy + ry, sx + rx) == (y, x)
                assert ry + h <= sh and rx + w <= sw
                assert rows[i] == c * coords.shape[1] + slot

    def test_uneven_chunks_pad_slots(self):
        rects = [(0, 0, 32, 32), (32, 0, 32, 32), (64, 0, 32, 32)]
        origins, _, coords, rows = plan_mesh_partition(rects, 96, 96, 2)
        assert len(origins) == 2
        assert coords.shape[1] >= 2  # pow2 slot padding
        assert sorted(rows) == sorted(
            set(rows)
        ), "row map must be collision-free"


# ---------------------------------------------------------------------------
# Satellite: ROI masks as a sharded operand
# ---------------------------------------------------------------------------


class TestMaskedShardedIdentity:
    def test_masked_group_serves_sharded(self, service):
        roi = (
            '[{"type":"rect","x":8,"y":8,"w":30,"h":20},'
            '{"type":"ellipse","cx":40,"cy":24,"rx":12,"ry":9}]'
        )
        spec = _spec(roi=roi)

        def ctxs_fn():
            return [
                _ctx(spec, 0, 0, 64, 48),
                _ctx(spec, 64, 0, 64, 48),
                _ctx(spec, 0, 48, 64, 48),
            ]

        ref = _host_ref(service, ctxs_fn)
        assert all(b is not None for b in ref)
        mesh = _mesh_pipe(service, 8)
        try:
            assert mesh.handle_batch(ctxs_fn()) == ref
            last = mesh.last_mesh_dispatch
            assert last is not None and last["executed"], (
                "masked group split to single-device"
            )
            assert last["tag"] == "render"
        finally:
            mesh.close()

    def test_masked_and_plain_mix_on_mesh(self, service):
        roi = '[{"type":"rect","x":0,"y":0,"w":20,"h":20}]'
        masked, plain = _spec(roi=roi), _spec()

        def ctxs_fn():
            return [
                _ctx(masked, 0, 0, 64, 48),
                _ctx(plain, 0, 0, 64, 48),
            ]

        ref = _host_ref(service, ctxs_fn)
        mesh = _mesh_pipe(service, 2)
        try:
            assert mesh.handle_batch(ctxs_fn()) == ref
        finally:
            mesh.close()


# ---------------------------------------------------------------------------
# Satellite: dynamic-Huffman deflate stays dynamic on the mesh
# ---------------------------------------------------------------------------


def _raw_ctx(x=0, y=0, w=64, h=64, z=0):
    return TileCtx(
        image_id=1, z=z, c=0, t=0, region=RegionDef(x, y, w, h),
        format="png", omero_session_key="k",
    )


class TestDynamicDeflateOnMesh:
    def test_mesh_dynamic_byte_identical_and_decodes(self, service):
        """Raw PNG tile groups keep the two-pass dynamic-Huffman
        chain on the mesh (no rle downgrade): bytes identical to the
        single-device dynamic path, pixels decode exactly, and the
        dispatch tag proves the histogram+emit chain ran sharded."""
        ctxs = [
            _raw_ctx(64 * (i % 2), 0 if i < 2 else 64 - 32, 64, 32, z=i % 4)
            for i in range(4)
        ]
        single = _mesh_pipe(service, None, buckets=(64,))
        mesh = _mesh_pipe(service, 8, buckets=(64,))
        try:
            ref = single.handle_batch(list(ctxs))
            out = mesh.handle_batch(list(ctxs))
            assert all(b is not None for b in ref)
            assert out == ref
            last = mesh.last_mesh_dispatch
            assert last is not None and last["executed"]
            assert last["tag"] == "dynamic", (
                "dynamic group downgraded off the two-pass chain"
            )
            for c, png in zip(ctxs, out):
                arr = np.array(Image.open(io.BytesIO(png)))
                r = c.region
                np.testing.assert_array_equal(
                    arr,
                    IMG[0, 0, c.z, r.y : r.y + r.height,
                        r.x : r.x + r.width],
                )
        finally:
            single.close()
            mesh.close()

    def test_mesh_dynamic_ratio_not_worse_than_rle(self, service):
        ctxs = [_raw_ctx(0, 0, 64, 64), _raw_ctx(64, 0, 64, 64)]
        dyn = _mesh_pipe(service, 8, buckets=(64,))
        rle = _mesh_pipe(
            service, 8, buckets=(64,), device_deflate_mode="rle"
        )
        try:
            dyn_out = dyn.handle_batch(list(ctxs))
            rle_out = rle.handle_batch(list(ctxs))
            assert sum(map(len, dyn_out)) <= sum(map(len, rle_out)), (
                "dynamic-on-mesh compresses no worse than rle"
            )
        finally:
            dyn.close()
            rle.close()


# ---------------------------------------------------------------------------
# Satellite: burst-continuation batching
# ---------------------------------------------------------------------------


def _cfg(extra=None):
    raw = {"session-store": {"type": "memory"}}
    raw.update(extra or {})
    return Config.from_dict(raw)


class _Counting:
    """handle_batch stand-in that records batch sizes — each call is
    one would-be device program."""

    def __init__(self):
        self.batches = []

    def handle(self, ctx):
        return b"x"

    def handle_batch(self, ctxs):
        self.batches.append(len(ctxs))
        return [b"x"] * len(ctxs)


class _FakeLoop:
    def __init__(self, t=100.0):
        self._t = t

    def time(self):
        return self._t


class TestBurstContinuationUnit:
    def _worker(self, bc=None):
        return BatchingTileWorker(
            _Counting(), AllowListValidator(), workers=1,
            burst_continuation=bc,
        )

    def test_burst_key_requires_hint_and_spec(self):
        w = self._worker()
        spec = _spec()
        hint = BurstHint(32, 32)
        assert w._burst_key(_ctx(spec, 0, 0)) is None  # no hint
        assert w._burst_key(_raw_ctx()) is None  # no render spec
        k1 = w._burst_key(_ctx(spec, 0, 0, burst=hint))
        k2 = w._burst_key(_ctx(spec, 32, 0, burst=hint))
        assert k1 == k2 is not None  # position-independent
        assert k1 != w._burst_key(_ctx(_spec(m="g"), 0, 0, burst=hint))

    def test_extension_fires_on_shared_key(self):
        bc = _cfg().backend.batching.burst_continuation
        w = self._worker(bc)
        spec, hint = _spec(), BurstHint(32, 32)
        batch = [
            (_ctx(spec, 0, 0, burst=hint), None),
            (_ctx(spec, 32, 0, burst=hint), None),
        ]
        assert w._burst_extension(batch, _FakeLoop()) == pytest.approx(
            0.025
        )
        # a lone keyed lane does not extend...
        assert w._burst_extension(batch[:1], _FakeLoop()) is None
        # ...unless the key carries over from the previous dispatch
        w._last_burst = (w._burst_key(batch[0][0]), 100.0 - 0.010)
        assert w._burst_extension(batch[:1], _FakeLoop()) is not None
        # and a stale carry (older than the window) does not count
        w._last_burst = (w._burst_key(batch[0][0]), 100.0 - 0.300)
        assert w._burst_extension(batch[:1], _FakeLoop()) is None

    def test_extension_deadline_bounded(self):
        bc = _cfg().backend.batching.burst_continuation
        w = self._worker(bc)
        spec, hint = _spec(), BurstHint(32, 32)
        a = _ctx(spec, 0, 0, burst=hint)
        b = _ctx(spec, 32, 0, burst=hint)
        b.deadline = Deadline.after(0.010)
        ext = w._burst_extension([(a, None), (b, None)], _FakeLoop())
        # never more than half the tightest remaining budget
        assert ext is not None and ext <= 0.005
        b.deadline = Deadline.after(0)
        time.sleep(0.001)
        assert (
            w._burst_extension([(a, None), (b, None)], _FakeLoop())
            is None
        )

    def test_disabled_or_absent_never_extends(self):
        spec, hint = _spec(), BurstHint(32, 32)
        batch = [
            (_ctx(spec, 0, 0, burst=hint), None),
            (_ctx(spec, 32, 0, burst=hint), None),
        ]
        assert self._worker()._burst_extension(batch, _FakeLoop()) is None
        bc = _cfg({
            "backend": {"batching": {
                "burst-continuation": {"enabled": False},
            }},
        }).backend.batching.burst_continuation
        assert (
            self._worker(bc)._burst_extension(batch, _FakeLoop()) is None
        )


class TestBurstContinuationChaining:
    def _run_burst(self, loop, bc, n=8, stagger=0.015):
        """n staggered burst lanes, each arriving after the 2ms base
        window of its predecessor — without continuation every lane is
        its own batch (program); with it the burst chains."""
        pipeline = _Counting()
        worker = BatchingTileWorker(
            pipeline, AllowListValidator(), max_batch=32,
            coalesce_window_ms=2.0, workers=1,
            burst_continuation=bc,
        )
        spec, hint = _spec(), BurstHint(32, 32)

        async def run():
            await worker.start()
            sends = []
            for i in range(n):
                sends.append(asyncio.ensure_future(
                    worker.handle(_ctx(spec, 32 * i, 0, burst=hint))
                ))
                await asyncio.sleep(stagger)
            out = await asyncio.gather(*sends)
            await worker.close()
            return out

        out = loop.run_until_complete(run())
        assert all(b[0] == b"x" for b in out)
        return pipeline.batches

    def test_burst_chains_into_few_programs(self, loop):
        bc = _cfg({
            "backend": {"batching": {
                "burst-continuation": {"window-ms": 250.0},
            }},
        }).backend.batching.burst_continuation
        batches = self._run_burst(loop, bc)
        # lane 0 may dispatch alone before the carry exists; the rest
        # of the burst must chain — the ≤ 1/4-programs acceptance pin
        # at test scale
        assert len(batches) <= 2, batches
        assert sum(batches) == 8

    def test_without_continuation_one_program_per_window(self, loop):
        batches = self._run_burst(loop, None)
        assert len(batches) == 8, batches

    def test_invalidated_mid_burst_splits_out(self, loop):
        """A lane whose budget dies during the extension answers 504
        at dispatch; the rest of the chained burst serves."""
        bc = _cfg({
            "backend": {"batching": {
                "burst-continuation": {"window-ms": 120.0},
            }},
        }).backend.batching.burst_continuation
        pipeline = _Counting()
        worker = BatchingTileWorker(
            pipeline, AllowListValidator(), max_batch=32,
            coalesce_window_ms=2.0, workers=1,
            burst_continuation=bc,
        )
        spec, hint = _spec(), BurstHint(32, 32)

        async def run():
            await worker.start()
            a = asyncio.ensure_future(
                worker.handle(_ctx(spec, 0, 0, burst=hint))
            )
            b = asyncio.ensure_future(
                worker.handle(_ctx(spec, 32, 0, burst=hint))
            )
            await asyncio.sleep(0.005)
            doomed = _ctx(spec, 64, 0, burst=hint)
            doomed.deadline = Deadline.after(0.001)
            d = asyncio.ensure_future(worker.handle(doomed))
            out = await asyncio.gather(*[a, b, d], return_exceptions=True)
            await worker.close()
            return out

        out = loop.run_until_complete(run())
        assert out[0][0] == b"x" and out[1][0] == b"x"
        assert isinstance(out[2], GatewayTimeoutError)


# ---------------------------------------------------------------------------
# Config knobs
# ---------------------------------------------------------------------------


class TestFusionConfig:
    def test_defaults(self):
        cfg = _cfg()
        assert cfg.supertile.mesh is True
        bc = cfg.backend.batching.burst_continuation
        assert bc.enabled is True and bc.window_ms == 25.0

    def test_supertile_mesh_parses(self):
        assert _cfg({"supertile": {"mesh": False}}).supertile.mesh is False

    def test_burst_continuation_parses(self):
        bc = _cfg({
            "backend": {"batching": {
                "burst-continuation": {
                    "enabled": False, "window-ms": 40,
                },
            }},
        }).backend.batching.burst_continuation
        assert bc.enabled is False and bc.window_ms == 40.0

    @pytest.mark.parametrize("block", [
        {"burst-continuation": {"windowms": 10}},
        {"burst-continuation": {"window-ms": "soon"}},
        {"burst-continuation": {"window-ms": -1}},
    ])
    def test_invalid_burst_continuation_fails_startup(self, block):
        with pytest.raises(ConfigError):
            _cfg({"backend": {"batching": block}})

    def test_shipped_config_parses(self):
        import os

        import yaml

        path = os.path.join(
            os.path.dirname(__file__), "..", "conf", "config.yaml"
        )
        with open(path) as fh:
            cfg = Config.from_dict(yaml.safe_load(fh))
        assert cfg.supertile.mesh is True
        assert cfg.backend.batching.burst_continuation.enabled is True
