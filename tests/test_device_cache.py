"""HBM-resident plane cache: device crops must encode identically to
the host path, planes stage once, and edge lanes fall back."""

import numpy as np
import pytest

from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.models.device_cache import DevicePlaneCache
from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
from omero_ms_pixel_buffer_tpu.ops.png import decode_png
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx


@pytest.fixture
def image(tmp_path):
    rng = np.random.default_rng(41)
    data = rng.integers(0, 60000, (1, 1, 2, 640, 640), dtype=np.uint16)
    path = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(path, data, tile_size=(256, 256), compression="zlib")
    registry = ImageRegistry()
    registry.add(1, path)
    return PixelsService(registry), data[0, 0]


def _ctx(x, y, w, h, z=0):
    return TileCtx(
        image_id=1, z=z, c=0, t=0, region=RegionDef(x, y, w, h),
        format="png", omero_session_key="k",
    )


class TestPlaneCache:
    def test_device_plane_path_matches_host(self, image):
        service, truth = image
        dev = TilePipeline(
            service, engine="device", use_pallas=False, buckets=(256,),
        )
        dev.mesh = None  # plane cache is the single-device path
        host = TilePipeline(service, engine="host")
        ctxs = [
            _ctx(0, 0, 256, 256),
            _ctx(128, 64, 256, 256),
            _ctx(37, 51, 100, 200),     # sub-bucket
            _ctx(500, 500, 140, 140),   # edge: crop would clamp -> host
            _ctx(0, 0, 256, 256, z=1),  # second plane
        ]
        # batch 1: admission threshold not met -> host staging, but
        # outputs already correct; batch 2: planes resident
        for round_ in range(2):
            out_dev = dev.handle_batch(list(ctxs))
            out_host = host.handle_batch(list(ctxs))
            for ctx, d, h in zip(ctxs, out_dev, out_host):
                assert d is not None and h is not None
                r = ctx.region
                z = ctx.z
                np.testing.assert_array_equal(
                    decode_png(d), truth[z, r.y : r.y + r.height,
                                         r.x : r.x + r.width],
                )
                np.testing.assert_array_equal(decode_png(d), decode_png(h))
        # two planes staged (z=0, z=1) on the second touch
        cache = dev._plane_cache
        assert cache is not None and len(cache) == 2
        misses = cache.misses
        out2 = dev.handle_batch([_ctx(64, 64, 256, 256)])
        assert out2[0] is not None
        assert cache.misses == misses  # pure hit

    def test_budget_zero_falls_back(self, image):
        service, truth = image
        pipe = TilePipeline(
            service, engine="device", use_pallas=False, buckets=(256,),
        )
        pipe.mesh = None  # plane cache is the single-device path
        pipe._plane_cache = DevicePlaneCache(max_bytes=0)
        out = pipe.handle_batch([_ctx(0, 0, 256, 256)])
        np.testing.assert_array_equal(
            decode_png(out[0]), truth[0, :256, :256]
        )
        assert len(pipe._plane_cache) == 0

    def test_plane_cache_lru_evicts(self, image):
        service, _ = image
        plane_bytes = 640 * 640 * 2
        cache = DevicePlaneCache(
            max_bytes=plane_bytes + 16, admit_after=1
        )
        buf = service.get_pixel_buffer(1)
        p0 = cache.get_plane(buf, 0, 0, 0, 0)
        p1 = cache.get_plane(buf, 0, 1, 0, 0)
        assert p0 is not None and p1 is not None
        assert len(cache) == 1  # first plane evicted
        assert cache.nbytes <= plane_bytes + 16

    def test_admission_defers_first_touch(self, image):
        service, _ = image
        cache = DevicePlaneCache(max_bytes=1 << 30)  # admit_after=2
        buf = service.get_pixel_buffer(1)
        assert cache.get_plane(buf, 0, 0, 0, 0) is None  # touch 1
        assert cache.get_plane(buf, 0, 0, 0, 0) is not None  # touch 2

    def test_disabled_plane_cache(self, image):
        service, truth = image
        pipe = TilePipeline(
            service, engine="device", use_pallas=False, buckets=(256,),
            use_plane_cache=False,
        )
        out = pipe.handle_batch([_ctx(32, 32, 128, 128)])
        np.testing.assert_array_equal(
            decode_png(out[0]), truth[0, 32:160, 32:160]
        )
        assert pipe._plane_cache is None


def test_admission_single_touch_per_batch(image):
    """Multiple cold lanes on one plane in one batch count ONE
    admission touch (get_plane called once), so admit_after=2 really
    defers staging to the second batch."""
    service, truth = image
    pipe = TilePipeline(
        service, engine="device", use_pallas=False, buckets=(256,),
    )
    pipe.mesh = None  # plane cache is the single-device path
    batch = [_ctx(0, 0, 256, 256), _ctx(128, 128, 256, 256)]
    out1 = pipe.handle_batch(list(batch))
    assert all(o is not None for o in out1)
    assert len(pipe._plane_cache) == 0  # still cold after batch 1
    out2 = pipe.handle_batch(list(batch))
    assert all(o is not None for o in out2)
    assert len(pipe._plane_cache) == 1  # staged on batch 2


def test_admission_counter_resets_after_staging(image):
    service, _ = image
    cache = DevicePlaneCache(max_bytes=1 << 30)
    buf = service.get_pixel_buffer(1)
    assert cache.get_plane(buf, 0, 0, 0, 0) is None
    assert cache.get_plane(buf, 0, 0, 0, 0) is not None  # staged
    # evict by replacing the cache contents, then the counter must
    # restart (no immediate restage on the first post-eviction touch)
    cache._planes.clear()
    cache._bytes = 0
    assert cache.get_plane(buf, 0, 0, 0, 0) is None  # touch 1 again


def test_admission_one_touch_across_buckets(image):
    """Two buckets of one cold plane in one batch still count a single
    admission touch."""
    service, _ = image
    pipe = TilePipeline(
        service, engine="device", use_pallas=False, buckets=(256, 512),
    )
    pipe.mesh = None  # plane cache is the single-device path
    batch = [_ctx(0, 0, 256, 256), _ctx(0, 0, 400, 400)]  # two buckets
    out1 = pipe.handle_batch(list(batch))
    assert all(o is not None for o in out1)
    assert len(pipe._plane_cache) == 0  # one touch -> still cold


def test_staging_single_flight(image):
    """Two threads passing admission concurrently stage the plane ONCE;
    the follower falls back to the host path instead of duplicating a
    full-plane read + transfer (ADVICE r1)."""
    import threading

    from omero_ms_pixel_buffer_tpu.models.device_cache import DevicePlaneCache

    service, _ = image
    buf = service.get_pixel_buffer(1)

    started = threading.Event()
    release = threading.Event()
    reads = []
    real_get = buf.get_tile_at

    def slow_get(level, z, c, t, x, y, w, h):
        reads.append((level, z, c, t))
        started.set()
        release.wait(5)
        return real_get(level, z, c, t, x, y, w, h)

    buf.get_tile_at = slow_get
    try:
        cache = DevicePlaneCache(admit_after=1)
        results = {}

        def leader():
            results["leader"] = cache.get_plane(buf, 0, 0, 0, 0)

        t1 = threading.Thread(target=leader)
        t1.start()
        assert started.wait(5)
        # leader is mid-read; a follower must get None, not a 2nd read
        assert cache.get_plane(buf, 0, 0, 0, 0) is None
        release.set()
        t1.join(10)
        assert results["leader"] is not None
        assert len([r for r in reads]) == 1
        # once staged, followers hit the resident plane
        assert cache.get_plane(buf, 0, 0, 0, 0) is not None
    finally:
        buf.get_tile_at = real_get
