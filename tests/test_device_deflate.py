"""On-device zlib streams (ops/device_deflate): the encode hot loop the
reference runs on a JVM worker thread (TileRequestHandler.java:176-199)
built entirely on the accelerator.

Correctness contract: ``zlib.decompress`` of every lane's stream equals
the input payload — any spec-valid stream is acceptable (clients only
decode), so tests pin decoded equality, not bytes. Runs on the CPU
backend (conftest); the same XLA program serves the TPU.
"""

import io
import zlib

import numpy as np
import pytest
from PIL import Image

from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
    deflate_filtered_batch,
    max_stream_len,
    stored_stream_len,
    zlib_rle_batch,
    zlib_stored_batch,
)

rng = np.random.default_rng(41)


def _roundtrip_rle(payloads: np.ndarray):
    streams, lengths = (
        np.asarray(a) for a in zlib_rle_batch(payloads)
    )
    assert streams.shape[1] == max_stream_len(payloads.shape[1])
    for lane, (stream, length) in enumerate(zip(streams, lengths)):
        assert 6 < length <= streams.shape[1]
        got = zlib.decompress(bytes(stream[:length]))
        assert got == payloads[lane].tobytes(), f"lane {lane}"
    return lengths


class TestRleStreams:
    def test_run_heavy_payload_compresses(self):
        # 20-byte runs: the Z_RLE sweet spot (Up-filtered microscopy
        # tiles look like this)
        payloads = np.repeat(
            rng.integers(0, 4, (3, 64)), 20, axis=1
        ).astype(np.uint8)
        lengths = _roundtrip_rle(payloads)
        assert (lengths < payloads.shape[1] // 2).all()

    def test_incompressible_payload_bounded(self):
        payloads = rng.integers(0, 256, (2, 4096)).astype(np.uint8)
        lengths = _roundtrip_rle(payloads)
        # all-literal worst case: 9 bits/byte + framing
        assert (lengths <= max_stream_len(4096)).all()

    def test_constant_payload(self):
        _roundtrip_rle(np.full((1, 100_000), 7, np.uint8))

    def test_alternating_no_runs(self):
        _roundtrip_rle(
            np.tile(np.array([1, 2], np.uint8), 2048)[None]
        )

    @pytest.mark.parametrize(
        "n",
        # run/match boundary cases: tails of 1-2 bytes after a match,
        # exact 258 chunks, one-past, tiny payloads
        [1, 2, 3, 4, 5, 257, 258, 259, 260, 261, 262, 516, 517, 518, 777],
    )
    def test_run_boundaries(self, n):
        _roundtrip_rle(np.zeros((1, n), np.uint8))
        _roundtrip_rle(rng.integers(0, 2, (1, n)).astype(np.uint8))

    def test_mixed_batch_lanes_independent(self):
        payloads = np.stack(
            [
                np.zeros(1500, np.uint8),
                rng.integers(0, 256, 1500).astype(np.uint8),
                np.repeat(rng.integers(0, 9, 75), 20).astype(np.uint8),
            ]
        )
        _roundtrip_rle(payloads)


class TestStoredStreams:
    @pytest.mark.parametrize("n", [1, 100, 65535, 65536, 70000, 131071])
    def test_roundtrip(self, n):
        payloads = rng.integers(0, 256, (2, n)).astype(np.uint8)
        streams = np.asarray(zlib_stored_batch(payloads))
        assert streams.shape[1] == stored_stream_len(n)
        for lane in range(2):
            assert (
                zlib.decompress(bytes(streams[lane]))
                == payloads[lane].tobytes()
            )


class TestDeflateFiltered:
    def _filtered(self, tiles: np.ndarray, mode: str = "up"):
        import jax.numpy as jnp

        from omero_ms_pixel_buffer_tpu.ops.convert import to_big_endian_bytes
        from omero_ms_pixel_buffer_tpu.ops.png import filter_batch

        rows = to_big_endian_bytes(jnp.asarray(tiles))
        return filter_batch(rows, tiles.dtype.itemsize, mode)

    def test_matches_host_payload(self):
        tiles = rng.integers(0, 60000, (4, 64, 64), dtype=np.uint16)
        filtered = self._filtered(tiles)
        streams, lengths = (
            np.asarray(a)
            for a in deflate_filtered_batch(filtered, 64, 1 + 64 * 2)
        )
        host = np.asarray(filtered)
        for lane in range(4):
            got = zlib.decompress(bytes(streams[lane][: lengths[lane]]))
            assert got == host[lane].tobytes()

    def test_bucket_padding_sliced_away(self):
        # real region 40x30 inside a 64x64 bucket: the stream must cover
        # only the leading rows x row_bytes
        tiles = np.zeros((2, 64, 64), np.uint16)
        tiles[:, :30, :40] = rng.integers(0, 60000, (2, 30, 40))
        filtered = self._filtered(tiles)
        streams, lengths = (
            np.asarray(a)
            for a in deflate_filtered_batch(filtered, 30, 1 + 40 * 2)
        )
        host = np.asarray(filtered)[:, :30, : 1 + 40 * 2]
        for lane in range(2):
            got = zlib.decompress(bytes(streams[lane][: lengths[lane]]))
            assert got == host[lane].tobytes()

    def test_stored_mode(self):
        tiles = rng.integers(0, 255, (2, 32, 32), dtype=np.uint8)
        filtered = self._filtered(tiles)
        streams, lengths = (
            np.asarray(a)
            for a in deflate_filtered_batch(
                filtered, 32, 33, mode="stored"
            )
        )
        host = np.asarray(filtered)
        for lane in range(2):
            assert lengths[lane] == stored_stream_len(32 * 33)
            got = zlib.decompress(bytes(streams[lane][: lengths[lane]]))
            assert got == host[lane].tobytes()

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError):
            deflate_filtered_batch(np.zeros((1, 8, 8), np.uint8), 8, 8,
                                   mode="huffman")


class TestPipelineDeviceDeflate:
    """End-to-end: handle_batch with the knob on serves pixel-identical
    PNGs through the device bucket path."""

    @pytest.fixture(scope="class")
    def service(self, tmp_path_factory):
        from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            ImageRegistry,
            PixelsService,
        )

        root = tmp_path_factory.mktemp("devdeflate")
        path = str(root / "img.ome.tiff")
        img = rng.integers(0, 60000, (1, 1, 1, 300, 300), dtype=np.uint16)
        write_ome_tiff(path, img, tile_size=(64, 64))
        registry = ImageRegistry()
        registry.add(1, path)
        svc = PixelsService(registry)
        yield svc, img
        svc.close()

    def _ctxs(self):
        from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

        return [
            TileCtx(image_id=1, z=0, c=0, t=0,
                    region=RegionDef(x, y, w, h), format="png",
                    omero_session_key="k")
            for x, y, w, h in [
                (0, 0, 64, 64), (64, 64, 64, 64),
                (128, 0, 100, 80),   # padded lane, same bucket
                (0, 128, 256, 128),  # larger bucket
            ]
        ]

    def test_pixel_equality_vs_source(self, service):
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        svc, img = service
        pipe = TilePipeline(svc, engine="device", device_deflate=True)
        pipe.mesh = None
        ctxs = self._ctxs()
        results = pipe.handle_batch(ctxs)
        assert all(r is not None for r in results)
        for ctx, png in zip(ctxs, results):
            decoded = np.array(Image.open(io.BytesIO(png)))
            r = ctx.region
            expect = img[0, 0, 0, r.y : r.y + r.height,
                         r.x : r.x + r.width]
            np.testing.assert_array_equal(decoded, expect)

    def test_matches_host_engine_pixels(self, service):
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        svc, _ = service
        dev = TilePipeline(svc, engine="device", device_deflate=True)
        dev.mesh = None
        host = TilePipeline(svc, engine="host")
        ctxs = self._ctxs()
        for d, h in zip(dev.handle_batch(ctxs), host.handle_batch(self._ctxs())):
            dp = np.array(Image.open(io.BytesIO(d)))
            hp = np.array(Image.open(io.BytesIO(h)))
            np.testing.assert_array_equal(dp, hp)

    def test_mesh_path_with_device_deflate(self, service):
        import jax

        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        svc, img = service
        assert len(jax.devices()) == 8
        pipe = TilePipeline(svc, engine="device", device_deflate=True)
        assert pipe._get_mesh() is not None
        results = pipe.handle_batch(self._ctxs())
        assert all(r is not None for r in results)
        for ctx, png in zip(self._ctxs(), results):
            decoded = np.array(Image.open(io.BytesIO(png)))
            r = ctx.region
            np.testing.assert_array_equal(
                decoded,
                img[0, 0, 0, r.y : r.y + r.height, r.x : r.x + r.width],
            )

    def test_adaptive_cap_across_batches(self, service):
        """The one-sync transfer's compressed-size guess adapts: the
        first batch may overflow it (incompressible noise), later
        batches reuse the learned cap — all pixel-exact either way."""
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        svc, img = service
        pipe = TilePipeline(svc, engine="device", device_deflate=True)
        pipe.mesh = None
        for _ in range(3):  # fresh guess -> overflow -> learned cap
            results = pipe.handle_batch(self._ctxs())
            for ctx, png in zip(self._ctxs(), results):
                decoded = np.array(Image.open(io.BytesIO(png)))
                r = ctx.region
                np.testing.assert_array_equal(
                    decoded,
                    img[0, 0, 0, r.y : r.y + r.height,
                        r.x : r.x + r.width],
                )
        assert pipe._dd_cap  # the guess was learned

    def test_config_knob_reaches_pipeline(self):
        from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
        from omero_ms_pixel_buffer_tpu.utils.config import Config

        config = Config.from_dict(
            {"session-store": {"type": "memory"},
             "backend": {"engine": "host"}}
        )
        assert config.backend.png.device_deflate is True  # default on
        app = PixelBufferApp(config)
        assert app.pipeline.device_deflate is True

        config_off = Config.from_dict(
            {"session-store": {"type": "memory"},
             "backend": {"engine": "host",
                         "png": {"device-deflate": False}}}
        )
        assert config_off.backend.png.device_deflate is False
        app_off = PixelBufferApp(config_off)
        assert app_off.pipeline.device_deflate is False
