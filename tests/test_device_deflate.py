"""On-device zlib streams (ops/device_deflate): the encode hot loop the
reference runs on a JVM worker thread (TileRequestHandler.java:176-199)
built entirely on the accelerator.

Correctness contract: ``zlib.decompress`` of every lane's stream equals
the input payload — any spec-valid stream is acceptable (clients only
decode), so tests pin decoded equality, not bytes. Runs on the CPU
backend (conftest); the same XLA program serves the TPU.
"""

import io
import os
import zlib

import numpy as np
import pytest
from PIL import Image

from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
    deflate_filtered_batch,
    fused_filter_deflate_batch,
    max_stream_len,
    stored_stream_len,
    zlib_rle_batch,
    zlib_stored_batch,
)

rng = np.random.default_rng(41)


def _payload_families(n: int = 1500):
    """The payload shapes that break packers: runs, noise, no-runs,
    constants, run/match boundary tails."""
    return np.stack([
        np.zeros(n, np.uint8),
        rng.integers(0, 256, n).astype(np.uint8),
        np.repeat(rng.integers(0, 9, (n + 19) // 20), 20)[:n].astype(
            np.uint8
        ),
        np.tile(np.array([200, 201], np.uint8), (n + 1) // 2)[:n],
        np.full(n, 7, np.uint8),
    ])


def _roundtrip_rle(payloads: np.ndarray):
    streams, lengths = (
        np.asarray(a) for a in zlib_rle_batch(payloads)
    )
    assert streams.shape[1] == max_stream_len(payloads.shape[1])
    for lane, (stream, length) in enumerate(zip(streams, lengths)):
        assert 6 < length <= streams.shape[1]
        got = zlib.decompress(bytes(stream[:length]))
        assert got == payloads[lane].tobytes(), f"lane {lane}"
    return lengths


class TestRleStreams:
    def test_run_heavy_payload_compresses(self):
        # 20-byte runs: the Z_RLE sweet spot (Up-filtered microscopy
        # tiles look like this)
        payloads = np.repeat(
            rng.integers(0, 4, (3, 64)), 20, axis=1
        ).astype(np.uint8)
        lengths = _roundtrip_rle(payloads)
        assert (lengths < payloads.shape[1] // 2).all()

    def test_incompressible_payload_bounded(self):
        payloads = rng.integers(0, 256, (2, 4096)).astype(np.uint8)
        lengths = _roundtrip_rle(payloads)
        # all-literal worst case: 9 bits/byte + framing
        assert (lengths <= max_stream_len(4096)).all()

    def test_constant_payload(self):
        _roundtrip_rle(np.full((1, 100_000), 7, np.uint8))

    def test_alternating_no_runs(self):
        _roundtrip_rle(
            np.tile(np.array([1, 2], np.uint8), 2048)[None]
        )

    @pytest.mark.parametrize(
        "n",
        # run/match boundary cases: tails of 1-2 bytes after a match,
        # exact 258 chunks, one-past, tiny payloads
        [1, 2, 3, 4, 5, 257, 258, 259, 260, 261, 262, 516, 517, 518, 777],
    )
    def test_run_boundaries(self, n):
        _roundtrip_rle(np.zeros((1, n), np.uint8))
        _roundtrip_rle(rng.integers(0, 2, (1, n)).astype(np.uint8))

    def test_mixed_batch_lanes_independent(self):
        payloads = np.stack(
            [
                np.zeros(1500, np.uint8),
                rng.integers(0, 256, 1500).astype(np.uint8),
                np.repeat(rng.integers(0, 9, 75), 20).astype(np.uint8),
            ]
        )
        _roundtrip_rle(payloads)


class TestMinStreamSelection:
    """Per-lane min(rle, stored): RLE on no-run content expands past
    9 bits/byte, and before r9 the stream could exceed the stored
    bound; now every lane's length is <= stored_stream_len(L)."""

    def test_pathological_no_runs_takes_stored(self):
        # alternating high-value bytes: every byte a 9-bit literal, no
        # matches -> RLE would expand ~12.5%; the stored stream wins
        n = 4096
        payloads = np.tile(np.array([200, 201], np.uint8), n // 2)[None]
        streams, lengths = (
            np.asarray(a) for a in zlib_rle_batch(payloads)
        )
        assert lengths[0] == stored_stream_len(n)
        assert zlib.decompress(bytes(streams[0][: lengths[0]])) == \
            payloads[0].tobytes()

    def test_randomized_lanes_never_exceed_stored_bound(self):
        local = np.random.default_rng(97)
        n = 2048
        payloads = np.stack([
            local.integers(0, 256, n).astype(np.uint8),
            local.integers(128, 256, n).astype(np.uint8),
            np.repeat(local.integers(0, 4, n // 16), 16).astype(np.uint8),
            (local.integers(0, 2, n) + 180).astype(np.uint8),
        ])
        streams, lengths = (
            np.asarray(a) for a in zlib_rle_batch(payloads)
        )
        bound = stored_stream_len(n)
        for lane in range(payloads.shape[0]):
            assert lengths[lane] <= bound, f"lane {lane}"
            got = zlib.decompress(bytes(streams[lane][: lengths[lane]]))
            assert got == payloads[lane].tobytes()

    def test_compressible_lanes_still_beat_stored(self):
        payloads = np.repeat(
            rng.integers(0, 4, (2, 128)), 20, axis=1
        ).astype(np.uint8)
        _, lengths = (np.asarray(a) for a in zlib_rle_batch(payloads))
        assert (lengths < stored_stream_len(payloads.shape[1]) // 2).all()


class TestPackerEquivalence:
    """The scan packer replaced the gather packer; both must emit
    byte-identical streams (same zero padding, same framing)."""

    @pytest.mark.parametrize("n", [1, 258, 777, 4096])
    def test_scan_matches_gather(self, n):
        payloads = _payload_families(n)
        s1, l1 = (
            np.asarray(a) for a in zlib_rle_batch(payloads, packer="scan")
        )
        s2, l2 = (
            np.asarray(a)
            for a in zlib_rle_batch(payloads, packer="gather")
        )
        np.testing.assert_array_equal(l1, l2)
        np.testing.assert_array_equal(s1, s2)


class TestPallasBitpack:
    """The Pallas per-block VMEM-emit kernel, interpret mode on CPU:
    streams must decompress to the input AND be bit-exact against the
    XLA scan packer (identical zero padding included)."""

    @pytest.mark.parametrize("n", [1, 5, 258, 1500, 70000])
    def test_bit_exact_lanes(self, n):
        payloads = _payload_families(n)
        ps, pl_ = (
            np.asarray(a)
            for a in zlib_rle_batch(payloads, packer="pallas")
        )
        ss, sl = (
            np.asarray(a) for a in zlib_rle_batch(payloads, packer="scan")
        )
        np.testing.assert_array_equal(pl_, sl)
        np.testing.assert_array_equal(ps, ss)
        bound = stored_stream_len(n)
        for lane in range(payloads.shape[0]):
            assert pl_[lane] <= bound
            got = zlib.decompress(bytes(ps[lane][: pl_[lane]]))
            assert got == payloads[lane].tobytes(), f"lane {lane}"

    def test_fused_chain_with_pallas_packer(self):
        import jax.numpy as jnp

        tiles = rng.integers(0, 60000, (3, 48, 48), dtype=np.uint16)
        streams, lengths = (
            np.asarray(a)
            for a in fused_filter_deflate_batch(
                jnp.asarray(tiles), 48, 1 + 48 * 2, 2, packer="pallas"
            )
        )
        from omero_ms_pixel_buffer_tpu.ops.convert import (
            to_big_endian_bytes,
        )
        from omero_ms_pixel_buffer_tpu.ops.png import filter_batch

        ref = np.asarray(
            filter_batch(to_big_endian_bytes(jnp.asarray(tiles)), 2, "up")
        )
        for lane in range(3):
            got = zlib.decompress(
                bytes(streams[lane][: lengths[lane]])
            )
            assert got == ref[lane].tobytes()


class TestStoredStreams:
    @pytest.mark.parametrize("n", [1, 100, 65535, 65536, 70000, 131071])
    def test_roundtrip(self, n):
        payloads = rng.integers(0, 256, (2, n)).astype(np.uint8)
        streams = np.asarray(zlib_stored_batch(payloads))
        assert streams.shape[1] == stored_stream_len(n)
        for lane in range(2):
            assert (
                zlib.decompress(bytes(streams[lane]))
                == payloads[lane].tobytes()
            )


class TestDeflateFiltered:
    def _filtered(self, tiles: np.ndarray, mode: str = "up"):
        import jax.numpy as jnp

        from omero_ms_pixel_buffer_tpu.ops.convert import to_big_endian_bytes
        from omero_ms_pixel_buffer_tpu.ops.png import filter_batch

        rows = to_big_endian_bytes(jnp.asarray(tiles))
        return filter_batch(rows, tiles.dtype.itemsize, mode)

    def test_matches_host_payload(self):
        tiles = rng.integers(0, 60000, (4, 64, 64), dtype=np.uint16)
        filtered = self._filtered(tiles)
        streams, lengths = (
            np.asarray(a)
            for a in deflate_filtered_batch(filtered, 64, 1 + 64 * 2)
        )
        host = np.asarray(filtered)
        for lane in range(4):
            got = zlib.decompress(bytes(streams[lane][: lengths[lane]]))
            assert got == host[lane].tobytes()

    def test_bucket_padding_sliced_away(self):
        # real region 40x30 inside a 64x64 bucket: the stream must cover
        # only the leading rows x row_bytes
        tiles = np.zeros((2, 64, 64), np.uint16)
        tiles[:, :30, :40] = rng.integers(0, 60000, (2, 30, 40))
        filtered = self._filtered(tiles)
        streams, lengths = (
            np.asarray(a)
            for a in deflate_filtered_batch(filtered, 30, 1 + 40 * 2)
        )
        host = np.asarray(filtered)[:, :30, : 1 + 40 * 2]
        for lane in range(2):
            got = zlib.decompress(bytes(streams[lane][: lengths[lane]]))
            assert got == host[lane].tobytes()

    def test_stored_mode(self):
        tiles = rng.integers(0, 255, (2, 32, 32), dtype=np.uint8)
        filtered = self._filtered(tiles)
        streams, lengths = (
            np.asarray(a)
            for a in deflate_filtered_batch(
                filtered, 32, 33, mode="stored"
            )
        )
        host = np.asarray(filtered)
        for lane in range(2):
            assert lengths[lane] == stored_stream_len(32 * 33)
            got = zlib.decompress(bytes(streams[lane][: lengths[lane]]))
            assert got == host[lane].tobytes()

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError):
            deflate_filtered_batch(np.zeros((1, 8, 8), np.uint8), 8, 8,
                                   mode="huffman")


class TestPipelineDeviceDeflate:
    """End-to-end: handle_batch with the knob on serves pixel-identical
    PNGs through the device bucket path."""

    @pytest.fixture(scope="class")
    def service(self, tmp_path_factory):
        from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            ImageRegistry,
            PixelsService,
        )

        root = tmp_path_factory.mktemp("devdeflate")
        path = str(root / "img.ome.tiff")
        img = rng.integers(0, 60000, (1, 1, 1, 300, 300), dtype=np.uint16)
        write_ome_tiff(path, img, tile_size=(64, 64))
        registry = ImageRegistry()
        registry.add(1, path)
        svc = PixelsService(registry)
        yield svc, img
        svc.close()

    def _ctxs(self):
        from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

        return [
            TileCtx(image_id=1, z=0, c=0, t=0,
                    region=RegionDef(x, y, w, h), format="png",
                    omero_session_key="k")
            for x, y, w, h in [
                (0, 0, 64, 64), (64, 64, 64, 64),
                (128, 0, 100, 80),   # padded lane, same bucket
                (0, 128, 256, 128),  # larger bucket
            ]
        ]

    def test_pixel_equality_vs_source(self, service):
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        svc, img = service
        pipe = TilePipeline(svc, engine="device", device_deflate=True)
        pipe.mesh = None
        ctxs = self._ctxs()
        results = pipe.handle_batch(ctxs)
        assert all(r is not None for r in results)
        for ctx, png in zip(ctxs, results):
            decoded = np.array(Image.open(io.BytesIO(png)))
            r = ctx.region
            expect = img[0, 0, 0, r.y : r.y + r.height,
                         r.x : r.x + r.width]
            np.testing.assert_array_equal(decoded, expect)

    def test_matches_host_engine_pixels(self, service):
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        svc, _ = service
        dev = TilePipeline(svc, engine="device", device_deflate=True)
        dev.mesh = None
        host = TilePipeline(svc, engine="host")
        ctxs = self._ctxs()
        for d, h in zip(dev.handle_batch(ctxs), host.handle_batch(self._ctxs())):
            dp = np.array(Image.open(io.BytesIO(d)))
            hp = np.array(Image.open(io.BytesIO(h)))
            np.testing.assert_array_equal(dp, hp)

    def test_mesh_path_with_device_deflate(self, service):
        import jax

        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        svc, img = service
        assert len(jax.devices()) == 8
        pipe = TilePipeline(svc, engine="device", device_deflate=True)
        assert pipe._get_mesh() is not None
        results = pipe.handle_batch(self._ctxs())
        assert all(r is not None for r in results)
        for ctx, png in zip(self._ctxs(), results):
            decoded = np.array(Image.open(io.BytesIO(png)))
            r = ctx.region
            np.testing.assert_array_equal(
                decoded,
                img[0, 0, 0, r.y : r.y + r.height, r.x : r.x + r.width],
            )

    def test_adaptive_cap_across_batches(self, service):
        """The one-sync transfer's compressed-size guess adapts: the
        first batch may overflow it (incompressible noise), later
        batches reuse the learned cap — all pixel-exact either way."""
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        svc, img = service
        pipe = TilePipeline(svc, engine="device", device_deflate=True)
        pipe.mesh = None
        for _ in range(3):  # fresh guess -> overflow -> learned cap
            results = pipe.handle_batch(self._ctxs())
            for ctx, png in zip(self._ctxs(), results):
                decoded = np.array(Image.open(io.BytesIO(png)))
                r = ctx.region
                np.testing.assert_array_equal(
                    decoded,
                    img[0, 0, 0, r.y : r.y + r.height,
                        r.x : r.x + r.width],
                )
        assert pipe._dd_cap  # the guess was learned

    def test_config_knob_reaches_pipeline(self):
        from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
        from omero_ms_pixel_buffer_tpu.utils.config import Config

        config = Config.from_dict(
            {"session-store": {"type": "memory"},
             "backend": {"engine": "host"}}
        )
        assert config.backend.png.device_deflate is True  # default on
        app = PixelBufferApp(config)
        assert app.pipeline.device_deflate is True

        config_off = Config.from_dict(
            {"session-store": {"type": "memory"},
             "backend": {"engine": "host",
                         "png": {"device-deflate": False}}}
        )
        assert config_off.backend.png.device_deflate is False
        app_off = PixelBufferApp(config_off)
        assert app_off.pipeline.device_deflate is False


class TestShardedEncode:
    """Real multi-chip dispatch: the fused filter+deflate chain
    shard_mapped over the 8-way CPU host-platform mesh must produce
    BYTE-identical streams to the single-device program."""

    def test_shard_map_roundtrip_byte_identical(self):
        import jax
        import jax.numpy as jnp

        from omero_ms_pixel_buffer_tpu.parallel.mesh import make_mesh
        from omero_ms_pixel_buffer_tpu.parallel.sharding import (
            pad_batch,
            shard_batch,
            sharded_filter_deflate,
        )

        assert len(jax.devices()) == 8
        mesh = make_mesh(("data",))
        tiles = rng.integers(0, 60000, (13, 32, 32), dtype=np.uint16)
        padded, real = pad_batch(jnp.asarray(tiles), 8)
        sharded = shard_batch(mesh, padded)
        s_mesh, l_mesh = (
            np.asarray(a)
            for a in sharded_filter_deflate(mesh, sharded, 32, 65, 2)
        )
        s_one, l_one = (
            np.asarray(a)
            for a in fused_filter_deflate_batch(
                jnp.asarray(tiles), 32, 65, 2
            )
        )
        np.testing.assert_array_equal(l_mesh[:real], l_one)
        np.testing.assert_array_equal(s_mesh[:real], s_one)
        for lane in range(real):
            got = zlib.decompress(
                bytes(s_mesh[lane][: l_mesh[lane]])
            )
            assert len(got) == 32 * 65

    def test_per_device_lane_counts(self):
        from omero_ms_pixel_buffer_tpu.parallel.mesh import lane_counts

        assert lane_counts(13, 8) == [2, 2, 2, 2, 2, 2, 1, 0]
        assert lane_counts(16, 8) == [2] * 8
        assert lane_counts(3, 8) == [1, 1, 1, 0, 0, 0, 0, 0]
        assert sum(lane_counts(9, 8)) == 9


@pytest.mark.resilience
class TestMeshDegradation:
    """Chaos: one mesh chip's fault point fires; the batch completes
    on the surviving chips instead of failing the requests."""

    @pytest.fixture(autouse=True)
    def _clean(self):
        from omero_ms_pixel_buffer_tpu.resilience import BOARD, INJECTOR

        yield
        INJECTOR.clear()
        BOARD.reset()
        BOARD.configure(enabled=True)

    def test_sick_chip_degrades_to_survivors(self):
        import jax
        import jax.numpy as jnp

        from omero_ms_pixel_buffer_tpu.models.device_dispatch import (
            DeviceEncodeDispatcher,
        )
        from omero_ms_pixel_buffer_tpu.parallel.mesh import MeshManager
        from omero_ms_pixel_buffer_tpu.resilience import INJECTOR
        from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
            always,
            first_n,
        )

        devices = jax.devices()
        assert len(devices) == 8
        sick = devices[3]
        # the first sharded dispatch blows up (a wedged chip surfaces
        # as the whole program failing)...
        INJECTOR.install(
            "device.mesh-dispatch", first_n(1, RuntimeError("ICI wedge"))
        )
        # ...and the probe pass finds exactly chip 3 dead
        INJECTOR.install(
            f"device.chip:{sick.id}", always(RuntimeError("chip down"))
        )
        mgr = MeshManager(devices=devices)
        disp = DeviceEncodeDispatcher({}, mesh_manager=mgr)
        tiles = rng.integers(0, 60000, (16, 32, 32), dtype=np.uint16)
        fut = disp.submit(
            tiles, 32, 65, 2, "up", "rle",
            lanes=list(range(16)), sizes=[(32, 32)] * 16,
            bit_depth=16, color_type=0,
        )
        out = fut.result(timeout=120)
        assert sorted(out) == list(range(16))
        assert mgr.last_dispatch["executed"] is True
        assert mgr.last_dispatch["n_devices"] == 7
        assert sick.id not in mgr.last_dispatch["device_ids"]
        assert sum(mgr.last_dispatch["lanes_per_device"]) == 16
        # byte-identical to the single-device encode of the same lanes
        s_one, l_one = (
            np.asarray(a)
            for a in fused_filter_deflate_batch(
                jnp.asarray(tiles), 32, 65, 2
            )
        )
        from omero_ms_pixel_buffer_tpu.ops.png import frame_png

        for lane in range(16):
            assert out[lane] == frame_png(
                bytes(s_one[lane][: l_one[lane]]), 32, 32, 16, 0
            )
        disp.close()

    def test_all_chips_down_raises(self):
        import jax

        from omero_ms_pixel_buffer_tpu.parallel.mesh import (
            MeshHealthError,
            MeshManager,
        )
        from omero_ms_pixel_buffer_tpu.resilience import INJECTOR
        from omero_ms_pixel_buffer_tpu.resilience.faultinject import always

        INJECTOR.install(
            "device.mesh-dispatch", always(RuntimeError("bus fire"))
        )
        for dev in jax.devices():
            INJECTOR.install(
                f"device.chip:{dev.id}", always(RuntimeError("down"))
            )
        mgr = MeshManager()
        with pytest.raises((MeshHealthError, RuntimeError)):
            mgr.dispatch(lambda mesh: mesh)

    def test_pipeline_batch_survives_sick_chip(self, tmp_path):
        """End-to-end: handle_batch with a serving mesh completes (and
        stays pixel-exact) while one chip is injected dead."""
        import jax

        from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            ImageRegistry,
            PixelsService,
        )
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )
        from omero_ms_pixel_buffer_tpu.resilience import INJECTOR
        from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
            always,
            first_n,
        )
        from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

        img = rng.integers(0, 60000, (1, 1, 1, 128, 128), dtype=np.uint16)
        path = str(tmp_path / "chaos.ome.tiff")
        write_ome_tiff(path, img, tile_size=(32, 32))
        registry = ImageRegistry()
        registry.add(1, path)
        svc = PixelsService(registry)
        try:
            pipe = TilePipeline(
                svc, engine="device", device_deflate=True,
                use_plane_cache=False,
            )
            assert pipe._get_mesh() is not None
            sick = jax.devices()[5]
            INJECTOR.install(
                "device.mesh-dispatch",
                first_n(1, RuntimeError("ICI wedge")),
            )
            INJECTOR.install(
                f"device.chip:{sick.id}", always(RuntimeError("down"))
            )
            ctxs = [
                TileCtx(image_id=1, z=0, c=0, t=0,
                        region=RegionDef(32 * (i % 4), 32 * (i // 4),
                                         32, 32),
                        format="png", omero_session_key="k")
                for i in range(16)
            ]
            results = pipe.handle_batch(ctxs)
            assert all(isinstance(r, bytes) for r in results)
            assert pipe.last_mesh_dispatch["n_devices"] == 7
            for ctx, png in zip(ctxs, results):
                decoded = np.array(Image.open(io.BytesIO(png)))
                r = ctx.region
                np.testing.assert_array_equal(
                    decoded,
                    img[0, 0, 0, r.y : r.y + r.height,
                        r.x : r.x + r.width],
                )
        finally:
            svc.close()


class TestCompilationCache:
    """config `jax.compilation-cache-dir` -> runtime/jax_cache: the
    explicit dir engages on any backend, device programs land in it,
    and a second TilePipeline construction reuses the same dir."""

    def test_config_key_validated(self):
        from omero_ms_pixel_buffer_tpu.utils.config import (
            Config,
            ConfigError,
        )

        cfg = Config.from_dict(
            {"session-store": {"type": "memory"},
             "jax": {"compilation-cache-dir": "/tmp/x"}}
        )
        assert cfg.jax.compilation_cache_dir == "/tmp/x"
        with pytest.raises(ConfigError):
            Config.from_dict(
                {"session-store": {"type": "memory"},
                 "jax": {"compilation-cache-dir": 17}}
            )
        with pytest.raises(ConfigError):
            Config.from_dict(
                {"session-store": {"type": "memory"},
                 "jax": {"compilation-cache-dirr": "/tmp/x"}}
            )

    def test_second_pipeline_hits_cache_dir(self, tmp_path, monkeypatch):
        import jax

        from omero_ms_pixel_buffer_tpu.runtime import jax_cache

        cache_dir = str(tmp_path / "xla-cache")
        # the module pins the dir process-globally once; reset for the
        # test (and restore after)
        monkeypatch.setattr(jax_cache, "_done", False)
        monkeypatch.setattr(jax_cache, "_enabled_path", None)
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        class _Svc:  # construction needs only the signature probe
            def get_pixel_buffer(self, image_id):
                return None

        TilePipeline(_Svc(), compilation_cache_dir=cache_dir)
        assert jax_cache.enabled_path() == cache_dir
        assert jax.config.jax_compilation_cache_dir == cache_dir
        # a device encode program persists into the dir...
        payload = np.zeros((1, 513), np.uint8)
        zlib_rle_batch(payload)
        entries = set(os.listdir(cache_dir))
        assert entries, "no compile-cache entries written"
        # ...and a second pipeline construction reuses the SAME dir
        # (idempotent enable), so a re-jit after dropping the in-
        # memory caches reloads from disk instead of recompiling
        TilePipeline(_Svc(), compilation_cache_dir=cache_dir)
        assert jax_cache.enabled_path() == cache_dir
        jax.clear_caches()
        zlib_rle_batch(payload)
        assert set(os.listdir(cache_dir)) == entries, (
            "second run recompiled instead of hitting the cache dir"
        )


# ---------------------------------------------------------------------------
# Dynamic-Huffman two-pass encode (r12)
# ---------------------------------------------------------------------------


def _dyn_corpus(n: int = 1500):
    """Randomized + pathological lanes for the dynamic bitstream: runs,
    no-runs, white noise, single-value, skewed alphabets."""
    r = np.random.default_rng(97)
    return np.stack([
        np.zeros(n, np.uint8),                          # all one run
        r.integers(0, 256, n).astype(np.uint8),         # white noise
        np.tile(np.array([5, 9], np.uint8), (n + 1) // 2)[:n],  # no runs
        np.repeat(r.integers(0, 4, (n + 39) // 40), 40)[:n].astype(
            np.uint8
        ),                                              # long runs
        (r.integers(0, 4, n) ** 3 % 7).astype(np.uint8),  # skewed alphabet
        np.arange(n, dtype=np.uint64).view(np.uint8)[:n],  # structured
    ])


class TestDynamicHuffman:
    """The two-pass canonical-code path: decode-exactness over the
    corpus, the per-lane min(dynamic, fixed, stored) guarantee, and the
    ratio win on low-run content it exists for."""

    def test_randomized_corpus_decodes_exact(self):
        from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
            zlib_dynamic_batch,
        )

        for n in (1, 2, 3, 257, 1500, 70000):  # incl. single-byte + >64K
            batch = _dyn_corpus(1500)[:, :n] if n <= 1500 else np.stack(
                [
                    np.resize(lane, n)
                    for lane in _dyn_corpus(1500)
                ]
            )
            streams, lengths = (
                np.asarray(a) for a in zlib_dynamic_batch(batch)
            )
            for i in range(batch.shape[0]):
                got = zlib.decompress(bytes(streams[i][: lengths[i]]))
                assert got == batch[i].tobytes(), (n, i)

    def test_selection_never_exceeds_stored_bound(self):
        from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
            zlib_dynamic_batch,
        )

        r = np.random.default_rng(11)
        for trial in range(6):
            n = int(r.integers(1, 4000))
            batch = np.stack([
                r.integers(0, 256, n).astype(np.uint8),
                np.tile(np.array([1, 2], np.uint8), (n + 1) // 2)[:n],
                r.integers(0, 2, n).astype(np.uint8),
            ])
            _, lengths = zlib_dynamic_batch(batch)
            assert (
                np.asarray(lengths) <= stored_stream_len(n)
            ).all(), (trial, n)

    def test_dynamic_never_worse_than_fixed(self):
        from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
            zlib_dynamic_batch,
        )

        batch = _dyn_corpus(2000)
        _, dyn = zlib_dynamic_batch(batch)
        _, rle = zlib_rle_batch(batch)
        assert (np.asarray(dyn) <= np.asarray(rle)).all()

    def test_ratio_bound_on_rendered_rgb(self):
        """THE acceptance pin: <= 1.10x host zlib-6 bytes on the
        rendered-RGB fixture (the fixed-Huffman stream measured ~1.4x
        there)."""
        import jax.numpy as jnp

        from omero_ms_pixel_buffer_tpu.ops.convert import (
            to_big_endian_bytes,
        )
        from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
            fused_filter_deflate_dynamic,
        )
        from omero_ms_pixel_buffer_tpu.ops.png import filter_batch
        from omero_ms_pixel_buffer_tpu.runtime.microbench import (
            synth_rgb_tiles,
        )

        b, tile = 4, 128
        rgb = synth_rgb_tiles(b, tile, tile, seed=5)
        rows = 1 + tile * 3
        _, lengths = fused_filter_deflate_dynamic(rgb, tile, rows, 3)
        filt = np.asarray(filter_batch(
            to_big_endian_bytes(jnp.asarray(rgb)).reshape(
                b, tile, tile * 3
            ),
            3, "up",
        ))
        host = np.array(
            [len(zlib.compress(filt[i].tobytes(), 6)) for i in range(b)]
        )
        ratio = float(np.asarray(lengths, np.int64).mean() / host.mean())
        assert ratio <= 1.10, f"dynamic ratio {ratio:.3f} > 1.10x host"

    def test_deflate_filtered_batch_dynamic_mode(self):
        from omero_ms_pixel_buffer_tpu.ops.pallas.filter import (
            filter_tiles,
        )

        tiles = rng.integers(0, 60000, (3, 32, 32)).astype(np.uint16)
        filtered = filter_tiles(tiles, "up")
        streams, lengths = (
            np.asarray(a)
            for a in deflate_filtered_batch(
                filtered, 32, 1 + 64, mode="dynamic"
            )
        )
        payloads = np.asarray(filtered)[:, :32, : 1 + 64]
        for i in range(3):
            got = zlib.decompress(bytes(streams[i][: lengths[i]]))
            assert got == payloads[i].tobytes()

    def test_packers_bit_exact_for_dynamic_tokens(self):
        """The Pallas kernels must agree with the scan packer on
        DYNAMIC token streams too (1..20-bit codes, explicit EOB)."""
        from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
            zlib_dynamic_batch,
        )

        batch = _dyn_corpus(1200)
        s0, l0 = (np.asarray(a) for a in zlib_dynamic_batch(
            batch, packer="scan"
        ))
        for packer in ("pallas", "pallas_dense"):
            s1, l1 = (np.asarray(a) for a in zlib_dynamic_batch(
                batch, packer=packer
            ))
            assert (l0 == l1).all(), packer
            assert (s0 == s1).all(), packer


class TestScalarPrefetchEmit:
    """The r12 PrefetchScalarGridSpec kernel: bit-exact against the
    XLA scan packer in interpret mode, with the op-count reduction
    pinned analytically (not timed — CI boxes are noisy)."""

    @pytest.mark.parametrize("n", [17, 256, 1000, 5000])
    def test_bit_exact_vs_scan(self, n):
        payloads = _payload_families(n)
        s0, l0 = (np.asarray(a) for a in zlib_rle_batch(
            payloads, packer="scan"
        ))
        s1, l1 = (np.asarray(a) for a in zlib_rle_batch(
            payloads, packer="pallas"
        ))
        assert (l0 == l1).all()
        assert (s0 == s1).all()

    def test_matches_dense_kernel(self, ):
        payloads = _payload_families(2048)
        s0, l0 = (np.asarray(a) for a in zlib_rle_batch(
            payloads, packer="pallas_dense"
        ))
        s1, l1 = (np.asarray(a) for a in zlib_rle_batch(
            payloads, packer="pallas"
        ))
        assert (l0 == l1).all()
        assert (s0 == s1).all()

    def test_op_count_reduction_pinned(self):
        from omero_ms_pixel_buffer_tpu.ops.pallas.bitpack import (
            emit_ops_per_token,
        )

        dense = emit_ops_per_token("dense")
        sp = emit_ops_per_token("sp")
        assert sp * 4 < dense, (
            f"scalar-prefetch emit ({sp:.0f} ops/token) must cut the "
            f"dense emit ({dense:.0f}) by >= 4x"
        )

    def test_default_packer_names(self):
        from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
            default_packer,
        )

        for name in ("scan", "pallas", "pallas_dense", "gather"):
            os.environ["OMPB_BITPACK"] = name
            try:
                assert default_packer() == name
            finally:
                del os.environ["OMPB_BITPACK"]


# ---------------------------------------------------------------------------
# Streaming cross-batch encode queue (r12)
# ---------------------------------------------------------------------------


class TestStreamingQueue:
    """The persistent submit/readback queue: bounded in-flight groups,
    non-blocking submission, clean drain, cross-batch reuse, and
    byte-identity with the direct fused encode."""

    def _dispatcher(self, queue_depth=2):
        from omero_ms_pixel_buffer_tpu.models.device_dispatch import (
            DeviceEncodeDispatcher,
        )

        return DeviceEncodeDispatcher({}, queue_depth=queue_depth)

    def _tiles(self, b=2, n=16):
        return rng.integers(0, 60000, (b, n, n)).astype(np.uint16)

    def _submit(self, disp, tiles, mode="rle"):
        b, n = tiles.shape[0], tiles.shape[1]
        return disp.submit(
            tiles, n, 1 + n * 2, 2, "up", mode,
            list(range(b)), [(n, n)] * b, 16, 0,
        )

    def test_groups_resolve_to_pngs(self):
        disp = self._dispatcher()
        try:
            tiles = self._tiles()
            for mode in ("rle", "dynamic", "stored"):
                out = self._submit(disp, tiles, mode).result(timeout=120)
                assert set(out) == {0, 1}
                for i, png in out.items():
                    decoded = np.array(Image.open(io.BytesIO(png)))
                    np.testing.assert_array_equal(decoded, tiles[i])
        finally:
            disp.close()

    def test_bounded_inflight_and_nonblocking_submit(self):
        """queue_depth bounds the groups in flight: with the readback
        worker wedged, the third group's staging must WAIT (on the
        queue's submit thread, not the caller), and the caller-facing
        submit returns immediately."""
        import threading
        import time as _time

        from omero_ms_pixel_buffer_tpu.models import device_dispatch as dd

        disp = self._dispatcher(queue_depth=2)
        gate = threading.Event()
        real = dd.DeviceEncodeDispatcher._readback_group

        def gated(self, *args, **kwargs):
            gate.wait(timeout=60)
            return real(self, *args, **kwargs)

        try:
            disp._readback_group = gated.__get__(disp)
            tiles = self._tiles()
            t0 = _time.perf_counter()
            futs = [self._submit(disp, tiles) for _ in range(3)]
            submit_dt = _time.perf_counter() - t0
            assert submit_dt < 5.0, "submit must not block the caller"
            deadline = _time.perf_counter() + 30
            while disp._groups < 2 and _time.perf_counter() < deadline:
                _time.sleep(0.01)
            _time.sleep(0.2)  # give group 3 a chance to (wrongly) launch
            assert disp._groups == 2, "3rd group launched past the bound"
            assert disp._inflight == 2
            gate.set()
            for fut in futs:
                assert set(fut.result(timeout=120)) == {0, 1}
            snap = disp.snapshot()
            assert snap["groups"] == 3
            assert snap["inflight"] == 0
        finally:
            gate.set()
            disp.close()

    def test_close_drains_pending_groups(self):
        disp = self._dispatcher()
        tiles = self._tiles()
        futs = [self._submit(disp, tiles) for _ in range(3)]
        disp.close()  # must DRAIN, not abandon
        for fut in futs:
            assert set(fut.result(timeout=5)) == {0, 1}
        with pytest.raises(RuntimeError):
            self._submit(disp, tiles)

    def test_close_drain_deadline_on_wedged_group(self):
        """A group wedged inside the device wait must not hold close()
        hostage: past the drain deadline the leftover futures resolve
        exceptionally (callers host-fall-back) and close() returns."""
        import threading
        import time as _time

        disp = self._dispatcher(queue_depth=2)
        gate = threading.Event()
        real = disp._readback_group

        def wedged(*args, **kwargs):
            gate.wait(timeout=60)  # simulates a dropped-tunnel hang
            return real(*args, **kwargs)

        disp._readback_group = wedged
        try:
            tiles = self._tiles()
            futs = [self._submit(disp, tiles) for _ in range(3)]
            t0 = _time.perf_counter()
            disp.close(drain_timeout=0.5)
            assert _time.perf_counter() - t0 < 10.0, (
                "close() blocked past the drain deadline"
            )
            for fut in futs:
                with pytest.raises(TimeoutError):
                    fut.result(timeout=5)
        finally:
            # unwedge so the abandoned worker threads exit (their late
            # set_result loses the race benignly — the guarded path)
            gate.set()

    def test_cross_batch_queue_persistence(self, ):
        """Consecutive handle_batch calls feed the SAME queue: the
        dispatcher (and its telemetry) survives the batcher boundary."""
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )

        pipe, img = _mini_pipeline()
        try:
            ctxs = _mini_ctxs(4)
            pipe.handle_batch(ctxs[:2])
            disp1 = pipe._dispatcher
            g1 = disp1._groups
            assert disp1 is not None and g1 >= 1
            pipe.handle_batch(ctxs[2:])
            assert pipe._dispatcher is disp1, "queue rebuilt per batch"
            assert disp1._groups > g1, "second batch bypassed the queue"
        finally:
            pipe.close()
            pipe.pixels_service.close()

    def test_byte_identity_vs_direct_fused_encode(self):
        """The queue path's PNGs are byte-identical to framing the
        fused program's streams directly (the r05 single-batch path):
        the queue changes WHEN work runs, never what it computes."""
        from omero_ms_pixel_buffer_tpu.ops.device_deflate import (
            fused_filter_deflate_batch,
        )
        from omero_ms_pixel_buffer_tpu.ops.png import frame_png

        for mode in ("rle", "dynamic"):
            disp = self._dispatcher()
            try:
                tiles = self._tiles(b=3, n=16)
                out = self._submit(disp, tiles, mode).result(timeout=120)
                streams, lengths = (
                    np.asarray(a) for a in fused_filter_deflate_batch(
                        tiles, 16, 1 + 32, 2, mode=mode
                    )
                )
                for i in range(3):
                    direct = frame_png(
                        streams[i][: lengths[i]].tobytes(), 16, 16, 16, 0
                    )
                    assert out[i] == direct, (mode, i)
            finally:
                disp.close()


def _mini_pipeline():
    """A tiny device pipeline over a generated OME-TIFF (module-level
    so several suites share it without the class fixture plumbing)."""
    import tempfile

    from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
    from omero_ms_pixel_buffer_tpu.io.pixels_service import (
        ImageRegistry,
        PixelsService,
    )
    from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline

    root = tempfile.mkdtemp(prefix="ompb_queue_")
    path = os.path.join(root, "img.ome.tiff")
    img = rng.integers(0, 60000, (1, 1, 1, 128, 128), dtype=np.uint16)
    write_ome_tiff(path, img, tile_size=(64, 64))
    registry = ImageRegistry()
    registry.add(1, path)
    svc = PixelsService(registry)
    pipe = TilePipeline(
        svc, engine="device", device_deflate=True, buckets=(64,)
    )
    pipe.mesh = None
    return pipe, img


def _mini_ctxs(n):
    from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

    coords = [(0, 0), (64, 0), (0, 64), (64, 64)]
    return [
        TileCtx(image_id=1, z=0, c=0, t=0,
                region=RegionDef(*coords[i % 4], 64, 64), format="png",
                omero_session_key="k")
        for i in range(n)
    ]


@pytest.mark.resilience
class TestQueueChaos:
    """Chaos lane: a wedged in-flight group degrades THAT group to the
    host fallback without stalling or reordering later batches."""

    @pytest.fixture(autouse=True)
    def _clean(self):
        from omero_ms_pixel_buffer_tpu.resilience import INJECTOR

        yield
        INJECTOR.clear()

    def test_wedged_group_degrades_to_host_without_stalling(self):
        from omero_ms_pixel_buffer_tpu.resilience import INJECTOR
        from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
            first_n,
        )

        pipe, img = _mini_pipeline()
        try:
            # wedge exactly the FIRST group the queue ever stages
            INJECTOR.install(
                "device.encode-group",
                first_n(1, RuntimeError("wedged in-flight group")),
            )
            ctxs = _mini_ctxs(4)
            results = pipe.handle_batch(ctxs[:2])
            assert all(r is not None for r in results), (
                "wedged group must host-fall-back, not 404"
            )
            for ctx, png in zip(ctxs[:2], results):
                decoded = np.array(Image.open(io.BytesIO(png)))
                r = ctx.region
                np.testing.assert_array_equal(
                    decoded,
                    img[0, 0, 0, r.y : r.y + r.height,
                        r.x : r.x + r.width],
                )
            # later batches flow through the SAME queue unharmed
            results2 = pipe.handle_batch(ctxs[2:])
            assert all(r is not None for r in results2)
            assert INJECTOR.calls("device.encode-group") >= 2
        finally:
            pipe.close()
            pipe.pixels_service.close()


@pytest.mark.resilience
class TestMeshResizeWarmup:
    """A probe-shrink (or heal) changes the padded batch width; the
    dispatcher must pre-warm known group shapes for the NEW width on a
    background thread instead of paying the compile inline."""

    @pytest.fixture(autouse=True)
    def _clean(self):
        from omero_ms_pixel_buffer_tpu.resilience import BOARD, INJECTOR

        yield
        INJECTOR.clear()
        BOARD.reset()
        BOARD.configure(enabled=True)

    def test_width_change_prewarms_seen_shapes(self):
        import jax

        from omero_ms_pixel_buffer_tpu.models.device_dispatch import (
            DeviceEncodeDispatcher,
        )
        from omero_ms_pixel_buffer_tpu.parallel.mesh import MeshManager
        from omero_ms_pixel_buffer_tpu.resilience import INJECTOR
        from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
            first_n,
        )

        devices = jax.devices()
        assert len(devices) == 8
        mgr = MeshManager(devices=devices)
        mgr.mesh()  # establish the 8-wide baseline
        disp = DeviceEncodeDispatcher({}, mesh_manager=mgr)
        try:
            tiles = rng.integers(0, 60000, (8, 16, 16)).astype(np.uint16)
            out = disp.submit(
                tiles, 16, 1 + 32, 2, "up", "rle",
                list(range(8)), [(16, 16)] * 8, 16, 0,
            ).result(timeout=120)
            assert len(out) == 8
            assert disp._seen_mesh, "mesh group shape not registered"
            # chip 3 fails its probe -> width 8 -> 7 -> warmup fires
            INJECTOR.install(
                f"device.chip:{devices[3].id}",
                first_n(1, RuntimeError("dead chip")),
            )
            assert mgr.probe_device(devices[3]) is False
            warm = getattr(disp, "_warm_thread", None)
            assert warm is not None, "width change spawned no warmup"
            warm.join(timeout=120)
            assert any(w == 7 for (w, _) in disp._warmed), (
                "no shape pre-warmed for the shrunken width"
            )
            # the chip heals -> width back to 8 -> warmup again
            assert mgr.probe_device(devices[3]) is True
            warm = disp._warm_thread
            warm.join(timeout=120)
            assert any(w == 8 for (w, _) in disp._warmed)
        finally:
            disp.close()
