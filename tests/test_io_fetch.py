"""The batched read plane (r14): shared per-host fetch pool, ranged
GETs, the dedupe/coalesce planner, negative-chunk caching, and the
chaos lanes (fault -> single-key fallback, dead store -> breaker,
hung fetch -> timeout, expired deadline -> 504 path).
"""

import functools
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from omero_ms_pixel_buffer_tpu.io import fetch
from omero_ms_pixel_buffer_tpu.io.fetch import (
    FetchPool,
    FetchStats,
    RangeReq,
    StoreError,
    StoreUnavailableError,
    fetch_many,
    io_snapshot,
)
from omero_ms_pixel_buffer_tpu.io.pixel_buffer import (
    BlockCache,
    set_negative_ttl,
)
from omero_ms_pixel_buffer_tpu.io.stores import (
    FileStore,
    HTTPStore,
    _project_range,
    _range_header,
)
from omero_ms_pixel_buffer_tpu.io.zarr import ZarrPixelBuffer, write_ngff
from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
from omero_ms_pixel_buffer_tpu.resilience.deadline import (
    Deadline,
    DeadlineExceeded,
    deadline_scope,
)
from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
    INJECTOR,
    always,
)
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError


@pytest.fixture(autouse=True)
def _clean_io():
    """Every test starts with chaos off, stock read-plane config, and
    closed breakers — and leaves it that way."""
    yield
    INJECTOR.clear()
    BOARD.reset()
    fetch.CONFIG.parallel = True
    fetch.CONFIG.coalesce_gap_bytes = 64 << 10
    set_negative_ttl(300.0)


class RangeHandler(BaseHTTPRequestHandler):
    """Range-capable static handler with keep-alive (HTTP/1.1) and
    per-class request/concurrency accounting — the loopback stand-in
    for a remote object store."""

    protocol_version = "HTTP/1.1"
    # class-level accounting (reset per test via reset())
    requests: list = []
    active = 0
    max_active = 0
    delay_s = 0.0
    _stats_lock = threading.Lock()

    def __init__(self, root, *args, **kwargs):
        self.root = root
        super().__init__(*args, **kwargs)

    @classmethod
    def reset(cls):
        with cls._stats_lock:
            cls.requests = []
            cls.active = 0
            cls.max_active = 0
            cls.delay_s = 0.0

    def log_message(self, *a):
        pass

    def _reply(self, code, body=b"", extra=None):
        self.send_response(code)
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        import urllib.parse

        cls = type(self)
        with cls._stats_lock:
            cls.requests.append(
                (self.path, self.headers.get("Range"))
            )
            cls.active += 1
            cls.max_active = max(cls.max_active, cls.active)
        try:
            if cls.delay_s:
                time.sleep(cls.delay_s)
            rel = urllib.parse.unquote(self.path.lstrip("/"))
            if ".." in rel:
                return self._reply(400)
            path = os.path.join(self.root, rel)
            if not os.path.isfile(path):
                return self._reply(404)
            with open(path, "rb") as f:
                data = f.read()
            rng = self.headers.get("Range")
            if rng is None:
                return self._reply(200, data)
            spec = rng.split("=", 1)[1]
            if spec.startswith("-"):  # suffix
                n = int(spec[1:])
                body = data[-n:] if n <= len(data) else data
                return self._reply(206, body)
            lo_s, _, hi_s = spec.partition("-")
            lo = int(lo_s)
            if lo >= len(data):
                return self._reply(416)
            hi = int(hi_s) + 1 if hi_s else len(data)
            return self._reply(206, data[lo:min(hi, len(data))])
        finally:
            with cls._stats_lock:
                cls.active -= 1


class NoRangeHandler(RangeHandler):
    """An origin that ignores Range entirely (always 200 + full
    body) — the degradation every ranged client must survive."""

    def do_GET(self):
        if self.headers.get("Range") is not None:
            del self.headers["Range"]
        return super().do_GET()


def serve(root, handler_cls):
    handler_cls.reset()
    server = ThreadingHTTPServer(
        ("127.0.0.1", 0), functools.partial(handler_cls, root)
    )
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


@pytest.fixture()
def payload_dir(tmp_path):
    (tmp_path / "obj").write_bytes(bytes(range(256)) * 16)  # 4096 B
    (tmp_path / "small").write_bytes(b"hello world")
    return str(tmp_path)


# ---------------------------------------------------------------------------
# range plumbing
# ---------------------------------------------------------------------------


class TestRangeSpelling:
    def test_header_forms(self):
        assert _range_header(0, 10) == "bytes=0-9"
        assert _range_header(100, 1) == "bytes=100-100"
        assert _range_header(5, None) == "bytes=5-"
        assert _range_header(-32, 32) == "bytes=-32"

    def test_project_range(self):
        body = bytes(range(100))
        assert _project_range(body, 10, 5) == body[10:15]
        assert _project_range(body, -7, 7) == body[-7:]
        assert _project_range(body, 0, None) == body
        # suffix longer than the body: the whole body (an absent
        # prefix cannot be invented)
        assert _project_range(b"ab", -10, 10) == b"ab"


class TestFileStoreRanges:
    def test_bounded_suffix_missing(self, payload_dir):
        fs = FileStore(payload_dir)
        data = bytes(range(256)) * 16
        assert fs.get_range("obj", 100, 20) == data[100:120]
        assert fs.get_range("obj", -64, 64) == data[-64:]
        assert fs.get_range("obj", 10, None) == data[10:]
        assert fs.get_range("nope", 0, 4) is None
        # short object: returns what exists; callers validate length
        assert fs.get_range("small", 8, 100) == b"rld"


class TestHTTPStoreRanges:
    def test_206_and_suffix(self, payload_dir):
        server = serve(payload_dir, RangeHandler)
        try:
            store = HTTPStore(
                f"http://127.0.0.1:{server.server_address[1]}"
            )
            data = bytes(range(256)) * 16
            assert store.get_range("obj", 32, 64) == data[32:96]
            assert store.get_range("obj", -100, 100) == data[-100:]
            assert store.get_range("missing", 0, 4) is None
            with pytest.raises(StoreError):
                store.get_range("obj", 999999, 4)  # 416
        finally:
            server.shutdown()

    def test_range_ignoring_origin_sliced_locally(self, payload_dir):
        server = serve(payload_dir, NoRangeHandler)
        try:
            store = HTTPStore(
                f"http://127.0.0.1:{server.server_address[1]}"
            )
            data = bytes(range(256)) * 16
            assert store.get_range("obj", 32, 64) == data[32:96]
            assert store.get_range("obj", -8, 8) == data[-8:]
        finally:
            server.shutdown()


class TestFetchPool:
    def test_keepalive_reuse(self, payload_dir):
        server = serve(payload_dir, RangeHandler)
        try:
            pool = FetchPool(max_per_host=4)
            url = (
                f"http://127.0.0.1:{server.server_address[1]}/small"
            )
            for _ in range(5):
                status, body = pool.request(url, {}, 5.0)
                assert status == 200 and body == b"hello world"
            snap = pool.snapshot()
            host = next(iter(snap["hosts"].values()))
            # all five requests rode ONE pooled connection
            assert host["idle"] == 1 and host["in_use"] == 0
        finally:
            server.shutdown()

    def test_per_host_bound(self, payload_dir):
        server = serve(payload_dir, RangeHandler)
        RangeHandler.delay_s = 0.15
        try:
            pool = FetchPool(max_per_host=2)
            url = (
                f"http://127.0.0.1:{server.server_address[1]}/small"
            )
            threads = [
                threading.Thread(
                    target=lambda: pool.request(url, {}, 5.0)
                )
                for _ in range(6)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # the semaphore kept at most 2 requests in flight against
            # the origin even with 6 concurrent callers
            assert RangeHandler.max_active <= 2
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------


class RecordingStore:
    """In-memory store that records every call the planner issues."""

    def __init__(self, objects):
        self.objects = dict(objects)
        self.calls = []
        self.fail_ranges = False

    def get(self, key):
        self.calls.append(("get", key))
        return self.objects.get(key)

    def get_range(self, key, start, length=None):
        if self.fail_ranges:
            raise StoreError("ranges are broken today")
        self.calls.append(("range", key, start, length))
        body = self.objects.get(key)
        if body is None:
            return None
        return _project_range(body, start, length)

    def describe(self):
        return "recording://"


class TestPlanner:
    def test_adjacent_ranges_coalesce(self):
        store = RecordingStore({"k": bytes(range(256))})
        fetch.CONFIG.coalesce_gap_bytes = 16
        reqs = [
            RangeReq("k", 0, 10),
            RangeReq("k", 10, 10),      # adjacent
            RangeReq("k", 30, 10),      # 10-byte gap <= 16: merges
            RangeReq("k", 100, 10),     # 60-byte gap: new request
        ]
        out = fetch_many(store, reqs)
        assert out == [
            bytes(range(0, 10)), bytes(range(10, 20)),
            bytes(range(30, 40)), bytes(range(100, 110)),
        ]
        ranged = [c for c in store.calls if c[0] == "range"]
        assert len(ranged) == 2
        assert ranged[0] == ("range", "k", 0, 40)
        assert ranged[1] == ("range", "k", 100, 10)

    def test_gap_threshold_zero_splits(self):
        store = RecordingStore({"k": bytes(range(256))})
        fetch.CONFIG.coalesce_gap_bytes = 0
        out = fetch_many(
            store, [RangeReq("k", 0, 10), RangeReq("k", 20, 10)]
        )
        assert out == [bytes(range(0, 10)), bytes(range(20, 30))]
        assert len([c for c in store.calls if c[0] == "range"]) == 2

    def test_identical_requests_dedupe(self):
        store = RecordingStore({"k": b"x" * 64})
        out = fetch_many(store, [RangeReq("k")] * 5)
        assert out == [b"x" * 64] * 5
        assert store.calls == [("get", "k")]

    def test_overlapping_ranges_merge(self):
        store = RecordingStore({"k": bytes(range(200))})
        out = fetch_many(
            store, [RangeReq("k", 0, 100), RangeReq("k", 50, 100)]
        )
        assert out[0] == bytes(range(100))
        assert out[1] == bytes(range(50, 150))
        assert len(store.calls) == 1

    def test_absent_key_is_none_for_all_members(self):
        store = RecordingStore({})
        out = fetch_many(
            store, [RangeReq("gone", 0, 4), RangeReq("gone", 4, 4)]
        )
        assert out == [None, None]

    def test_stats_accounting(self):
        store = RecordingStore({"k": bytes(range(256))})
        stats = FetchStats()
        fetch_many(
            store,
            [RangeReq("k", 0, 8), RangeReq("k", 8, 8),
             RangeReq("k", 16, 8)],
            stats=stats,
        )
        snap = stats.snapshot()
        assert snap["planned"] == 3
        assert snap["issued"] == 1
        assert snap["coalesced_saved"] == 2
        assert snap["coalesced_ratio"] == pytest.approx(2 / 3, abs=1e-3)

    def test_sequential_escape_same_bytes(self):
        store = RecordingStore({"k": bytes(range(256))})
        reqs = [RangeReq("k", i * 16, 16) for i in range(8)]
        want = fetch_many(store, reqs)
        fetch.CONFIG.parallel = False
        store2 = RecordingStore({"k": bytes(range(256))})
        assert fetch_many(store2, reqs) == want

    def test_healthz_snapshot_shape(self):
        snap = io_snapshot()
        for key in ("planned", "issued", "coalesced_ratio", "pool",
                    "config", "fallbacks"):
            assert key in snap


# ---------------------------------------------------------------------------
# chaos lanes
# ---------------------------------------------------------------------------


@pytest.mark.resilience
class TestChaos:
    def test_range_fault_degrades_to_single_key(self, payload_dir):
        server = serve(payload_dir, RangeHandler)
        try:
            store = HTTPStore(
                f"http://127.0.0.1:{server.server_address[1]}"
            )
            INJECTOR.install("io.range-get", always(
                lambda: StoreError("injected range outage")
            ))
            data = bytes(range(256)) * 16
            stats = FetchStats()
            out = store.get_many(
                [RangeReq("obj", 0, 64), RangeReq("obj", 2048, 64)],
                stats=stats,
            )
            # bytes still correct — served by the whole-key fallback
            assert out == [data[:64], data[2048:2048 + 64]]
            assert fetch.IO_STATS.snapshot()["fallbacks"] >= 1
            whole_gets = [
                (p, r) for (p, r) in RangeHandler.requests if r is None
            ]
            assert len(whole_gets) >= 1
        finally:
            server.shutdown()

    def test_dead_store_opens_breaker(self):
        import socket

        # a port nothing listens on: every connect is refused
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        store = HTTPStore(f"http://127.0.0.1:{port}", timeout_s=1.0)
        with pytest.raises(StoreUnavailableError):
            for _ in range(30):
                try:
                    store.get_many(
                        [RangeReq("obj", 0, 16),
                         RangeReq("obj", 1024, 16)]
                    )
                except StoreUnavailableError:
                    raise
                except StoreError:
                    continue
        assert store.breaker.state == "open"

    def test_hung_fetch_bounded_by_timeout(self, payload_dir):
        server = serve(payload_dir, RangeHandler)
        RangeHandler.delay_s = 5.0
        try:
            store = HTTPStore(
                f"http://127.0.0.1:{server.server_address[1]}",
                timeout_s=0.3,
            )
            t0 = time.monotonic()
            with pytest.raises(StoreError):
                store.get_range("obj", 0, 16)
            # bounded by the per-call timeout (x retries), never the
            # 5 s the origin would have parked us for
            assert time.monotonic() - t0 < 4.0
        finally:
            RangeHandler.delay_s = 0.0
            server.shutdown()

    def test_expired_deadline_stops_fetch(self):
        store = RecordingStore({"k": bytes(range(64))})
        expired = Deadline.after(-1.0)
        with deadline_scope(expired):
            with pytest.raises(DeadlineExceeded):
                fetch_many(
                    store,
                    [RangeReq("k", 0, 8), RangeReq("k", 32, 8)],
                )

    def test_pool_fault_point_fires(self, payload_dir):
        server = serve(payload_dir, RangeHandler)
        try:
            store = HTTPStore(
                f"http://127.0.0.1:{server.server_address[1]}"
            )
            INJECTOR.install("io.fetch-pool", always(
                lambda: StoreError("pool chaos")
            ))
            with pytest.raises(StoreError):
                store.get("small")
            assert INJECTOR.calls("io.fetch-pool") >= 1
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# negative-chunk caching (satellite): absent chunks stop costing one
# store GET per batch — TTL-bounded, invalidation-purged
# ---------------------------------------------------------------------------


class CountingFileStore(FileStore):
    def __init__(self, root):
        super().__init__(root)
        self.gets = []

    def get(self, key):
        self.gets.append(key)
        return super().get(key)


def _sparse_ngff(tmp_path):
    """A 128x128 plane with only the top-left 32x32 chunk present —
    15 of 16 chunk keys are absent (fill_value)."""
    img = np.zeros((1, 1, 1, 128, 128), np.uint16)
    img[0, 0, 0, :32, :32] = 7
    root = str(tmp_path / "sparse.zarr")
    write_ngff(root, img, chunks=(32, 32), levels=1)
    import glob
    import os as _os

    for f in glob.glob(_os.path.join(root, "0", "0.0.0.*")):
        if _os.path.basename(f) != "0.0.0.0.0":
            _os.remove(f)
    return root, img


class TestNegativeChunkCache:
    def test_absent_chunks_not_refetched_across_batches(self, tmp_path):
        root, img = _sparse_ngff(tmp_path)
        buf = ZarrPixelBuffer(root)
        store = CountingFileStore(root)
        buf.store = store
        for lv in buf.levels:
            lv.store = store
        coords = [(0, 0, 0, 0, 0, 128, 128)]
        first = buf.read_tiles(coords, level=0)
        n_first = len(store.gets)
        assert n_first == 16  # every chunk key asked once, cold
        second = buf.read_tiles(coords, level=0)
        # second batch: zero store traffic — data chunks AND absent
        # chunks (negatives) answer from the shared BlockCache
        assert len(store.gets) == n_first
        assert np.array_equal(first[0], second[0])
        assert np.array_equal(first[0], img[0, 0, 0])

    def test_negative_ttl_expires(self, tmp_path):
        root, _ = _sparse_ngff(tmp_path)
        set_negative_ttl(0.05)
        buf = ZarrPixelBuffer(root)
        store = CountingFileStore(root)
        buf.store = store
        for lv in buf.levels:
            lv.store = store
        coords = [(0, 0, 0, 0, 0, 128, 128)]
        buf.read_tiles(coords, level=0)
        n_first = len(store.gets)
        time.sleep(0.06)
        buf.read_tiles(coords, level=0)
        # the 15 negatives expired and re-asked; the decoded data
        # chunk is NOT TTL-bounded and stays cached
        assert len(store.gets) == n_first + 15

    def test_invalidation_purges_negatives(self, tmp_path):
        from omero_ms_pixel_buffer_tpu.io.pixels_service import (
            ImageRegistry,
            PixelsService,
        )
        from omero_ms_pixel_buffer_tpu.models.tile_pipeline import (
            TilePipeline,
        )
        from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx

        root, img = _sparse_ngff(tmp_path)
        registry = ImageRegistry()
        registry.add(1, root)
        service = PixelsService(registry)
        pipe = TilePipeline(service, use_device=False)

        def ctx():
            return TileCtx(
                image_id=1, z=0, c=0, t=0,
                region=RegionDef(0, 0, 128, 128), format=None,
            )

        first = pipe.handle(ctx())
        assert first is not None
        ns = service.get_pixel_buffer(1).cache_ns
        assert len(service.block_cache) >= 16
        pipe.invalidate_image(1)
        # the namespace's entries (data + negatives) are gone
        assert all(
            not (isinstance(k, tuple) and k and k[0] == ns)
            for k in service.block_cache._entries
        )
        assert pipe.handle(ctx()) == first

    def test_negative_entries_charge_budget(self):
        cache = BlockCache(1 << 20)
        for i in range(100):
            cache[(1, 0, (i,))] = None
        assert cache.nbytes == 100 * 64  # nominal charge, never 0


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


class TestIoConfig:
    BASE = {"session-store": {"type": "memory"}}

    def test_defaults(self):
        cfg = Config.from_dict(dict(self.BASE))
        assert cfg.io.parallel_fetch is True
        assert cfg.io.fetch_workers == 16
        assert cfg.io.max_conns_per_host == 8
        assert cfg.io.coalesce_gap_kb == 64.0
        assert cfg.io.decode_workers == 4
        assert cfg.io.negative_ttl_s == 300.0

    def test_unknown_key_rejected(self):
        raw = dict(self.BASE)
        raw["io"] = {"coalesce-gap": 1}
        with pytest.raises(ConfigError, match="io"):
            Config.from_dict(raw)

    @pytest.mark.parametrize("key,value", [
        ("fetch-workers", 0),
        ("fetch-workers", "lots"),
        ("max-conns-per-host", -1),
        ("coalesce-gap-kb", "wide"),
        ("decode-workers", -2),
        ("negative-ttl-s", -5),
    ])
    def test_bad_values_rejected(self, key, value):
        raw = dict(self.BASE)
        raw["io"] = {key: value}
        with pytest.raises(ConfigError):
            Config.from_dict(raw)

    def test_configure_applies(self):
        from omero_ms_pixel_buffer_tpu.io.pixel_buffer import (
            negative_ttl_s,
        )

        raw = dict(self.BASE)
        raw["io"] = {
            "parallel-fetch": False,
            "coalesce-gap-kb": 8,
            "negative-ttl-s": 12.5,
        }
        cfg = Config.from_dict(raw)
        fetch.configure(cfg.io)
        try:
            assert fetch.parallel_enabled() is False
            assert fetch.CONFIG.coalesce_gap_bytes == 8 << 10
            assert negative_ttl_s() == 12.5
        finally:
            fetch.configure(Config.from_dict(dict(self.BASE)).io)
