"""Viewer-protocol adapter conformance suite (http/protocols/).

Covers: the DZI descriptor pinned BYTE-EXACT, the DZI level ladder
math, IIIF info.json schema (3.0 + 2.1), the IIIF region/size/
rotation/quality grammar (precise 400s vs 501s), the Iris metadata/
grid math, and the equivalence matrix over real HTTP: adapter-served
tiles byte-identical to the equivalent native ``/render`` request
with the SAME ETag and SHARED cache entries (second request through
any dialect is an ``X-Cache: hit`` without a second render). Chaos
lanes (``-m resilience``) prove adapter requests shed/degrade/504
exactly like native ones — same door gate, same deadline, same
engine-fallback byte identity.
"""

import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.errors import BadRequestError
from omero_ms_pixel_buffer_tpu.http.protocols import dzi as pdzi
from omero_ms_pixel_buffer_tpu.http.protocols import iiif as piiif
from omero_ms_pixel_buffer_tpu.http.protocols import iris as piris
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
    INJECTOR,
    always,
)
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

rng = np.random.default_rng(29)
AUTH = {"Cookie": "sessionid=ck"}

# 128x96 with a 2-level pyramid: DZI maxLevel = 7, pyramid levels
# 0 (128x96) and 1 (64x48)
IMG = rng.integers(0, 60000, (1, 2, 2, 96, 128), dtype=np.uint16)


@pytest.fixture(autouse=True)
def _clean_chaos():
    INJECTOR.clear()
    yield
    INJECTOR.clear()
    BOARD.reset()


async def _make_client(tmp_path, overrides=None):
    write_ome_tiff(
        str(tmp_path / "img.ome.tiff"), IMG, tile_size=(64, 64),
        pyramid_levels=2,
    )
    registry = ImageRegistry()
    registry.add(1, str(tmp_path / "img.ome.tiff"))
    store = MemorySessionStore({"ck": "key-1"})
    raw = {
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 1.0}},
        "protocols": {
            "dzi": {"tile-size": 64},
            "iiif": {"tile-size": 64},
            "iris": {"tile-size": 64},
        },
    }
    for key, value in (overrides or {}).items():
        raw[key] = value
    config = Config.from_dict(raw)
    app_obj = PixelBufferApp(
        config, pixels_service=PixelsService(registry),
        session_store=store,
    )
    client = TestClient(TestServer(app_obj.make_app()))
    await client.start_server()
    return client, app_obj


# ---------------------------------------------------------------------------
# pure grammar / math units
# ---------------------------------------------------------------------------


class TestDziMath:
    def test_max_level(self):
        assert pdzi.max_level(1, 1) == 0
        assert pdzi.max_level(128, 96) == 7
        assert pdzi.max_level(129, 96) == 8
        assert pdzi.max_level(65536, 40000) == 16

    def test_descriptor_golden_bytes(self):
        """The descriptor is pinned BYTE-EXACT — viewers hash and
        cache it, so encoding drift is a contract break."""
        assert pdzi.descriptor_xml(128, 96, 64) == (
            b'<?xml version="1.0" encoding="UTF-8"?>\n'
            b'<Image xmlns="http://schemas.microsoft.com/deepzoom/2008"'
            b' Format="png" Overlap="0" TileSize="64">'
            b'<Size Height="96" Width="128"/></Image>'
        )

    def test_resolve_tile_ladder(self):
        sizes = [(128, 96), (64, 48)]
        # level 7 = resolution 0; full grid
        assert pdzi.resolve_tile(sizes, 7, 0, 0, 64) == (
            0, 0, 0, 64, 64
        )
        # right/bottom edge tiles clip
        assert pdzi.resolve_tile(sizes, 7, 1, 1, 64) == (
            0, 64, 64, 64, 32
        )
        # level 6 = resolution 1
        assert pdzi.resolve_tile(sizes, 6, 0, 0, 64) == (
            1, 0, 0, 64, 48
        )
        # coarser than the stored pyramid -> None (404)
        assert pdzi.resolve_tile(sizes, 5, 0, 0, 64) is None
        # finer than the image -> None
        assert pdzi.resolve_tile(sizes, 8, 0, 0, 64) is None
        # off the grid -> None
        assert pdzi.resolve_tile(sizes, 7, 9, 0, 64) is None

    def test_non_dyadic_pyramid_is_404_not_wrong_scale(self):
        """A factor-4 NGFF pyramid does not back DZI's factor-2
        ladder: the intermediate rung must 404, never serve 1/4-scale
        pixels laid out at 1/2 scale."""
        sizes = [(4096, 4096), (1024, 1024), (256, 256)]
        # maxLevel 12; level 12 = res 0 (4096, dyadic) serves
        assert pdzi.resolve_tile(sizes, 12, 0, 0, 256) is not None
        # level 11 expects 2048 but the stored level 1 is 1024
        assert pdzi.resolve_tile(sizes, 11, 0, 0, 256) is None
        assert pdzi.resolve_tile(sizes, 10, 0, 0, 256) is None
        # odd extents: floor AND ceil halvings both accepted
        odd = [(97, 97), (48, 48)]
        assert pdzi.resolve_tile(odd, 6, 0, 0, 64) is not None
        odd_ceil = [(97, 97), (49, 49)]
        assert pdzi.resolve_tile(odd_ceil, 6, 0, 0, 64) is not None


class TestIiifGrammar:
    SIZES = [(128, 96), (64, 48)]

    def _candidates(self, x, y, w, h):
        return [
            (r, piiif.map_region_to_level(x, y, w, h, self.SIZES, r))
            for r in range(len(self.SIZES))
        ]

    def test_region_full_and_rect(self):
        assert piiif.parse_region("full", 128, 96) == (0, 0, 128, 96)
        assert piiif.parse_region("0,0,64,64", 128, 96) == (0, 0, 64, 64)
        # clips to the extent
        assert piiif.parse_region("100,80,64,64", 128, 96) == (
            100, 80, 28, 16
        )

    @pytest.mark.parametrize("region", [
        "0,0,64", "a,0,64,64", "-1,0,64,64", "0,0,0,64", "200,0,1,1",
    ])
    def test_region_400(self, region):
        with pytest.raises(BadRequestError):
            piiif.parse_region(region, 128, 96)

    @pytest.mark.parametrize("region", ["square", "pct:0,0,50,50"])
    def test_region_501(self, region):
        with pytest.raises(piiif.IiifNotSupported):
            piiif.parse_region(region, 128, 96)

    def test_size_exact_levels(self):
        cands = self._candidates(0, 0, 128, 96)
        assert piiif.parse_size("max", cands) == 0
        assert piiif.parse_size("full", cands) == 0
        assert piiif.parse_size("128,96", cands) == 0
        assert piiif.parse_size("64,48", cands) == 1
        assert piiif.parse_size("64,", cands) == 1
        assert piiif.parse_size(",48", cands) == 1
        assert piiif.parse_size("!100,60", cands) == 1  # best fit

    def test_size_501(self):
        cands = self._candidates(0, 0, 128, 96)
        for s in ("100,75", "^200,150", "pct:50", "!32,24"):
            with pytest.raises(piiif.IiifNotSupported):
                piiif.parse_size(s, cands)

    @pytest.mark.parametrize("size", ["", ",", "a,b", "0,0", "-1,"])
    def test_size_400(self, size):
        with pytest.raises(BadRequestError):
            piiif.parse_size(size, self._candidates(0, 0, 128, 96))

    def test_rotation_and_quality(self):
        piiif.parse_rotation("0")
        for r in ("90", "45.5", "!0"):
            with pytest.raises(piiif.IiifNotSupported):
                piiif.parse_rotation(r)
        assert piiif.parse_quality_format("default.png") == ({}, "png")
        assert piiif.parse_quality_format("gray.jpg") == (
            {"m": "g"}, "jpeg"
        )
        with pytest.raises(piiif.IiifNotSupported):
            piiif.parse_quality_format("bitonal.png")
        with pytest.raises(piiif.IiifNotSupported):
            piiif.parse_quality_format("default.tif")
        with pytest.raises(BadRequestError):
            piiif.parse_quality_format("defaultpng")
        with pytest.raises(BadRequestError):
            piiif.parse_quality_format("shiny.png")

    def test_info_documents(self):
        v3 = piiif.info_document("http://s/iiif/1", self.SIZES, 64, 3)
        # required Image API 3.0 fields
        for key in ("@context", "id", "type", "protocol", "profile",
                    "width", "height"):
            assert key in v3, key
        assert v3["type"] == "ImageService3"
        assert v3["width"] == 128 and v3["height"] == 96
        assert v3["sizes"][0] == {"width": 64, "height": 48}
        assert v3["tiles"][0]["scaleFactors"] == [1, 2]
        v2 = piiif.info_document("http://s/iiif/1", self.SIZES, 64, 2)
        assert v2["@context"].endswith("/2/context.json")
        assert "@id" in v2 and "id" not in v2


class TestIrisMath:
    def test_layer_grid(self):
        sizes = [(128, 96), (64, 48)]
        # layer 0 = coarsest = resolution 1
        assert piris.layer_grid(sizes, 0, 64) == (1, 1, 1, 64, 48)
        assert piris.layer_grid(sizes, 1, 64) == (0, 2, 2, 128, 96)
        assert piris.layer_grid(sizes, 2, 64) is None

    def test_metadata_document(self):
        doc = piris.metadata_document([(128, 96), (64, 48)], 64)
        assert doc["extent"]["width"] == 128
        layers = doc["extent"]["layers"]
        assert layers[0] == {"x_tiles": 1, "y_tiles": 1, "scale": 2}
        assert layers[1] == {"x_tiles": 2, "y_tiles": 2, "scale": 1}


# ---------------------------------------------------------------------------
# HTTP: descriptors, equivalence matrix, grammar statuses, gating
# ---------------------------------------------------------------------------


class TestAdapterHttp:
    async def test_descriptors(self, tmp_path):
        client, _ = await _make_client(tmp_path)
        try:
            r = await client.get("/dzi/1.dzi", headers=AUTH)
            assert r.status == 200
            assert r.headers["Content-Type"].startswith(
                "application/xml"
            )
            assert await r.read() == pdzi.descriptor_xml(128, 96, 64)

            r = await client.get("/iiif/1/info.json", headers=AUTH)
            info = json.loads(await r.read())
            assert info["type"] == "ImageService3"
            assert info["width"] == 128
            r = await client.get(
                "/iiif/1/info.json?version=2", headers=AUTH
            )
            assert "@id" in json.loads(await r.read())

            r = await client.get("/iris/1/metadata", headers=AUTH)
            meta = json.loads(await r.read())
            assert meta["extent"]["tile_size"] == 64

            # unknown image -> 404; no session -> 403
            for url in ("/dzi/9.dzi", "/iiif/9/info.json",
                        "/iris/9/metadata"):
                assert (await client.get(url, headers=AUTH)).status == 404
            assert (await client.get("/dzi/1.dzi")).status == 403
        finally:
            await client.close()

    async def test_equivalence_matrix(self, tmp_path):
        """The acceptance pin: adapter responses for equivalent
        regions are byte-identical to native /render output, carry
        the same ETag, and SHARE its cache entries — the second
        request through any dialect is a hit without a second
        render."""
        client, _ = await _make_client(tmp_path)
        try:
            native_url = (
                "/render/1/0/0/0?x=64&y=0&w=64&h=64&resolution=0"
                "&format=png"
            )
            n = await client.get(native_url, headers=AUTH)
            assert n.status == 200 and n.headers["X-Cache"] == "miss"
            native = await n.read()
            etag = n.headers["ETag"]

            # DZI level 7 == resolution 0; tile (1, 0)
            d = await client.get("/dzi/1_files/7/1_0.png", headers=AUTH)
            assert d.status == 200
            assert d.headers["X-Cache"] == "hit"  # SHARED entry
            assert d.headers["ETag"] == etag
            assert await d.read() == native

            # IIIF full-res region spelling of the same tile
            i = await client.get(
                "/iiif/1/64,0,64,64/64,64/0/default.png", headers=AUTH
            )
            assert i.status == 200
            assert i.headers["X-Cache"] == "hit"
            assert i.headers["ETag"] == etag
            assert await i.read() == native

            # Iris layer 1 (= resolution 0), flat tile 1 = (col 1, row 0)
            ir = await client.get(
                "/iris/1/layers/1/tiles/1", headers=AUTH
            )
            assert ir.status == 200
            assert ir.headers["X-Cache"] == "hit"
            assert ir.headers["ETag"] == etag
            assert await ir.read() == native

            # 304 revalidation straight through an adapter
            d304 = await client.get(
                "/dzi/1_files/7/1_0.png",
                headers={**AUTH, "If-None-Match": etag},
            )
            assert d304.status == 304
        finally:
            await client.close()

    async def test_adapter_first_warms_native(self, tmp_path):
        """The reverse direction: a cold DZI request warms the entry
        the native endpoint then hits."""
        client, _ = await _make_client(tmp_path)
        try:
            d = await client.get("/dzi/1_files/6/0_0.png", headers=AUTH)
            assert d.status == 200 and d.headers["X-Cache"] == "miss"
            n = await client.get(
                "/render/1/0/0/0?x=0&y=0&w=64&h=48&resolution=1"
                "&format=png",
                headers=AUTH,
            )
            assert n.headers["X-Cache"] == "hit"
            assert await n.read() == await d.read()
        finally:
            await client.close()

    async def test_render_params_ride_along(self, tmp_path):
        """A DZI viewer appending render settings (channels, colors,
        gamma) drives the full render model — and still shares keys
        with the native spelling of the same thing."""
        client, _ = await _make_client(tmp_path)
        try:
            q = "c=1|0:60000$FF0000,2|0:60000$00FF00"
            d = await client.get(
                f"/dzi/1_files/7/0_0.png?{q}", headers=AUTH
            )
            assert d.status == 200
            n = await client.get(
                f"/render/1/0/0/0?x=0&y=0&w=64&h=64&resolution=0"
                f"&format=png&{q}",
                headers=AUTH,
            )
            assert n.headers["X-Cache"] == "hit"
            assert await n.read() == await d.read()
        finally:
            await client.close()

    async def test_grammar_statuses(self, tmp_path):
        client, _ = await _make_client(tmp_path)
        try:
            # DZI: bad format 400, unbacked level 404, off-grid 404
            assert (await client.get(
                "/dzi/1_files/7/0_0.gif", headers=AUTH
            )).status == 400
            assert (await client.get(
                "/dzi/1_files/4/0_0.png", headers=AUTH
            )).status == 404
            assert (await client.get(
                "/dzi/1_files/7/5_0.png", headers=AUTH
            )).status == 404
            # IIIF 501s: pct region, arbitrary scale, rotation,
            # bitonal, exotic format
            for url in (
                "/iiif/1/pct:0,0,50,50/max/0/default.png",
                "/iiif/1/full/100,75/0/default.png",
                "/iiif/1/full/max/90/default.png",
                "/iiif/1/full/max/0/bitonal.png",
                "/iiif/1/full/max/0/default.webp",
            ):
                assert (await client.get(url, headers=AUTH)).status == 501, url
            # IIIF 400s: malformed region/size/quality
            for url in (
                "/iiif/1/0,0,64/max/0/default.png",
                "/iiif/1/full/a,b/0/default.png",
                "/iiif/1/full/max/0/shiny.png",
                "/iiif/1/500,500,10,10/max/0/default.png",
            ):
                assert (await client.get(url, headers=AUTH)).status == 400, url
            # Iris: off-ladder layer / off-grid tile
            assert (await client.get(
                "/iris/1/layers/9/tiles/0", headers=AUTH
            )).status == 404
            assert (await client.get(
                "/iris/1/layers/1/tiles/99", headers=AUTH
            )).status == 404
        finally:
            await client.close()

    async def test_adapter_gating(self, tmp_path):
        """Per-adapter enable flags: IIIF off leaves DZI serving."""
        client, _ = await _make_client(
            tmp_path, {"protocols": {
                "dzi": {"tile-size": 64},
                "iiif": {"enabled": False},
                "iris": {"tile-size": 64},
            }},
        )
        try:
            assert (await client.get(
                "/iiif/1/info.json", headers=AUTH
            )).status == 405  # not mounted (OPTIONS catch-all)
            assert (await client.get(
                "/dzi/1.dzi", headers=AUTH
            )).status == 200
            h = json.loads(
                await (await client.get("/healthz")).read()
            )
            assert h["protocols"] == {
                "dzi": True, "iiif": False, "iris": True
            }
        finally:
            await client.close()

    def test_protocols_config_validation(self):
        base = {"session-store": {"type": "memory"}}
        with pytest.raises(ConfigError):
            Config.from_dict({**base, "protocols": {"dzzi": {}}})
        with pytest.raises(ConfigError):
            Config.from_dict(
                {**base, "protocols": {"dzi": {"tile": 64}}}
            )
        with pytest.raises(ConfigError):
            Config.from_dict(
                {**base, "protocols": {"dzi": {"tile-size": 4}}}
            )
        with pytest.raises(ConfigError):
            Config.from_dict({**base, "analysis": {"bins": 1}})
        with pytest.raises(ConfigError):
            Config.from_dict({**base, "analysis": {"max-bins": 1}})
        cfg = Config.from_dict({
            **base,
            "protocols": {"iiif": {"enabled": False}},
            "analysis": {"max-bins": 1024},
        })
        assert not cfg.protocols.iiif.enabled
        assert cfg.protocols.dzi.enabled
        assert cfg.analysis.max_bins == 1024


# ---------------------------------------------------------------------------
# chaos lanes: adapters degrade exactly like native requests
# ---------------------------------------------------------------------------


class TestAdapterChaos:
    @pytest.mark.resilience
    async def test_door_shed_parity(self, tmp_path):
        """When the SLO door gate sheds, the DZI/IIIF/Iris surfaces
        503 with Retry-After exactly like native /render — adapters
        are serving lanes, not side doors around admission."""
        client, app_obj = await _make_client(tmp_path)
        try:
            app_obj.scheduler.would_overflow_shed = lambda p: True
            native = await client.get(
                "/render/1/0/0/0?w=64&h=64", headers=AUTH
            )
            assert native.status == 503
            for url in (
                "/dzi/1_files/7/0_0.png",
                "/iiif/1/full/max/0/default.png",
                "/iris/1/layers/1/tiles/0",
            ):
                r = await client.get(url, headers=AUTH)
                assert r.status == 503, url
                assert "Retry-After" in r.headers
        finally:
            await client.close()

    @pytest.mark.resilience
    async def test_engine_chaos_adapter_bytes_identical(self, tmp_path):
        """render.engine failing under a DZI request host-falls-back
        to byte-identical tiles — the adapter inherits the engine
        contract wholesale."""
        client, app_obj = await _make_client(
            tmp_path, {"cache": {"enabled": False}}
        )
        try:
            clean = await client.get(
                "/dzi/1_files/7/0_0.png", headers=AUTH
            )
            assert clean.status == 200
            clean_body = await clean.read()
            INJECTOR.install("render.engine", always(RuntimeError))
            broken = await client.get(
                "/dzi/1_files/7/0_0.png", headers=AUTH
            )
            assert broken.status == 200
            assert await broken.read() == clean_body
        finally:
            await client.close()

    @pytest.mark.resilience
    async def test_dependency_down_is_503_not_404(self, tmp_path):
        """An open-breaker store under an adapter descriptor/tile
        lookup answers 503 + Retry-After, never 404 — a 404 would
        read as 'image gone' to viewers and HTTP caches for the whole
        open duration (the tile_pipeline contract)."""
        from omero_ms_pixel_buffer_tpu.io.stores import (
            StoreUnavailableError,
        )

        client, app_obj = await _make_client(tmp_path)
        try:
            def dead(*a, **k):
                raise StoreUnavailableError(
                    "breaker open", retry_after_s=2.0
                )

            app_obj.pixels_service.get_pixel_buffer = dead
            for url in ("/dzi/1.dzi", "/iiif/1/info.json",
                        "/iris/1/metadata",
                        "/dzi/1_files/7/0_0.png"):
                r = await client.get(url, headers=AUTH)
                assert r.status == 503, (url, r.status)
                assert "Retry-After" in r.headers
        finally:
            await client.close()

    @pytest.mark.resilience
    async def test_adapter_deadline_504(self, tmp_path):
        client, _ = await _make_client(
            tmp_path, {"resilience": {"request-budget-ms": 1}}
        )
        try:
            for url in (
                "/dzi/1_files/7/0_0.png",
                "/iris/1/layers/1/tiles/0",
            ):
                r = await client.get(url, headers=AUTH)
                assert r.status == 504, (url, r.status)
        finally:
            await client.close()
