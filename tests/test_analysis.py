"""Analysis plane suite (render/analysis + render/masks + the render
model extensions this PR ships).

Covers: HistogramSpec parsing (incl. 400s over HTTP), the histogram
reduction pinned integer-identical across the numpy mirror, the
jitted device program, and the 8-way CPU mesh (vs an independent
np.histogram reference), ROI mask grammar + rasterization + the
per-image raster cache, masked-render byte identity (fused device
chain == host mirror), float32/int32 windowing through the u16
quantization, polynomial/logarithmic quantization families,
t-projection, the projection stack-byte 413 bound, the HBM
plane-cache projection-read regression, and — under ``-m
resilience`` — the ``analysis.engine`` chaos lane plus deadline/
admission flow-through for histogram requests.
"""

import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.errors import (
    BadRequestError,
    RequestTooLargeError,
)
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.models.device_cache import DevicePlaneCache
from omero_ms_pixel_buffer_tpu.models.tile_pipeline import TilePipeline
from omero_ms_pixel_buffer_tpu.render import analysis as ran
from omero_ms_pixel_buffer_tpu.render import engine as rengine
from omero_ms_pixel_buffer_tpu.render import masks as rmasks
from omero_ms_pixel_buffer_tpu.render.analysis import HistogramSpec
from omero_ms_pixel_buffer_tpu.render.model import RenderSpec
from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
from omero_ms_pixel_buffer_tpu.resilience.faultinject import (
    INJECTOR,
    always,
)
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx
from omero_ms_pixel_buffer_tpu.utils.config import Config

rng = np.random.default_rng(23)
AUTH = {"Cookie": "sessionid=ck"}

# (T, C, Z, Y, X): 2 timepoints, 3 channels, 4 z — enough for both
# projection axes
IMG = rng.integers(0, 4096, (2, 3, 4, 96, 128), dtype=np.uint16)
FIMG = rng.normal(0.0, 25.0, (1, 1, 3, 64, 64)).astype(np.float32)


@pytest.fixture(autouse=True)
def _clean_chaos():
    INJECTOR.clear()
    yield
    INJECTOR.clear()
    BOARD.reset()


def _registry(tmp_path):
    write_ome_tiff(
        str(tmp_path / "img.ome.tiff"), IMG, tile_size=(64, 64)
    )
    write_ome_tiff(
        str(tmp_path / "f.ome.tiff"), FIMG, tile_size=(64, 64)
    )
    registry = ImageRegistry()
    registry.add(1, str(tmp_path / "img.ome.tiff"))
    registry.add(2, str(tmp_path / "f.ome.tiff"))
    return registry


def _ctx(
    analysis=None, render=None, img=1, z=0, c=0, t=0,
    x=0, y=0, w=64, h=48, session="k",
):
    fmt = "json" if analysis is not None else (
        render.format if render is not None else "png"
    )
    return TileCtx(
        image_id=img, z=z, c=c, t=t,
        region=RegionDef(x, y, w, h), format=fmt,
        omero_session_key=session, render=render, analysis=analysis,
    )


# ---------------------------------------------------------------------------
# HistogramSpec parsing
# ---------------------------------------------------------------------------


class TestHistogramSpec:
    def test_defaults(self):
        spec = HistogramSpec.from_params({}, default_channel=2)
        assert spec.bins == 256 and not spec.use_pixel_range
        assert [c.index for c in spec.channels] == [2]

    def test_channel_dialect_with_windows(self):
        spec = HistogramSpec.from_params(
            {"c": "1|100:600,-2,3", "bins": "64"}
        )
        assert [c.index for c in spec.channels] == [0, 2]
        assert spec.channels[0].window == (100.0, 600.0)
        assert spec.bins == 64

    def test_use_pixels_type_range(self):
        spec = HistogramSpec.from_params({"usePixelsTypeRange": "true"})
        assert spec.use_pixel_range

    @pytest.mark.parametrize("bins", ["1", "0", "-4", "999999", "x"])
    def test_bad_bins_400(self, bins):
        with pytest.raises(BadRequestError):
            HistogramSpec.from_params({"bins": bins})

    def test_max_bins_config_cap(self):
        with pytest.raises(BadRequestError):
            HistogramSpec.from_params({"bins": "512"}, max_bins=256)

    def test_duplicate_channel_400(self):
        with pytest.raises(BadRequestError):
            HistogramSpec.from_params({"c": "1,1"})

    def test_signature_and_json_round_trip(self):
        spec = HistogramSpec.from_params(
            {"c": "2|0:100", "bins": "32", "usePixelsTypeRange": "1"}
        )
        again = HistogramSpec.from_json(spec.to_json())
        assert again.signature() == spec.signature()
        other = HistogramSpec.from_params({"c": "2|0:100", "bins": "33"})
        assert other.signature() != spec.signature()

    def test_signature_joins_cache_key(self):
        a = _ctx(analysis=HistogramSpec.from_params({"bins": "16"}))
        b = _ctx(analysis=HistogramSpec.from_params({"bins": "32"}))
        raw = _ctx(render=None)
        raw.format = "json"
        assert a.cache_key("q") != b.cache_key("q")
        assert a.cache_key("q") != raw.cache_key("q")
        assert a.lane_key() != b.lane_key()


# ---------------------------------------------------------------------------
# the reduction: host mirror == device == mesh == numpy reference
# ---------------------------------------------------------------------------


class TestHistogramReduction:
    @pytest.mark.parametrize("dtype", [np.uint8, np.uint16, np.int16])
    def test_host_device_reference_identical(self, dtype):
        dtype = np.dtype(dtype)
        info = np.iinfo(dtype)
        planes = rng.integers(
            info.min, int(info.max) + 1, (4, 40, 56), dtype=dtype
        )
        bins = 32
        window = (float(info.min), float(info.max))
        tab = ran.build_bin_table(dtype, window, bins)
        idx = rengine.unsigned_view(planes)
        tabs = np.stack([tab] * 4)
        host = ran.histogram_host(idx, tabs, bins)
        dev = ran.histogram_batch(idx, tabs, bins)
        np.testing.assert_array_equal(host, dev)
        # independent reference: np.histogram over the clamped range
        for i in range(4):
            ref, _ = np.histogram(
                planes[i].astype(np.float64),
                bins=bins,
                range=(window[0], window[1] + 1),
            )
            np.testing.assert_array_equal(host[i], ref)

    def test_mesh_identical(self):
        from omero_ms_pixel_buffer_tpu.parallel.mesh import make_mesh

        planes = rng.integers(0, 65536, (5, 32, 32), dtype=np.uint16)
        tab = ran.build_bin_table(np.uint16, (0.0, 65535.0), 64)
        tabs = np.stack([tab] * 5)
        mesh = make_mesh(("data",))
        sharded = ran.sharded_histogram_batch(mesh, planes, tabs, 64)
        single = ran.histogram_batch(planes, tabs, 64)
        np.testing.assert_array_equal(sharded, single)

    def test_window_clamps_into_edge_bins(self):
        plane = np.array([[0, 10, 50, 90, 255]], dtype=np.uint8)
        tab = ran.build_bin_table(np.uint8, (10.0, 90.0), 4)
        counts = ran.histogram_host(plane[None], tab[None], 4)[0]
        # 0 clamps into bin 0; 255 clamps into bin 3
        assert counts.sum() == 5
        assert counts[0] >= 2 and counts[3] >= 2

    def test_stats_from_counts(self):
        counts = np.array([0, 2, 2, 0], dtype=np.int64)
        st = ran.stats_from_counts(counts, (0.0, 8.0), 4)
        assert st["count"] == 4
        assert st["min"] == 2.0 and st["max"] == 6.0
        assert st["p50"] == 2.0
        empty = ran.stats_from_counts(np.zeros(4), (0.0, 8.0), 4)
        assert empty["count"] == 0 and empty["min"] is None


# ---------------------------------------------------------------------------
# ROI masks: grammar, rasterization, cache
# ---------------------------------------------------------------------------


class TestMasks:
    def test_rect_raster_pixel_center_rule(self):
        (shape,) = rmasks.parse_roi(
            '[{"type":"rect","x":1,"y":2,"w":3,"h":4}]'
        )
        m = rmasks.rasterize((shape,), 0, 0, 8, 8)
        assert m.sum() == 12  # centers in [1,4]x[2,6]: 3 cols x 4 rows
        assert m[2, 1] == 1 and m[0, 0] == 0

    def test_ellipse_and_polygon(self):
        shapes = rmasks.parse_roi(
            '[{"type":"ellipse","cx":4,"cy":4,"rx":2,"ry":2},'
            '{"type":"polygon","points":[[10,0],[16,0],[13,6]]}]'
        )
        m = rmasks.rasterize(shapes, 0, 0, 20, 8)
        assert m[4, 4] == 1  # ellipse center
        assert m[1, 13] == 1  # inside the triangle
        assert m[7, 19] == 0

    def test_polyline_stroke(self):
        shapes = rmasks.parse_roi(
            '[{"type":"polyline","points":[[0,4],[10,4]],"width":2}]'
        )
        m = rmasks.rasterize(shapes, 0, 0, 10, 10)
        assert m[4, 5] == 1 and m[0, 5] == 0

    def test_region_offset_consistency(self):
        """A shape rasterizes identically no matter how the tile grid
        cuts it — the pan-consistency contract."""
        shapes = rmasks.parse_roi(
            '[{"type":"ellipse","cx":30,"cy":30,"rx":18,"ry":12}]'
        )
        whole = rmasks.rasterize(shapes, 0, 0, 64, 64)
        left = rmasks.rasterize(shapes, 0, 0, 32, 64)
        right = rmasks.rasterize(shapes, 32, 0, 32, 64)
        np.testing.assert_array_equal(
            whole, np.concatenate([left, right], axis=1)
        )

    @pytest.mark.parametrize("raw", [
        "not json",
        "[]",
        '[{"type":"blob"}]',
        '[{"type":"rect","x":0,"y":0,"w":0,"h":5}]',
        '[{"type":"polygon","points":[[0,0],[1,1]]}]',
        '[{"type":"polygon","points":[[0,0],[1,1],"x"]}]',
        '[{"type":"ellipse","cx":0,"cy":0,"rx":-1,"ry":1}]',
        '[{"type":"rect","x":0,"y":0,"w":1,"h":1,"zz":1}]',
        '[{"type":"polyline","points":[[0,0],[1,1]],"width":0}]',
    ])
    def test_grammar_errors_400(self, raw):
        with pytest.raises(BadRequestError):
            rmasks.parse_roi(raw)

    def test_too_many_shapes_400(self):
        raw = json.dumps(
            [{"type": "rect", "x": i, "y": 0, "w": 1, "h": 1}
             for i in range(65)]
        )
        with pytest.raises(BadRequestError):
            rmasks.parse_roi(raw)

    def test_cache_hit_and_invalidate(self):
        cache = rmasks.MaskRasterCache()
        shapes = rmasks.parse_roi(
            '[{"type":"rect","x":0,"y":0,"w":4,"h":4}]'
        )
        a = cache.get(7, shapes, (0, 0, 8, 8))
        b = cache.get(7, shapes, (0, 0, 8, 8))
        assert a is b and cache.hits == 1
        cache.invalidate_image(7)
        c = cache.get(7, shapes, (0, 0, 8, 8))
        assert c is not a

    def test_roi_joins_render_signature(self):
        plain = RenderSpec.from_params({"c": "1"})
        masked = RenderSpec.from_params(
            {"c": "1",
             "roi": '[{"type":"rect","x":0,"y":0,"w":4,"h":4}]'}
        )
        assert plain.signature() != masked.signature()
        again = RenderSpec.from_json(masked.to_json())
        assert again.signature() == masked.signature()


# ---------------------------------------------------------------------------
# engine extensions: mask identity, quantization, families, t-projection
# ---------------------------------------------------------------------------


class TestEngineExtensions:
    def test_masked_fused_device_equals_host_mirror(self):
        spec = RenderSpec.from_params({"c": "1|0:4095$FF8000"})
        planes = rng.integers(0, 4096, (2, 1, 32, 48), dtype=np.uint16)
        mask = rmasks.rasterize(
            rmasks.parse_roi(
                '[{"type":"ellipse","cx":24,"cy":16,"rx":15,"ry":9}]'
            ), 0, 0, 48, 32,
        )
        tables, luts = rengine.build_tables(spec, np.uint16)
        streams, lengths = rengine.fused_render_filter_deflate_batch(
            planes, tables, luts, 32, 1 + 48 * 3, "up", "rle",
            mask=np.stack([mask, mask]),
        )
        from omero_ms_pixel_buffer_tpu.ops.png import frame_png

        pngs = []
        for b in range(2):
            dev_png = frame_png(
                bytes(np.asarray(streams[b])[: int(lengths[b])]),
                48, 32, 8, 2,
            )
            host_png = rengine.render_png_host(
                planes[b], tables, luts, "up", mask
            )
            assert dev_png == host_png
            pngs.append(host_png)
        # masked-out pixels are black, masked-in identical to plain
        from omero_ms_pixel_buffer_tpu.ops.png import decode_png

        rgb = decode_png(pngs[0])
        plain = rengine.render_host(planes[0], tables, luts)
        assert (rgb[mask == 0] == 0).all()
        np.testing.assert_array_equal(rgb[mask == 1], plain[mask == 1])

    def test_quantize_to_u16(self):
        plane = np.array(
            [[-5.0, 0.0, 5.0, 10.0, 15.0, np.nan, np.inf]],
            dtype=np.float32,
        )
        q = rengine.quantize_to_u16(plane, (0.0, 10.0))
        assert q[0, 0] == 0 and q[0, 1] == 0
        assert q[0, 2] == 32768 and q[0, 3] == 65535
        assert q[0, 4] == 65535  # clipped above
        assert q[0, 5] == 0 and q[0, 6] == 65535  # nan / inf
        with pytest.raises(rengine.RenderError):
            rengine.quantize_to_u16(plane, (3.0, 3.0))

    def test_quantizable_domain(self):
        assert rengine.quantizable_dtype(np.float32)
        assert rengine.quantizable_dtype(np.int32)
        assert rengine.quantizable_dtype(np.float64)
        assert not rengine.quantizable_dtype(np.uint16)
        assert not rengine.renderable_dtype(np.float32)

    def test_polynomial_equals_exponential_tables(self):
        """OMERO's 'polynomial' family IS the gamma curve this
        service always called 'exponential' — identical tables."""
        maps_p = '[{"quantization":{"family":"polynomial","coefficient":2.0}}]'
        maps_e = '[{"quantization":{"family":"exponential","coefficient":2.0}}]'
        tp, _ = rengine.build_tables(
            RenderSpec.from_params({"c": "1", "maps": maps_p}), np.uint8
        )
        te, _ = rengine.build_tables(
            RenderSpec.from_params({"c": "1", "maps": maps_e}), np.uint8
        )
        np.testing.assert_array_equal(tp, te)

    def test_logarithmic_family(self):
        maps = '[{"quantization":{"family":"logarithmic","coefficient":9.0}}]'
        spec = RenderSpec.from_params({"c": "1", "maps": maps})
        tab, _ = rengine.build_tables(spec, np.uint8)
        x = np.arange(256) / 255.0
        ref = np.clip(
            np.floor(
                np.log1p(9.0 * x) / np.log1p(9.0) * 255.0 + 0.5
            ), 0, 255,
        ).astype(np.uint8)
        np.testing.assert_array_equal(tab[0], ref)

    def test_unknown_family_400(self):
        with pytest.raises(BadRequestError):
            RenderSpec.from_params({
                "c": "1",
                "maps": '[{"quantization":{"family":"cubic"}}]',
            })

    def test_projection_axis_parse_and_ranges(self):
        spec = RenderSpec.from_params({"p": "intmean:t|1:3"})
        assert spec.proj_axis == "t"
        assert spec.plane_range(2, 0, 4, 6) == [(2, 1), (2, 2), (2, 3)]
        zspec = RenderSpec.from_params({"p": "intmax"})
        assert zspec.proj_axis == "z"
        assert zspec.plane_range(0, 1, 3, 6) == [
            (0, 1), (1, 1), (2, 1)
        ]
        # axis only joins the signature when non-default (old cached
        # z-projection signatures stay stable)
        assert "@t" in spec.signature()
        assert "@" not in zspec.signature()
        with pytest.raises(BadRequestError):
            RenderSpec.from_params({"p": "intmax:q"})


# ---------------------------------------------------------------------------
# pipeline integration
# ---------------------------------------------------------------------------


class TestPipelineAnalysis:
    def test_histogram_host_device_bytes_identical(self, tmp_path):
        registry = _registry(tmp_path)
        spec = HistogramSpec.from_params({"bins": "32", "c": "1,2"})
        host = TilePipeline(PixelsService(registry), engine="host")
        dev = TilePipeline(PixelsService(registry), engine="device")
        bh = host.handle(_ctx(analysis=spec))
        bd = dev.handle(_ctx(analysis=spec))
        assert isinstance(bh, bytes) and bh == bd
        obj = json.loads(bh)
        ref = np.histogram(
            IMG[0, 0, 0, :48, :64], bins=32, range=(0, 65536)
        )[0]
        assert obj["data"] == ref.tolist()
        assert obj["channels"][1]["index"] == 1
        assert obj["channels"][0]["stats"]["count"] == 64 * 48

    def test_histogram_window_and_pixel_range(self, tmp_path):
        registry = _registry(tmp_path)
        pipe = TilePipeline(PixelsService(registry), engine="host")
        win = HistogramSpec.from_params(
            {"bins": "16", "c": "1|0:1024"}
        )
        body = pipe.handle(_ctx(analysis=win))
        obj = json.loads(body)
        assert obj["channels"][0]["window"] == [0.0, 1024.0]
        # all pixels land somewhere (clamped), count preserved
        assert sum(obj["data"]) == 64 * 48
        ptr = HistogramSpec.from_params(
            {"bins": "16", "c": "1|0:1024", "usePixelsTypeRange": "1"}
        )
        obj2 = json.loads(pipe.handle(_ctx(analysis=ptr)))
        assert obj2["channels"][0]["window"] == [0.0, 65535.0]

    def test_float_histogram_and_render(self, tmp_path):
        registry = _registry(tmp_path)
        host = TilePipeline(PixelsService(registry), engine="host")
        dev = TilePipeline(PixelsService(registry), engine="device")
        hspec = HistogramSpec.from_params({"bins": "16"})
        ctx = _ctx(analysis=hspec, img=2, w=64, h=64)
        bh = host.handle(ctx)
        assert bh is not None and bh == dev.handle(ctx)
        # float render with a window: host == device engine, and the
        # pixels equal an independent quantize-then-table reference
        rspec = RenderSpec.from_params({"c": "1|-50:50"})
        rh = host.handle(_ctx(render=rspec, img=2, w=64, h=64))
        rd = dev.handle(_ctx(render=rspec, img=2, w=64, h=64))
        assert isinstance(rh, bytes) and rh == rd
        from omero_ms_pixel_buffer_tpu.ops.png import decode_png

        q = rengine.quantize_to_u16(
            FIMG[0, 0, 0], (-50.0, 50.0)
        )
        tb, lu = rengine.build_tables(
            rspec.without_windows(), np.uint16
        )
        np.testing.assert_array_equal(
            decode_png(rh), rengine.render_host(q[None], tb, lu)
        )

    def test_float_render_without_window_404(self, tmp_path):
        registry = _registry(tmp_path)
        pipe = TilePipeline(PixelsService(registry), engine="host")
        assert pipe.handle(
            _ctx(render=RenderSpec.from_params({}), img=2)
        ) is None

    def test_t_projection(self, tmp_path):
        registry = _registry(tmp_path)
        pipe = TilePipeline(PixelsService(registry), engine="host")
        spec = RenderSpec.from_params({"c": "1|0:4095", "p": "intmax:t"})
        png = pipe.handle(_ctx(render=spec))
        from omero_ms_pixel_buffer_tpu.ops.png import decode_png

        ref = IMG[:, 0, 0, :48, :64].max(axis=0)
        tb, lu = rengine.build_tables(spec, np.uint16)
        np.testing.assert_array_equal(
            decode_png(png), rengine.render_host(ref[None], tb, lu)
        )

    def test_masked_render_through_pipeline(self, tmp_path):
        registry = _registry(tmp_path)
        pipe = TilePipeline(PixelsService(registry), engine="host")
        roi = '[{"type":"rect","x":8,"y":8,"w":16,"h":16}]'
        plain = pipe.handle(
            _ctx(render=RenderSpec.from_params({"c": "1|0:4095"}))
        )
        masked = pipe.handle(_ctx(render=RenderSpec.from_params(
            {"c": "1|0:4095", "roi": roi}
        )))
        from omero_ms_pixel_buffer_tpu.ops.png import decode_png

        m, p = decode_png(masked), decode_png(plain)
        assert (m[40:, 40:] == 0).all()
        np.testing.assert_array_equal(m[9:23, 9:23], p[9:23, 9:23])
        # raster cache warmed + namespaced invalidation
        assert pipe._mask_cache.snapshot()["rasters"] == 1
        pipe.invalidate_image(1)
        assert pipe._mask_cache.snapshot()["rasters"] == 0

    def test_projection_stack_bytes_413(self, tmp_path):
        """Regression: the per-plane max-tile-bytes guard let a
        z-projection materialize size_z times the budget."""
        registry = _registry(tmp_path)
        pipe = TilePipeline(
            PixelsService(registry), engine="host",
            max_tile_bytes=64 * 48 * 2 * 2,  # two planes' worth
        )
        proj = RenderSpec.from_params({"c": "1|0:4095", "p": "intmax"})
        r = pipe.handle(_ctx(render=proj))
        assert isinstance(r, RequestTooLargeError) and r.code == 413
        # a single plane (and a 2-plane range) still fits
        assert isinstance(pipe.handle(
            _ctx(render=RenderSpec.from_params({"c": "1|0:4095"}))
        ), bytes)
        assert isinstance(pipe.handle(_ctx(
            render=RenderSpec.from_params(
                {"c": "1|0:4095", "p": "intmax|0:1"}
            )
        )), bytes)

    def test_histogram_multichannel_bytes_413(self, tmp_path):
        registry = _registry(tmp_path)
        pipe = TilePipeline(
            PixelsService(registry), engine="host",
            max_tile_bytes=64 * 48 * 2 * 2,
        )
        spec = HistogramSpec.from_params({"c": "1,2,3"})
        r = pipe.handle(_ctx(analysis=spec))
        assert isinstance(r, RequestTooLargeError)
        assert isinstance(pipe.handle(
            _ctx(analysis=HistogramSpec.from_params({"c": "1,2"}))
        ), bytes)

    def test_projection_reads_fill_plane_cache(self, tmp_path):
        """Regression (KNOWN_GAPS r10): projection plane reads used
        to bypass the HBM plane cache — a repeated projection pan
        re-read every z plane per tile. Now they go through (and
        fill) it: the second batch issues ZERO host tile reads."""
        registry = _registry(tmp_path)
        pipe = TilePipeline(PixelsService(registry), engine="device")
        pipe._plane_cache = DevicePlaneCache(admit_after=1)
        spec = RenderSpec.from_params(
            {"c": "1|0:4095", "p": "intmax|0:3"}
        )
        buf = pipe.pixels_service.get_pixel_buffer(1)
        calls = {"read_tiles": 0}
        orig = buf.read_tiles

        def counting(coords, level=0):
            calls["read_tiles"] += len(coords)
            return orig(coords, level=level)

        buf.read_tiles = counting
        first = pipe.handle(_ctx(render=spec))
        after_first = calls["read_tiles"]
        second = pipe.handle(_ctx(render=spec, x=64, w=64))
        assert first is not None and second is not None
        assert calls["read_tiles"] == after_first == 0
        # and the bytes match the host engine exactly
        host = TilePipeline(PixelsService(registry), engine="host")
        assert host.handle(_ctx(render=spec)) == first


# ---------------------------------------------------------------------------
# HTTP integration + chaos lanes
# ---------------------------------------------------------------------------


async def _make_client(tmp_path, overrides=None):
    registry = _registry(tmp_path)
    store = MemorySessionStore({"ck": "key-1"})
    raw = {
        "session-store": {"type": "memory"},
        "backend": {"batching": {"coalesce-window-ms": 1.0}},
    }
    for key, value in (overrides or {}).items():
        raw[key] = value
    config = Config.from_dict(raw)
    app_obj = PixelBufferApp(
        config, pixels_service=PixelsService(registry),
        session_store=store,
    )
    client = TestClient(TestServer(app_obj.make_app()))
    await client.start_server()
    return client, app_obj


class TestHistogramHttp:
    async def test_full_flow(self, tmp_path):
        client, _ = await _make_client(tmp_path)
        try:
            r = await client.get(
                "/histogram/1/0/0/0?bins=16&w=64&h=64", headers=AUTH
            )
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            assert r.headers["X-Cache"] == "miss"
            etag = r.headers["ETag"]
            obj = json.loads(await r.read())
            ref = np.histogram(
                IMG[0, 0, 0, :64, :64], bins=16, range=(0, 65536)
            )[0]
            assert obj["data"] == ref.tolist()
            r2 = await client.get(
                "/histogram/1/0/0/0?bins=16&w=64&h=64", headers=AUTH
            )
            assert r2.headers["X-Cache"] == "hit"
            assert r2.headers["ETag"] == etag
            r3 = await client.get(
                "/histogram/1/0/0/0?bins=16&w=64&h=64",
                headers={**AUTH, "If-None-Match": etag},
            )
            assert r3.status == 304
        finally:
            await client.close()

    async def test_auth_and_grammar(self, tmp_path):
        client, _ = await _make_client(tmp_path)
        try:
            assert (await client.get("/histogram/1/0/0/0")).status == 403
            assert (await client.get(
                "/histogram/1/0/0/0?bins=0", headers=AUTH
            )).status == 400
            assert (await client.get(
                "/histogram/1/0/0/0?bins=999999", headers=AUTH
            )).status == 400
            assert (await client.get(
                "/histogram/99/0/0/0", headers=AUTH
            )).status == 404
            assert (await client.get(
                "/histogram/1/9/0/0", headers=AUTH
            )).status == 404
        finally:
            await client.close()

    async def test_analysis_disabled(self, tmp_path):
        client, _ = await _make_client(
            tmp_path, {"analysis": {"enabled": False}}
        )
        try:
            r = await client.get(
                "/histogram/1/0/0/0", headers=AUTH
            )
            # the route is simply not mounted (405 via the OPTIONS
            # catch-all — the same answer any unknown GET path gets
            # from this server)
            assert r.status == 405
        finally:
            await client.close()

    async def test_projection_413_over_http(self, tmp_path):
        client, app_obj = await _make_client(
            tmp_path,
            {"backend": {"max-tile-mb": 256,
                         "batching": {"coalesce-window-ms": 1.0}}},
        )
        app_obj.pipeline.max_tile_bytes = 64 * 64 * 2 * 2
        try:
            r = await client.get(
                "/render/1/0/0/0?w=64&h=64&p=intmax", headers=AUTH
            )
            assert r.status == 413
            ok = await client.get(
                "/render/1/0/0/0?w=64&h=64", headers=AUTH
            )
            assert ok.status == 200
        finally:
            await client.close()

    @pytest.mark.resilience
    def test_engine_chaos_host_fallback_identical(
        self, tmp_path
    ):
        """The analysis.engine chaos seam: a failing device reduction
        degrades to the host mirror with byte-identical JSON."""
        registry = _registry(tmp_path)
        pipe = TilePipeline(PixelsService(registry), engine="device")
        spec = HistogramSpec.from_params({"bins": "64", "c": "1,2"})
        clean = pipe.handle(_ctx(analysis=spec))
        INJECTOR.install("analysis.engine", always(RuntimeError))
        broken = pipe.handle(_ctx(analysis=spec))
        assert clean is not None and clean == broken

    @pytest.mark.resilience
    async def test_histogram_deadline_504(self, tmp_path):
        client, _ = await _make_client(
            tmp_path, {"resilience": {"request-budget-ms": 1}}
        )
        try:
            r = await client.get(
                "/histogram/1/0/0/0?w=64&h=64", headers=AUTH
            )
            assert r.status == 504
        finally:
            await client.close()

    @pytest.mark.resilience
    async def test_histogram_sheds_at_door_like_tiles(
        self, tmp_path
    ):
        """Admission parity: when the SLO door gate sheds, histogram
        requests 503 with Retry-After exactly like native tiles."""
        client, app_obj = await _make_client(tmp_path)
        try:
            app_obj.scheduler.would_overflow_shed = lambda p: True
            r = await client.get(
                "/histogram/1/0/0/0?w=64&h=64", headers=AUTH
            )
            assert r.status == 503 and "Retry-After" in r.headers
        finally:
            await client.close()
