"""TIFF LZW (5) + PackBits (32773) codec coverage: python/native
round-trips, predictor 2, PIL cross-validation in BOTH directions
(independent encoder -> our decoder; our encoder -> independent
decoder), and end-to-end serving of compressed fixtures.

Reference behavior being matched: Bio-Formats decodes these inside
ome.io.nio readers (TileRequestHandler.java:104-112)."""

import zlib

import numpy as np
import pytest
from PIL import Image

from omero_ms_pixel_buffer_tpu.io.ometiff import (
    OmeTiffPixelBuffer,
    write_ome_tiff,
)
from omero_ms_pixel_buffer_tpu.ops import codecs


def _smooth(n, seed=0):
    rng = np.random.default_rng(seed)
    return (
        np.cumsum(rng.integers(-3, 4, n), dtype=np.int64)
        .astype(np.uint8)
        .tobytes()
    )


class TestPythonCodecs:
    def test_lzw_roundtrip_all_widths(self):
        # enough distinct phrases to cross the 9->10->11->12 bit bumps
        # and force a table restart (Clear)
        data = _smooth(300_000)
        enc = codecs.lzw_encode(data)
        assert codecs.lzw_decode(enc, len(data)) == data
        assert len(enc) < len(data)  # actually compresses smooth data

    def test_lzw_incompressible_roundtrip(self):
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, 100_000).astype(np.uint8).tobytes()
        assert codecs.lzw_decode(codecs.lzw_encode(data), len(data)) == data

    def test_lzw_corrupt_returns_none(self):
        assert codecs.lzw_decode(b"", 100) is None
        # first code after Clear must be a literal; 0xFFFF... gives 511
        assert codecs.lzw_decode(b"\xff\xff\xff\xff", 100) is None

    def test_lzw_truncated_returns_none(self):
        # a stream cut mid-codeword must fail the lane, not serve a
        # partially-decoded block
        data = _smooth(4000)
        enc = codecs.lzw_encode(data)
        assert codecs.lzw_decode(enc[: len(enc) // 2], len(data)) is None

    def test_packbits_fuzz(self):
        rng = np.random.default_rng(3)
        for trial in range(100):
            n = int(rng.integers(1, 600))
            alphabet = int(rng.integers(2, 256))
            row = rng.integers(0, alphabet, n).astype(np.uint8).tobytes()
            rb = int(rng.integers(1, n + 1))
            enc = codecs.packbits_encode(row, rb)
            assert codecs.packbits_decode(enc, n) == row, trial

    def test_packbits_noop_byte_skipped(self):
        assert codecs.packbits_decode(b"\x80\x00a", 1) == b"a"

    @pytest.mark.parametrize("itemsize,bo", [(1, "="), (2, "<"), (2, ">")])
    @pytest.mark.parametrize("samples", [1, 3])
    def test_predictor2_roundtrip(self, itemsize, bo, samples):
        rng = np.random.default_rng(11)
        w, rows = 17, 6
        hi = 255 if itemsize == 1 else 60000
        raw = rng.integers(0, hi, rows * w * samples)
        dtype = np.uint8 if itemsize == 1 else np.dtype(f"{bo}u2")
        block = np.ascontiguousarray(raw.astype(dtype)).view(np.uint8)
        fwd = codecs.apply_predictor2(
            block.copy(), w * samples, itemsize, samples, bo
        )
        back = codecs.undo_predictor2(
            fwd.copy(), w * samples, itemsize, samples, bo
        )
        assert bytes(back) == bytes(block)


class TestNativeDecodeBatch:
    def test_mixed_codec_batch_matches_python(self):
        from omero_ms_pixel_buffer_tpu.runtime.native import get_engine

        engine = get_engine()
        if engine is None:
            pytest.skip("no native engine")
        rng = np.random.default_rng(5)
        blocks, caps, codes, truths = [], [], [], []
        for i in range(24):
            n = int(rng.integers(100, 50_000))
            raw = _smooth(n, seed=i)
            codec = [8, 5, 32773][i % 3]
            if codec == 8:
                enc = zlib.compress(raw)
            elif codec == 5:
                enc = codecs.lzw_encode(raw)
            else:
                enc = codecs.packbits_encode(raw, 500)
            blocks.append(enc)
            caps.append(n)
            codes.append(codec)
            truths.append(raw)
        outs = engine.decode_batch(blocks, caps, codes)
        for truth, out, codec in zip(truths, outs, codes):
            assert out is not None and out.tobytes() == truth, codec

    def test_corrupt_lane_degrades_alone(self):
        from omero_ms_pixel_buffer_tpu.runtime.native import get_engine

        engine = get_engine()
        if engine is None:
            pytest.skip("no native engine")
        good = _smooth(1000)
        outs = engine.decode_batch(
            [b"\x00garbage", codecs.lzw_encode(good)],
            [1000, 1000],
            [5, 5],
        )
        assert outs[0] is None  # corrupt lane must degrade to None
        assert outs[1] is not None and outs[1].tobytes() == good

    def test_truncated_lzw_lane_degrades_native(self):
        from omero_ms_pixel_buffer_tpu.runtime.native import get_engine

        engine = get_engine()
        if engine is None:
            pytest.skip("no native engine")
        good = _smooth(1000)
        enc = codecs.lzw_encode(good)
        outs = engine.decode_batch(
            [enc[: len(enc) // 2], enc], [1000, 1000], [5, 5]
        )
        assert outs[0] is None
        assert outs[1] is not None and outs[1].tobytes() == good

    def test_abi_v2_fallback_caps_zlib_output(self):
        """The pure-Python decode fallback must bound zlib output at
        the lane capacity (a hostile stream can't balloon memory) and
        fail truncated streams like native uncompress does."""
        import zlib

        from omero_ms_pixel_buffer_tpu.runtime.native import get_engine

        engine = get_engine()
        if engine is None:
            pytest.skip("no native engine")
        saved = engine._has_decode_batch
        engine._has_decode_batch = False
        try:
            good = _smooth(1000)
            bomb = zlib.compress(b"\x00" * 50_000_000)  # 50 MB from ~48 KB
            trunc = zlib.compress(good)[:-8]
            outs = engine.decode_batch(
                [bomb, trunc, zlib.compress(good), codecs.lzw_encode(good)],
                [1000, 1000, 1000, 1000],
                [8, 8, 8, 5],  # mixed codecs forces the generic fallback
            )
            assert outs[0] is None  # overflow past cap -> failed lane
            assert outs[1] is None  # truncated stream -> failed lane
            assert outs[2] is not None and outs[2].tobytes() == good
            assert outs[3] is not None and outs[3].tobytes() == good
        finally:
            engine._has_decode_batch = saved


def _plane(shape=(160, 200), dtype=np.uint16, seed=2):
    rng = np.random.default_rng(seed)
    hi = 255 if np.dtype(dtype).itemsize == 1 else 60000
    smooth = np.cumsum(
        rng.integers(-9, 10, shape), axis=1, dtype=np.int64
    ) % hi
    return smooth.astype(dtype)


class TestReaderCompression:
    @pytest.mark.parametrize("compression", ["lzw", "packbits"])
    @pytest.mark.parametrize("tiled", [True, False])
    def test_roundtrip_through_reader(self, tmp_path, compression, tiled):
        truth = _plane()
        path = str(tmp_path / f"c-{compression}-{tiled}.ome.tiff")
        write_ome_tiff(
            path, truth[None, None, None],
            tile_size=(64, 64) if tiled else None,
            compression=compression,
        )
        buf = OmeTiffPixelBuffer(path)
        got = buf.get_tile_at(0, 0, 0, 0, 16, 8, 100, 120)
        np.testing.assert_array_equal(got, truth[8:128, 16:116])
        buf.close()

    @pytest.mark.parametrize("compression", ["lzw", "zlib"])
    @pytest.mark.parametrize("dtype", [np.uint8, np.uint16])
    def test_predictor2_roundtrip_through_reader(
        self, tmp_path, compression, dtype
    ):
        truth = _plane(dtype=dtype)
        path = str(tmp_path / "pred2.ome.tiff")
        write_ome_tiff(
            path, truth[None, None, None], tile_size=(64, 64),
            compression=compression, predictor=2,
        )
        buf = OmeTiffPixelBuffer(path)
        got = buf.get_tile_at(0, 0, 0, 0, 0, 0, 200, 160)
        np.testing.assert_array_equal(got, truth)
        buf.close()

    @pytest.mark.parametrize("compression", ["lzw", "packbits"])
    def test_batched_read_tiles(self, tmp_path, compression):
        truth = _plane((256, 256))
        path = str(tmp_path / "batch.ome.tiff")
        write_ome_tiff(
            path, truth[None, None, None], tile_size=(64, 64),
            compression=compression,
        )
        buf = OmeTiffPixelBuffer(path)
        coords = [
            (0, 0, 0, x, y, 96, 96)
            for x in (0, 80, 160) for y in (0, 80, 160)
        ]
        tiles = buf.read_tiles(coords)
        for (z, c, t, x, y, w, h), tile in zip(coords, tiles):
            np.testing.assert_array_equal(
                tile, truth[y : y + h, x : x + w]
            )
        buf.close()


class TestPilInterop:
    """PIL is the independent implementation: files it writes must
    decode pixel-exact here, and files this writer produces must
    decode pixel-exact in PIL."""

    @pytest.mark.parametrize(
        "pil_comp", ["tiff_lzw", "packbits", "tiff_adobe_deflate"]
    )
    def test_pil_written_file_decodes_here(self, tmp_path, pil_comp):
        truth = _plane((120, 150), dtype=np.uint8)
        path = str(tmp_path / "pil.tiff")
        Image.fromarray(truth).save(path, compression=pil_comp)
        buf = OmeTiffPixelBuffer(path)
        got = buf.get_tile_at(0, 0, 0, 0, 0, 0, 150, 120)
        np.testing.assert_array_equal(got, truth)
        buf.close()

    def test_pil_lzw_with_predictor_decodes_here(self, tmp_path):
        truth = _plane((120, 150), dtype=np.uint8)
        path = str(tmp_path / "pil-pred.tiff")
        Image.fromarray(truth).save(
            path, compression="tiff_lzw", tiffinfo={317: 2}
        )
        buf = OmeTiffPixelBuffer(path)
        got = buf.get_tile_at(0, 0, 0, 0, 0, 0, 150, 120)
        np.testing.assert_array_equal(got, truth)
        buf.close()

    @pytest.mark.parametrize("compression", ["lzw", "packbits"])
    def test_our_file_decodes_in_pil(self, tmp_path, compression):
        truth = _plane((120, 150), dtype=np.uint8)
        path = str(tmp_path / "ours.ome.tiff")
        write_ome_tiff(
            path, truth[None, None, None], tile_size=None,
            compression=compression, big_endian=False,
        )
        got = np.array(Image.open(path))
        np.testing.assert_array_equal(got, truth)

    def test_our_lzw_predictor_decodes_in_pil(self, tmp_path):
        truth = _plane((120, 150), dtype=np.uint8)
        path = str(tmp_path / "ours-pred.ome.tiff")
        write_ome_tiff(
            path, truth[None, None, None], tile_size=None,
            compression="lzw", predictor=2, big_endian=False,
        )
        got = np.array(Image.open(path))
        np.testing.assert_array_equal(got, truth)
