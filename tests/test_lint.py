"""ompb-lint (tools/analyze) — detection, precision, and policy.

Three contracts:

- every seeded violation in ``tests/fixtures/lint/seeded`` is caught
  by its rule (detection);
- the clean corpus produces ZERO findings (precision — a linter that
  cries wolf gets deleted from CI within a month);
- the escape hatches behave: inline suppressions count as suppressed,
  the baseline hides exactly what it lists, and hot-path modules are
  REFUSED baseline entries.

Plus the acceptance bar itself: the repo is clean under the checked-in
baseline — the same invariant the CI ``lint`` job enforces via
``python -m tools.analyze``.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from tools.analyze import run_paths, write_baseline
from tools.analyze.core import REPO_ROOT

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "lint"
SEEDED = str(FIXTURES / "seeded")
CLEAN = str(FIXTURES / "clean")
SUPPRESSED = str(FIXTURES / "suppressed")


def _by_file(report):
    out = {}
    for f in report.findings:
        out.setdefault(os.path.basename(f.path), []).append(f)
    return out


class TestSeededViolations:
    @pytest.fixture(scope="class")
    def seeded(self):
        return _by_file(run_paths([SEEDED], baseline_path=None))

    def test_loop_block_direct_and_indirect(self, seeded):
        found = seeded["blocking_async.py"]
        assert all(f.rule == "loop-block" for f in found)
        messages = " | ".join(f.message for f in found)
        # one per seeded async function: direct sleep, call-graph
        # reach, Future.result, open(), subprocess
        assert len(found) == 5
        assert "time.sleep" in messages
        assert "helper() -> time.sleep" in messages
        assert "Future.result" in messages
        assert "sync file open" in messages
        assert "subprocess" in messages

    def test_lock_discipline(self, seeded):
        found = seeded["unlocked_shared.py"]
        assert found and all(f.rule == "lock-discipline" for f in found)
        assert any(
            "SharedQueue.items" in f.message and "'drain'" in f.message
            for f in found
        )

    def test_resilience_coverage(self, seeded):
        found = seeded["naked_store.py"]
        assert [f.rule for f in found] == ["resilience-coverage"]
        assert "HTTPConnection" in found[0].message
        assert "circuit-breaker" in found[0].message

    def test_resilience_coverage_requires_timeout(self, seeded):
        """Breaker + fault point alone no longer suffice: the rule
        also demands a per-call timeout on some caller path."""
        found = seeded["no_timeout.py"]
        assert [f.rule for f in found] == ["resilience-coverage"]
        assert "per-call timeout" in found[0].message

    def test_resilience_coverage_requires_retry(self, seeded):
        """r18: breaker + fault point + timeout still don't suffice —
        the rule also demands retry evidence (resilient_get, a
        retry-named wrapper, or the reconnect-once try/except shape)
        on some caller path."""
        found = seeded["no_retry.py"]
        assert [f.rule for f in found] == ["resilience-coverage"]
        assert "retry policy" in found[0].message

    def test_jax_hotpath(self, seeded):
        found = seeded["hotpath_sync.py"]
        assert all(f.rule == "jax-hotpath" for f in found)
        messages = " | ".join(f.message for f in found)
        assert "np.asarray(...)" in messages       # host sync
        assert "block_until_ready" in messages     # full sync
        assert "re-traces" in messages             # per-call jit

    def test_jax_hotpath_loop_sinks(self, seeded):
        # r9 extension: np.asarray / .item() / float() on device
        # values INSIDE loops — the per-iteration round trip the
        # double-buffered dispatcher code must never reintroduce
        found = seeded["hotpath_loop_sync.py"]
        assert all(f.rule == "jax-hotpath" for f in found)
        assert len(found) == 3
        messages = " | ".join(f.message for f in found)
        assert messages.count("inside a loop") == 3
        assert "np.asarray(...)" in messages
        assert ".item() on device value" in messages
        assert "float(...)" in messages

    def test_error_taxonomy(self, seeded):
        found = seeded["bad_errors.py"]
        assert all(f.rule == "error-taxonomy" for f in found)
        messages = " | ".join(f.message for f in found)
        assert "bare 'except:'" in messages
        assert "CancelledError" in messages
        assert "'KeyError'" in messages

    def test_every_rule_fired(self, seeded):
        fired = {f.rule for fs in seeded.values() for f in fs}
        assert fired == {
            "loop-block", "lock-discipline", "resilience-coverage",
            "jax-hotpath", "error-taxonomy",
        }


class TestPrecision:
    def test_clean_corpus_no_false_positives(self):
        report = run_paths([CLEAN], baseline_path=None)
        assert report.findings == [], [
            f.format() for f in report.findings
        ]

    def test_inline_suppressions(self):
        report = run_paths([SUPPRESSED], baseline_path=None)
        assert report.findings == []
        # both spellings (same-line and comment-above) counted
        assert len(report.suppressed) == 2
        assert all(f.rule == "loop-block" for f in report.suppressed)


class TestBaseline:
    def test_roundtrip(self, tmp_path):
        baseline = str(tmp_path / "baseline.json")
        dirty = run_paths([SEEDED], baseline_path=None)
        assert dirty.findings
        written, hot = write_baseline([SEEDED], baseline_path=baseline)
        assert written == len(dirty.findings) and not hot
        clean = run_paths([SEEDED], baseline_path=baseline)
        assert clean.findings == []
        assert len(clean.baselined) == written

    def test_baseline_does_not_hide_new_findings(self, tmp_path):
        baseline = str(tmp_path / "baseline.json")
        write_baseline([SEEDED], baseline_path=baseline)
        # a finding the baseline has never seen stays live
        extra = tmp_path / "extra.py"
        extra.write_text(
            "import time\n\nasync def fresh():\n    time.sleep(1)\n"
        )
        report = run_paths(
            [SEEDED, str(extra)], baseline_path=baseline
        )
        assert [f.rule for f in report.findings] == ["loop-block"]

    def test_hot_path_refused(self, tmp_path):
        root = tmp_path
        hot_dir = root / "omero_ms_pixel_buffer_tpu" / "models"
        hot_dir.mkdir(parents=True)
        bad = hot_dir / "bad.py"
        bad.write_text(
            "import time\n\nasync def f():\n    time.sleep(1)\n"
        )
        baseline = str(tmp_path / "baseline.json")
        written, hot = write_baseline(
            ["omero_ms_pixel_buffer_tpu/models/bad.py"],
            baseline_path=baseline, root=str(root),
        )
        assert written == 0
        assert hot and hot[0].rule == "loop-block"
        assert not os.path.exists(baseline)


class TestRepoIsClean:
    def test_package_has_no_unsuppressed_findings(self):
        """The acceptance criterion: ``python -m tools.analyze`` exits
        0 on the repo — every live finding has been fixed, justified
        inline, or (non-hot-path only) baselined."""
        report = run_paths()  # default paths + checked-in baseline
        assert report.findings == [], "\n" + "\n".join(
            f.format() for f in report.findings
        )

    def test_baseline_entries_all_match_reality(self):
        """Stale baseline entries (code fixed but entry kept) must be
        pruned so the debt list tracks reality."""
        from tools.analyze.core import load_baseline

        report = run_paths()
        assert len(report.baselined) == len(load_baseline())


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.analyze", *args],
            cwd=REPO_ROOT, capture_output=True, text=True,
        )

    def test_exit_codes(self):
        assert self._run(CLEAN).returncode == 0
        dirty = self._run(SEEDED)
        assert dirty.returncode == 1
        assert "loop-block" in dirty.stdout

    def test_json_output(self):
        proc = self._run(SEEDED, "--json")
        data = json.loads(proc.stdout)
        assert data["findings"] and all(
            {"rule", "path", "line", "message"} <= set(f)
            for f in data["findings"]
        )

    def test_repo_gate(self):
        """Exactly what CI runs."""
        assert self._run().returncode == 0
