"""ompb-lint (tools/analyze) — detection, precision, and policy.

Three contracts:

- every seeded violation in ``tests/fixtures/lint/seeded`` is caught
  by its rule (detection);
- the clean corpus produces ZERO findings (precision — a linter that
  cries wolf gets deleted from CI within a month);
- the escape hatches behave: inline suppressions count as suppressed,
  the baseline hides exactly what it lists, and hot-path modules are
  REFUSED baseline entries.

Plus the acceptance bar itself: the repo is clean under the checked-in
baseline — the same invariant the CI ``lint`` job enforces via
``python -m tools.analyze``.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from tools.analyze import run_paths, write_baseline
from tools.analyze.core import REPO_ROOT

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "lint"
SEEDED = str(FIXTURES / "seeded")
CLEAN = str(FIXTURES / "clean")
SUPPRESSED = str(FIXTURES / "suppressed")


def _by_file(report):
    out = {}
    for f in report.findings:
        out.setdefault(os.path.basename(f.path), []).append(f)
    return out


class TestSeededViolations:
    @pytest.fixture(scope="class")
    def seeded(self):
        return _by_file(run_paths([SEEDED], baseline_path=None))

    def test_loop_block_direct_and_indirect(self, seeded):
        found = seeded["blocking_async.py"]
        assert all(f.rule == "loop-block" for f in found)
        messages = " | ".join(f.message for f in found)
        # one per seeded async function: direct sleep, call-graph
        # reach, Future.result, open(), subprocess
        assert len(found) == 5
        assert "time.sleep" in messages
        assert "helper() -> time.sleep" in messages
        assert "Future.result" in messages
        assert "sync file open" in messages
        assert "subprocess" in messages

    def test_lock_discipline(self, seeded):
        found = seeded["unlocked_shared.py"]
        assert found and all(f.rule == "lock-discipline" for f in found)
        assert any(
            "SharedQueue.items" in f.message and "'drain'" in f.message
            for f in found
        )

    def test_resilience_coverage(self, seeded):
        found = seeded["naked_store.py"]
        assert [f.rule for f in found] == ["resilience-coverage"]
        assert "HTTPConnection" in found[0].message
        assert "circuit-breaker" in found[0].message

    def test_resilience_coverage_requires_timeout(self, seeded):
        """Breaker + fault point alone no longer suffice: the rule
        also demands a per-call timeout on some caller path."""
        found = seeded["no_timeout.py"]
        assert [f.rule for f in found] == ["resilience-coverage"]
        assert "per-call timeout" in found[0].message

    def test_resilience_coverage_requires_retry(self, seeded):
        """r18: breaker + fault point + timeout still don't suffice —
        the rule also demands retry evidence (resilient_get, a
        retry-named wrapper, or the reconnect-once try/except shape)
        on some caller path."""
        found = seeded["no_retry.py"]
        assert [f.rule for f in found] == ["resilience-coverage"]
        assert "retry policy" in found[0].message

    def test_jax_hotpath(self, seeded):
        found = seeded["hotpath_sync.py"]
        assert all(f.rule == "jax-hotpath" for f in found)
        messages = " | ".join(f.message for f in found)
        assert "np.asarray(...)" in messages       # host sync
        assert "block_until_ready" in messages     # full sync
        assert "re-traces" in messages             # per-call jit

    def test_jax_hotpath_loop_sinks(self, seeded):
        # r9 extension: np.asarray / .item() / float() on device
        # values INSIDE loops — the per-iteration round trip the
        # double-buffered dispatcher code must never reintroduce
        found = seeded["hotpath_loop_sync.py"]
        assert all(f.rule == "jax-hotpath" for f in found)
        assert len(found) == 3
        messages = " | ".join(f.message for f in found)
        assert messages.count("inside a loop") == 3
        assert "np.asarray(...)" in messages
        assert ".item() on device value" in messages
        assert "float(...)" in messages

    def test_error_taxonomy(self, seeded):
        found = seeded["bad_errors.py"]
        assert all(f.rule == "error-taxonomy" for f in found)
        messages = " | ".join(f.message for f in found)
        assert "bare 'except:'" in messages
        assert "CancelledError" in messages
        assert "'KeyError'" in messages

    def test_crossmod_loop_block(self, seeded):
        """r21 regression: the blocking helper lives in a SIBLING
        module — only the interprocedural call graph sees the chain."""
        found = seeded["crossmod_block_a.py"]
        assert [f.rule for f in found] == ["loop-block"]
        assert "busy_wait() -> time.sleep" in found[0].message
        assert "via tests/fixtures/lint/seeded/crossmod_block_b.py" in (
            found[0].message
        )
        # the sync helper module itself is not a violation
        assert "crossmod_block_b.py" not in seeded

    def test_passed_device_param(self, seeded):
        """r21 regression: the device value escapes through a
        PARAMETER — the caller produces it, the callee host-syncs it
        (the _finish_png_lanes shape the module-local analyzer
        missed)."""
        found = seeded["passed_device_param.py"]
        assert [f.rule for f in found] == ["jax-hotpath"]
        assert "'_finish_lanes'" in found[0].message
        assert "np.asarray(...)" in found[0].message
        assert (
            "device value arrives via parameter filtered"
            in found[0].message
        )

    def test_task_hygiene(self, seeded):
        found = seeded["untracked_task.py"]
        assert all(f.rule == "task-hygiene" for f in found)
        assert len(found) == 4
        messages = " | ".join(f.message for f in found)
        # each escape shape distinctly diagnosed
        assert messages.count("bare fire-and-forget statement") == 2
        assert "assigned to 't' which is never used again" in messages
        assert (
            "stored on 'self._task' but nothing in the class" in messages
        )
        assert "run_in_executor" in messages and "create_task" in messages

    def test_bounded_growth(self, seeded):
        found = seeded["unbounded_growth.py"]
        assert all(f.rule == "bounded-growth" for f in found)
        messages = " | ".join(f.message for f in found)
        assert "module-level '_SEEN'" in messages
        assert "'SessionIndex.by_key' grows (subscript store)" in messages
        assert "'SessionIndex.order' grows (append)" in messages

    def test_trust_surface(self, seeded):
        found = seeded["unguarded_internal.py"]
        assert all(f.rule == "trust-surface" for f in found)
        assert len(found) == 2
        messages = " | ".join(f.message for f in found)
        assert "route '/internal/state'" in messages
        assert "verify_cluster_request" in messages
        assert "decode_transfer(...) in 'ingest'" in messages
        assert "body_matches / verify_entry_bytes" in messages

    def test_session_channel_fleet_invariants(self, seeded):
        """r22: the fleet-invariant rules fire on SESSION-CHANNEL
        shapes — an uncapped channel registry, a fan-out task dropped
        on the floor, a pump stored but never drained. The leaks the
        interactive session plane must never grow, seeded."""
        found = seeded["session_channel_leak.py"]
        assert {f.rule for f in found} == {
            "task-hygiene", "bounded-growth"
        }
        messages = " | ".join(f.message for f in found)
        assert "'LeakyChannelRegistry.channels' grows" in messages
        assert "'LeakyChannelRegistry.pushes' grows" in messages
        assert "bare fire-and-forget statement" in messages
        assert "stored on 'self._pump' but nothing" in messages

    def test_config_drift(self, seeded):
        found = seeded["drift_config.py"]
        assert all(f.rule == "config-drift" for f in found)
        assert len(found) == 3
        messages = " | ".join(f.message for f in found)
        # one of each drift type
        assert "'mystery-knob' is validated/read here but never documented" in messages
        assert "'ghost-flag' is documented in drift_config.yaml" in messages
        assert "'dead-timeout-ms' is parsed but its value is never consumed" in messages

    def test_every_rule_fired(self, seeded):
        fired = {f.rule for fs in seeded.values() for f in fs}
        assert fired == {
            "loop-block", "lock-discipline", "resilience-coverage",
            "jax-hotpath", "error-taxonomy", "task-hygiene",
            "bounded-growth", "trust-surface", "config-drift",
        }


class TestPrecision:
    def test_clean_corpus_no_false_positives(self):
        report = run_paths([CLEAN], baseline_path=None)
        assert report.findings == [], [
            f.format() for f in report.findings
        ]

    def test_inline_suppressions(self):
        report = run_paths([SUPPRESSED], baseline_path=None)
        assert report.findings == []
        # both spellings (same-line and comment-above) counted
        assert len(report.suppressed) == 2
        assert all(f.rule == "loop-block" for f in report.suppressed)


class TestBaseline:
    def test_roundtrip(self, tmp_path):
        baseline = str(tmp_path / "baseline.json")
        dirty = run_paths([SEEDED], baseline_path=None)
        assert dirty.findings
        written, hot = write_baseline([SEEDED], baseline_path=baseline)
        assert written == len(dirty.findings) and not hot
        clean = run_paths([SEEDED], baseline_path=baseline)
        assert clean.findings == []
        assert len(clean.baselined) == written

    def test_baseline_does_not_hide_new_findings(self, tmp_path):
        baseline = str(tmp_path / "baseline.json")
        write_baseline([SEEDED], baseline_path=baseline)
        # a finding the baseline has never seen stays live
        extra = tmp_path / "extra.py"
        extra.write_text(
            "import time\n\nasync def fresh():\n    time.sleep(1)\n"
        )
        report = run_paths(
            [SEEDED, str(extra)], baseline_path=baseline
        )
        assert [f.rule for f in report.findings] == ["loop-block"]

    def test_hot_path_refused(self, tmp_path):
        root = tmp_path
        hot_dir = root / "omero_ms_pixel_buffer_tpu" / "models"
        hot_dir.mkdir(parents=True)
        bad = hot_dir / "bad.py"
        bad.write_text(
            "import time\n\nasync def f():\n    time.sleep(1)\n"
        )
        baseline = str(tmp_path / "baseline.json")
        written, hot = write_baseline(
            ["omero_ms_pixel_buffer_tpu/models/bad.py"],
            baseline_path=baseline, root=str(root),
        )
        assert written == 0
        assert hot and hot[0].rule == "loop-block"
        assert not os.path.exists(baseline)


class TestRepoIsClean:
    def test_package_has_no_unsuppressed_findings(self):
        """The acceptance criterion: ``python -m tools.analyze`` exits
        0 on the repo — every live finding has been fixed, justified
        inline, or (non-hot-path only) baselined."""
        report = run_paths()  # default paths + checked-in baseline
        assert report.findings == [], "\n" + "\n".join(
            f.format() for f in report.findings
        )

    def test_baseline_entries_all_match_reality(self):
        """Stale baseline entries (code fixed but entry kept) must be
        pruned so the debt list tracks reality."""
        from tools.analyze.core import load_baseline

        report = run_paths()
        assert len(report.baselined) == len(load_baseline())


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.analyze", *args],
            cwd=REPO_ROOT, capture_output=True, text=True,
        )

    def test_exit_codes(self):
        assert self._run(CLEAN).returncode == 0
        dirty = self._run(SEEDED)
        assert dirty.returncode == 1
        assert "loop-block" in dirty.stdout

    def test_json_output(self):
        proc = self._run(SEEDED, "--json")
        data = json.loads(proc.stdout)
        assert data["findings"] and all(
            {"rule", "path", "line", "message"} <= set(f)
            for f in data["findings"]
        )

    def test_json_format_fingerprints_and_summary(self):
        proc = self._run(SEEDED, "--format=json")
        data = json.loads(proc.stdout)
        assert data["summary"]["findings"] == len(data["findings"])
        assert data["summary"]["clean"] is False
        fps = [f["fingerprint"] for f in data["findings"]]
        assert all(fps) and len(fps) == len(set(fps))
        # stable across runs: same tree -> same fingerprints
        again = json.loads(self._run(SEEDED, "--format=json").stdout)
        assert fps == [f["fingerprint"] for f in again["findings"]]

    def test_fingerprint_survives_unrelated_edits(self, tmp_path):
        """The fingerprint keys on (rule, path, normalized line) like
        the baseline does, NOT on line numbers — edits above a finding
        must not re-identify it."""
        from tools.analyze.output import fingerprints

        mod = tmp_path / "mod.py"
        mod.write_text(
            "import time\n\nasync def f():\n    time.sleep(1)\n"
        )
        before = run_paths([str(mod)], baseline_path=None)
        (_, _, fp_before), = fingerprints(
            before.findings, before.project
        )
        mod.write_text(
            "import time\n\n# an unrelated comment\n\n\n"
            "async def f():\n    time.sleep(1)\n"
        )
        after = run_paths([str(mod)], baseline_path=None)
        (f_after, _, fp_after), = fingerprints(
            after.findings, after.project
        )
        assert f_after.line != before.findings[0].line
        assert fp_after == fp_before

    def test_sarif_output(self):
        proc = self._run(SEEDED, "--format=sarif")
        doc = json.loads(proc.stdout)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "ompb-lint"
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {
            "loop-block", "task-hygiene", "bounded-growth",
            "trust-surface", "config-drift", "jax-hotpath",
        } <= rule_ids
        assert run["results"]
        for res in run["results"]:
            assert res["ruleId"] in rule_ids
            loc = res["locations"][0]["physicalLocation"]
            assert loc["artifactLocation"]["uri"].startswith(
                "tests/fixtures/lint/seeded/"
            )
            assert loc["region"]["startLine"] >= 1
            assert res["partialFingerprints"]["ompbLintContext/v1"]

    def test_sarif_clean_run_still_documents_rules(self, tmp_path):
        out = tmp_path / "lint.sarif"
        proc = self._run(CLEAN, "--format=sarif", f"--output={out}")
        assert proc.returncode == 0
        doc = json.loads(out.read_text())
        assert doc["runs"][0]["results"] == []
        assert doc["runs"][0]["tool"]["driver"]["rules"]

    def test_repo_gate(self):
        """Exactly what CI runs."""
        assert self._run().returncode == 0
