"""Distributed cache plane (cache/plane/): the r11 cluster layers.

Covers the four layers end to end:

- **manifest** — disk-tier journal replay (warm restart), torn tails,
  orphan/missing-file reconcile, checksum corruption, compaction;
- **tinylfu** — sketch estimates vs exact counts on a Zipfian trace,
  halving decay, doorkeeper behavior, and an SLRU A/B asserting the
  viewer working set survives a robot sweep only WITH admission;
- **ring** — determinism, balance, consistent-hash stability;
- **l2** — RESP framing round trips against the in-memory stub, TTL,
  and (under ``-m resilience``) fault/timeout/dead-server degradation;
- **cluster** — TWO in-process app replicas on real sockets + the
  RESP stub: render-once cluster-wide with byte-identical ETags,
  cross-process single-flight, the X-OMPB-Peer loop guard, purge
  fan-out, and the chaos contract (dead Redis / dead peer / torn
  journal degrade to single-process behavior; a dead peer never
  blocks a local purge).
"""

import asyncio
import os
import socket
import time

import numpy as np
import pytest
from aiohttp import ClientSession, web

from omero_ms_pixel_buffer_tpu.auth.stores import MemorySessionStore
from omero_ms_pixel_buffer_tpu.cache.plane.l2 import (
    RedisL2Tier,
    decode_entry,
    encode_entry,
)
from omero_ms_pixel_buffer_tpu.cache.plane.manifest import (
    DiskManifest,
    JOURNAL_NAME,
)
from omero_ms_pixel_buffer_tpu.cache.plane.resp_stub import (
    InMemoryRespServer,
)
from omero_ms_pixel_buffer_tpu.cache.plane.ring import HashRing
from omero_ms_pixel_buffer_tpu.cache.plane.tinylfu import TinyLFU
from omero_ms_pixel_buffer_tpu.cache.result_cache import (
    CachedTile,
    DiskTier,
    SegmentedLRU,
    TileResultCache,
)
from omero_ms_pixel_buffer_tpu.http.server import PixelBufferApp
from omero_ms_pixel_buffer_tpu.io.ometiff import write_ome_tiff
from omero_ms_pixel_buffer_tpu.io.pixels_service import (
    ImageRegistry,
    PixelsService,
)
from omero_ms_pixel_buffer_tpu.resilience import faultinject
from omero_ms_pixel_buffer_tpu.resilience.breaker import BOARD
from omero_ms_pixel_buffer_tpu.resilience.faultinject import INJECTOR
from omero_ms_pixel_buffer_tpu.resilience.timeouts import set_io_timeout
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

rng = np.random.default_rng(11)
IMG = rng.integers(0, 60000, (1, 1, 2, 256, 256), dtype=np.uint16)
AUTH = {"Cookie": "sessionid=ck"}


@pytest.fixture(autouse=True)
def _clean_chaos():
    INJECTOR.clear()
    yield
    INJECTOR.clear()
    BOARD.reset()
    set_io_timeout(5.0)


def _entry(body: bytes, filename: str = "f.png") -> CachedTile:
    return CachedTile(body, filename=filename)


# ---------------------------------------------------------------------------
# TinyLFU: sketch, doorkeeper, halving, SLRU A/B
# ---------------------------------------------------------------------------

class TestTinyLFU:
    def test_doorkeeper_absorbs_first_touch(self):
        lfu = TinyLFU(counters=1024)
        assert lfu.estimate("k") == 0
        lfu.record("k")
        # first occurrence lives in the doorkeeper only (membership
        # contributes 1); the sketch is untouched
        assert lfu.estimate("k") == 1
        assert lfu.sketch.estimate(
            __import__(
                "omero_ms_pixel_buffer_tpu.cache.plane.tinylfu",
                fromlist=["_hashes"],
            )._hashes("k")
        ) == 0
        lfu.record("k")
        assert lfu.estimate("k") == 2

    def test_estimates_track_exact_counts_on_zipf_trace(self):
        lfu = TinyLFU(counters=8192, sample_size=10_000_000)  # no aging
        trace_rng = np.random.default_rng(42)
        draws = trace_rng.zipf(1.2, size=20000) % 500
        exact = {}
        for d in draws:
            key = f"tile-{int(d)}"
            exact[key] = exact.get(key, 0) + 1
            lfu.record(key)
        for key, count in exact.items():
            est = lfu.estimate(key)
            # count-min never under-estimates (doorkeeper folds the
            # first touch back in as +1; counters saturate at 15)
            assert est >= min(count, 16), (key, count, est)
            # and over-estimation from collisions stays small at this
            # load factor (500 keys on 8192x4 counters)
            assert est <= min(count, 16) + 3, (key, count, est)

    def test_halving_decays_history(self):
        lfu = TinyLFU(counters=256, sample_size=300)
        for _ in range(40):
            lfu.record("hot")
        sat = lfu.estimate("hot")
        assert sat >= 15
        # push unrelated traffic until the sample period rolls over
        for i in range(300):
            lfu.record(f"noise-{i % 150}")
        assert lfu.resets >= 1
        decayed = lfu.estimate("hot")
        # counters halved and the doorkeeper bit cleared
        assert decayed <= sat // 2 + 1
        assert decayed >= 1  # history decays, it doesn't vanish

    def test_admit_prefers_frequent_victim(self):
        lfu = TinyLFU(counters=1024)
        for _ in range(8):
            lfu.record("viewer")
        lfu.record("robot")
        assert not lfu.admit("robot", "viewer")
        assert lfu.admit("viewer", "robot")
        # ties admit (recency wins — speculative fills survive a cold
        # sketch; see the module docstring)
        assert lfu.admit("fresh-a", "fresh-b")

    def _viewer_hits(self, admission) -> tuple:
        """Mixed workload: 16 viewer tiles looped slowly while a robot
        sweeps thousands of distinct tiles, touching each TWICE in
        quick succession. The double touch defeats plain SLRU's scan
        resistance (sweep keys promote into protected and the churn
        between two touches of one viewer tile exceeds the whole
        byte budget); TinyLFU admission compares frequencies at
        eviction time and refuses to let a twice-seen sweep key
        displace a many-times-seen viewer tile."""
        lru = SegmentedLRU(max_bytes=48, admission=admission)
        viewers = [f"v-{i}" for i in range(16)]
        hits = 0

        def access(key):
            nonlocal hits
            found = lru.get(key) is not None
            if key.startswith("v-") and found:
                hits += 1
            if not found:
                lru.put(key, _entry(b"x"))

        # warm the viewer set (twice: land them in protected — and in
        # the sketch, which sees reads and writes)
        for _ in range(4):
            for v in viewers:
                access(v)
        robot = 0
        for step in range(600):
            access(viewers[step % 16])
            for _ in range(4):  # 4 fresh sweep tiles per viewer touch
                key = f"r-{robot}"
                robot += 1
                access(key)
                access(key)  # the promoting second touch
        return hits, 600

    def test_slru_ab_admission_protects_viewer_set(self):
        plain, touches = self._viewer_hits(admission=None)
        filtered, _ = self._viewer_hits(
            admission=TinyLFU(counters=4096, sample_size=10_000_000)
        )
        # the filter must be a strict, large improvement under this
        # workload: the viewer loop should essentially never miss
        # once the sketch has seen a few loops, while plain SLRU
        # loses the set to the sweep between touches
        assert filtered > plain * 1.5, (plain, filtered)
        assert filtered >= touches * 0.8, (plain, filtered)


# ---------------------------------------------------------------------------
# manifest: journal replay, torn tails, reconcile, compaction
# ---------------------------------------------------------------------------

def _disk_tier(tmp_path, max_bytes=1 << 20):
    d = str(tmp_path)
    return DiskTier(d, max_bytes, manifest=DiskManifest(d))


class TestManifest:
    def test_warm_restart_replays_entries(self, tmp_path):
        tier = _disk_tier(tmp_path)
        bodies = {}
        for i in range(5):
            body = f"tile-{i}".encode() * 10
            entry = _entry(body, filename=f"t{i}.png")
            tier.put(f"img={i}|z=0", entry)
            bodies[f"img={i}|z=0"] = (body, entry.etag)
        tier.manifest.close()

        reborn = _disk_tier(tmp_path)
        assert len(reborn) == 5
        for key, (body, etag) in bodies.items():
            got = reborn.get(key)
            assert got is not None
            assert got.body == body
            assert got.etag == etag  # validators survive the restart
            assert got.filename.endswith(".png")

    def test_evictions_replay(self, tmp_path):
        tier = _disk_tier(tmp_path)
        for i in range(5):
            tier.put(f"img={i}|z=0", _entry(b"x" * 50))
        tier.remove("img=1|z=0")
        tier.remove("img=3|z=0")
        tier.manifest.close()
        reborn = _disk_tier(tmp_path)
        assert len(reborn) == 3
        assert reborn.get("img=1|z=0") is None
        assert reborn.get("img=0|z=0") is not None

    @pytest.mark.resilience
    def test_torn_tail_tolerated(self, tmp_path):
        tier = _disk_tier(tmp_path)
        for i in range(4):
            tier.put(f"img={i}|z=0", _entry(b"y" * 30))
        tier.manifest.close()
        journal = tmp_path / JOURNAL_NAME
        with open(journal, "ab") as fh:
            fh.write(b'deadbeef {"op":"admit","key":"img=9')  # torn
        reborn = _disk_tier(tmp_path)
        assert reborn.manifest.torn
        assert len(reborn) == 4  # everything before the tear survives
        # and the journal was truncated + compacted: a THIRD boot is
        # clean
        reborn.manifest.close()
        third = _disk_tier(tmp_path)
        assert not third.manifest.torn
        assert len(third) == 4

    @pytest.mark.resilience
    def test_corrupt_record_reads_as_tail(self, tmp_path):
        tier = _disk_tier(tmp_path)
        for i in range(5):
            tier.put(f"img={i}|z=0", _entry(b"z" * 20))
        tier.manifest.close()
        journal = tmp_path / JOURNAL_NAME
        lines = journal.read_bytes().splitlines(keepends=True)
        lines[2] = b"ffffffff" + lines[2][8:]  # break line 3's crc
        journal.write_bytes(b"".join(lines))
        reborn = _disk_tier(tmp_path)
        # replay stops at the corrupt record; the two intact prefix
        # entries survive, the rest reconcile away as orphans
        assert len(reborn) == 2
        assert reborn.get("img=0|z=0") is not None
        assert reborn.get("img=3|z=0") is None
        leftovers = [
            f for f in os.listdir(tmp_path) if f.endswith(".tile")
        ]
        assert len(leftovers) == 2  # orphan data files removed

    def test_orphan_files_removed(self, tmp_path):
        tier = _disk_tier(tmp_path)
        tier.put("img=1|z=0", _entry(b"a" * 10))
        tier.manifest.close()
        (tmp_path / "feedface.tile").write_bytes(b"stray")
        (tmp_path / "feedface.tile.tmp").write_bytes(b"stray")
        reborn = _disk_tier(tmp_path)
        assert reborn.manifest.orphans_removed >= 2
        names = set(os.listdir(tmp_path))
        assert "feedface.tile" not in names
        assert "feedface.tile.tmp" not in names

    def test_missing_file_drops_entry(self, tmp_path):
        tier = _disk_tier(tmp_path)
        tier.put("img=1|z=0", _entry(b"a" * 10))
        tier.put("img=2|z=0", _entry(b"b" * 10))
        victim = os.path.join(str(tmp_path), tier._fname("img=1|z=0"))
        tier.manifest.close()
        os.unlink(victim)
        reborn = _disk_tier(tmp_path)
        assert len(reborn) == 1
        assert reborn.manifest.dropped_missing == 1
        assert reborn.get("img=2|z=0") is not None

    def test_compaction_bounds_journal(self, tmp_path):
        d = str(tmp_path)
        tier = DiskTier(
            d, 1 << 20, manifest=DiskManifest(d, compact_bytes=2048)
        )
        for round_ in range(40):
            for i in range(6):
                tier.put(f"img={i}|r={round_}", _entry(b"c" * 10))
            for i in range(6):
                tier.remove(f"img={i}|r={round_}")
        tier.put("img=keep|z=0", _entry(b"k" * 10))
        size = os.path.getsize(tmp_path / JOURNAL_NAME)
        assert size < 8192  # ~40x6 admit+evict pairs would be >40 KiB
        tier.manifest.close()
        reborn = _disk_tier(tmp_path)
        assert len(reborn) == 1
        assert reborn.get("img=keep|z=0").body == b"k" * 10

    async def test_result_cache_restart_is_warm(self, tmp_path):
        """The integration shape of the acceptance criterion: spill
        through the real TileResultCache, close, reopen, hit."""
        disk = str(tmp_path / "spill")
        cache = TileResultCache(
            memory_bytes=256, disk_dir=disk, disk_bytes=1 << 20,
        )
        # entries larger than the RAM budget spill on displacement
        for i in range(4):
            await cache.put(f"img={i}|z=0|q=s", _entry(b"B" * 200))
        cache._io.submit(lambda: None).result()  # drain the spill
        cache.close()

        reborn = TileResultCache(
            memory_bytes=256, disk_dir=disk, disk_bytes=1 << 20,
        )
        try:
            hits = 0
            for i in range(4):
                if await reborn.get(f"img={i}|z=0|q=s") is not None:
                    hits += 1
            assert hits >= 3  # warm: at worst the last unspilled entry
        finally:
            reborn.close()

    async def test_disk_hit_rejected_by_admission_not_respilled(
        self, tmp_path
    ):
        """A disk hit the TinyLFU gate refuses to re-admit to RAM must
        NOT rewrite its (identical) bytes + journal record on every
        read — the file is already on disk."""
        lfu = TinyLFU(counters=1024, sample_size=10_000_000)
        cache = TileResultCache(
            memory_bytes=400, disk_dir=str(tmp_path / "s"),
            admission=lfu,
        )
        try:
            for i in range(4):  # hot set fills RAM exactly
                for _ in range(10):
                    lfu.record(f"hot{i}")
                await cache.put(f"hot{i}", _entry(b"H" * 100))
            cache.disk.put("cold", CachedTile(b"C" * 100))
            jb0 = cache.disk.manifest._journal_bytes
            for _ in range(3):
                got = await cache.get("cold")
                assert got is not None and got.body == b"C" * 100
                # the hot set kept its RAM residency
                assert cache.memory.peek("hot0") is not None
            cache._io.submit(lambda: None).result()
            assert cache.disk.manifest._journal_bytes == jb0
        finally:
            cache.close()

    async def test_manifest_off_restores_cold_sweep(self, tmp_path):
        disk = str(tmp_path / "spill")
        cache = TileResultCache(
            memory_bytes=256, disk_dir=disk, disk_bytes=1 << 20,
            manifest=False,
        )
        for i in range(4):
            await cache.put(f"img={i}|z=0|q=s", _entry(b"B" * 200))
        cache._io.submit(lambda: None).result()
        cache.close()
        reborn = TileResultCache(
            memory_bytes=256, disk_dir=disk, disk_bytes=1 << 20,
            manifest=False,
        )
        try:
            for i in range(4):
                assert await reborn.get(f"img={i}|z=0|q=s") is None
            assert not any(
                f.endswith(".tile") for f in os.listdir(disk)
            )
        finally:
            reborn.close()


# ---------------------------------------------------------------------------
# consistent-hash ring
# ---------------------------------------------------------------------------

class TestHashRing:
    MEMBERS = [
        "http://replica-a:8082",
        "http://replica-b:8082",
        "http://replica-c:8082",
    ]

    def test_deterministic_across_instances(self):
        r1 = HashRing(self.MEMBERS, virtual_nodes=64)
        r2 = HashRing(list(self.MEMBERS), virtual_nodes=64)
        for i in range(200):
            key = f"img={i}|z=0|c=0|t=0"
            assert r1.owner(key) == r2.owner(key)

    def test_balance(self):
        ring = HashRing(self.MEMBERS, virtual_nodes=64)
        counts = {m: 0 for m in self.MEMBERS}
        for i in range(3000):
            counts[ring.owner(f"img={i}|z={i % 7}")] += 1
        for member, n in counts.items():
            assert n > 3000 * 0.15, counts  # no starved member

    def test_consistency_on_member_removal(self):
        full = HashRing(self.MEMBERS, virtual_nodes=64)
        reduced = HashRing(self.MEMBERS[:2], virtual_nodes=64)
        moved = stayed = 0
        for i in range(2000):
            key = f"img={i}|z=0"
            before = full.owner(key)
            after = reduced.owner(key)
            if before == self.MEMBERS[2]:
                continue  # the removed member's keys must remap
            if before == after:
                stayed += 1
            else:
                moved += 1
        assert moved == 0  # survivors keep every key they owned
        assert stayed > 0

    def test_duplicate_members_rejected(self):
        with pytest.raises(ValueError):
            HashRing(["http://a", "http://a"])


# ---------------------------------------------------------------------------
# L2 tier against the RESP stub
# ---------------------------------------------------------------------------

class TestL2Tier:
    def test_value_framing_round_trip(self):
        entry = CachedTile(b"PNG-BYTES", filename="tile.png")
        decoded = decode_entry(encode_entry(entry))
        assert decoded.body == entry.body
        assert decoded.etag == entry.etag
        assert decoded.filename == "tile.png"
        assert decode_entry(b"garbage") is None
        assert decode_entry(b"OMPB1\xff\xff\xff\xffrest") is None

    async def test_put_get_delete_against_stub(self):
        server = InMemoryRespServer()
        await server.start()
        tier = RedisL2Tier(server.uri)
        try:
            entry = _entry(b"tile-bytes", filename="t.png")
            assert await tier.put("img=1|z=0|q=s", entry)
            got = await tier.get("img=1|z=0|q=s")
            assert got.body == b"tile-bytes"
            assert got.etag == entry.etag
            assert await tier.get("img=1|z=9|q=s") is None
            # image-scoped purge removes only that image's keys
            await tier.put("img=2|z=0|q=s", _entry(b"other"))
            removed = await tier.delete_image(1)
            assert removed == 1
            assert await tier.get("img=1|z=0|q=s") is None
            assert (await tier.get("img=2|z=0|q=s")).body == b"other"
        finally:
            await tier.close()
            await server.close()

    async def test_ttl_expires(self):
        server = InMemoryRespServer()
        await server.start()
        tier = RedisL2Tier(server.uri, ttl_s=0.05)
        try:
            await tier.put("img=1|z=0", _entry(b"x"))
            assert (await tier.get("img=1|z=0")) is not None
            await asyncio.sleep(0.08)
            assert await tier.get("img=1|z=0") is None
        finally:
            await tier.close()
            await server.close()

    @pytest.mark.resilience
    async def test_dead_server_degrades_and_opens_breaker(self):
        # grab a port nothing listens on
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        tier = RedisL2Tier(f"redis://127.0.0.1:{port}/0")
        for _ in range(6):
            assert await tier.get("img=1|z=0") is None  # never raises
        assert tier.breaker.state == "open"
        # breaker-open short-circuits without touching the socket
        assert await tier.get("img=1|z=0") is None
        assert not await tier.put("img=1|z=0", _entry(b"x"))
        await tier.close()

    @pytest.mark.resilience
    async def test_fault_point_degrades(self):
        server = InMemoryRespServer()
        await server.start()
        tier = RedisL2Tier(server.uri)
        try:
            await tier.put("img=1|z=0", _entry(b"x"))
            INJECTOR.install(
                "cache.l2", faultinject.always(ConnectionError("chaos"))
            )
            assert await tier.get("img=1|z=0") is None
            INJECTOR.clear()
            assert (await tier.get("img=1|z=0")).body == b"x"
        finally:
            await tier.close()
            await server.close()

    @pytest.mark.resilience
    async def test_hung_server_bounded_by_io_timeout(self):
        server = InMemoryRespServer()
        await server.start()
        server.fail_mode = "hang"
        set_io_timeout(0.1)
        tier = RedisL2Tier(server.uri)
        try:
            t0 = time.monotonic()
            assert await tier.get("img=1|z=0") is None
            assert time.monotonic() - t0 < 1.0
        finally:
            await tier.close()
            await server.close()


# ---------------------------------------------------------------------------
# two-replica cluster over real sockets
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Replica:
    def __init__(self, app_obj, url, runner):
        self.app = app_obj
        self.url = url
        self.runner = runner
        self.renders = []

    def count_renders(self):
        inner_handle = self.app.pipeline.handle
        inner_batch = self.app.pipeline.handle_batch

        def handle(ctx):
            self.renders.append(1)
            return inner_handle(ctx)

        def handle_batch(ctxs):
            self.renders.extend([1] * len(ctxs))
            return inner_batch(ctxs)

        self.app.pipeline.handle = handle
        self.app.pipeline.handle_batch = handle_batch


async def _make_cluster(
    tmp_path, n=2, l2=True, dead_members=(), peer_timeout_ms=2000,
    cache_overrides=None,
):
    """Boot ``n`` real replicas (aiohttp TCPSite on loopback) sharing
    one image fixture and, optionally, one RESP stub; ``dead_members``
    adds ring members nobody listens on."""
    img_path = str(tmp_path / "img.ome.tiff")
    write_ome_tiff(img_path, IMG, tile_size=(64, 64), pyramid_levels=2)
    resp = None
    l2_block = {}
    if l2:
        resp = InMemoryRespServer()
        await resp.start()
        l2_block = {"l2": {"uri": resp.uri}}
    ports = [_free_port() for _ in range(n)]
    members = [f"http://127.0.0.1:{p}" for p in ports] + list(
        dead_members
    )
    replicas = []
    for i, port in enumerate(ports):
        registry = ImageRegistry()
        registry.add(1, img_path)
        config = Config.from_dict({
            "session-store": {"type": "memory"},
            "backend": {"batching": {"coalesce-window-ms": 1.0}},
            # prefetch off: speculative warming renders tiles beyond
            # the scripted workload, which would blur the render-once
            # accounting these tests pin
            "cache": {
                "prefetch": {"enabled": False},
                **(cache_overrides or {}),
            },
            "cluster": {
                "members": members,
                "self": members[i],
                "peer-timeout-ms": peer_timeout_ms,
                **l2_block,
            },
        })
        app_obj = PixelBufferApp(
            config,
            pixels_service=PixelsService(registry),
            session_store=MemorySessionStore({"ck": "omero-key-1"}),
        )
        runner = web.AppRunner(app_obj.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", port)
        await site.start()
        replica = _Replica(app_obj, members[i], runner)
        replica.count_renders()
        replicas.append(replica)

    async def cleanup():
        for r in replicas:
            await r.runner.cleanup()
        if resp is not None:
            await resp.close()

    return replicas, resp, cleanup


def _tile_paths(n):
    return [
        f"/tile/1/0/0/0?x={64 * (i % 4)}&y={64 * (i // 4)}&w=64&h=64"
        f"&format=png"
        for i in range(n)
    ]


class TestClusterServing:
    @pytest.mark.resilience
    async def test_render_once_and_identical_etags(self, tmp_path):
        """The acceptance pin: a shared workload over two replicas
        renders each unique tile exactly once cluster-wide, and both
        replicas answer with byte-identical bodies and ETags."""
        replicas, resp, cleanup = await _make_cluster(tmp_path, n=2)
        try:
            paths = _tile_paths(8)
            seen = {}
            async with ClientSession() as http:
                for i, path in enumerate(paths):
                    first = replicas[i % 2]
                    second = replicas[(i + 1) % 2]
                    async with http.get(
                        first.url + path, headers=AUTH
                    ) as r1:
                        assert r1.status == 200
                        body1 = await r1.read()
                        etag1 = r1.headers["ETag"]
                    async with http.get(
                        second.url + path, headers=AUTH
                    ) as r2:
                        assert r2.status == 200
                        body2 = await r2.read()
                        etag2 = r2.headers["ETag"]
                        assert r2.headers["X-Cache"] in (
                            "l2-hit", "peer-hit", "hit"
                        )
                    assert body1 == body2
                    assert etag1 == etag2
                    seen[path] = etag1
            total = sum(len(r.renders) for r in replicas)
            assert total == len(paths)  # rendered ONCE cluster-wide
            assert len(set(seen.values())) == len(paths)
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_cross_process_single_flight(self, tmp_path):
        """Concurrent cold misses for ONE tile on BOTH replicas: the
        non-owner peer-fetches the owner, joins the owner's local
        flight, and the cluster renders once."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2, l2=False
        )
        try:
            for r in replicas:
                inner = r.app.pipeline.handle_batch

                def slow_batch(ctxs, _inner=inner, _r=r):
                    time.sleep(0.05)  # hold the flight open
                    return _inner(ctxs)

                r.app.pipeline.handle_batch = slow_batch
            path = _tile_paths(1)[0]
            async with ClientSession() as http:
                async def fetch(url):
                    async with http.get(url + path, headers=AUTH) as r:
                        return r.status, await r.read(), (
                            r.headers["ETag"]
                        )

                results = await asyncio.gather(*(
                    fetch(replicas[i % 2].url) for i in range(6)
                ))
            assert all(s == 200 for s, _b, _e in results)
            assert len({b for _s, b, _e in results}) == 1
            assert len({e for _s, _b, e in results}) == 1
            total = sum(len(r.renders) for r in replicas)
            assert total == 1, total
        finally:
            await cleanup()

    async def test_peer_header_is_terminal(self, tmp_path):
        """The X-OMPB-Peer loop guard: a request carrying the header
        renders locally even when the ring says another member owns
        the key — forwarding is one hop, never a loop."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2, l2=False
        )
        try:
            paths = _tile_paths(8)
            async with ClientSession() as http:
                target = replicas[0]
                for path in paths:
                    async with http.get(
                        target.url + path,
                        headers={**AUTH, "X-OMPB-Peer": "test-origin"},
                    ) as r:
                        assert r.status == 200
            # every tile rendered by the targeted replica itself;
            # the other replica saw nothing
            assert len(replicas[0].renders) == len(paths)
            assert len(replicas[1].renders) == 0
        finally:
            await cleanup()

    async def test_healthz_reports_plane(self, tmp_path):
        replicas, resp, cleanup = await _make_cluster(tmp_path, n=2)
        try:
            async with ClientSession() as http:
                async with http.get(
                    replicas[0].url + "/healthz"
                ) as r:
                    health = await r.json()
            plane = health["cache"]["plane"]
            assert plane["self"] == replicas[0].url
            assert len(plane["ring"]["members"]) == 2
            assert "l2" in plane
            assert "manifest" not in health["cache"].get("disk", {})
        finally:
            await cleanup()


class TestClusterChaos:
    @pytest.mark.resilience
    async def test_dead_redis_degrades_to_local(self, tmp_path):
        """Killing Redis mid-run: requests keep succeeding (rendered
        locally), the l2 breaker opens, and X-Cache provenance shows
        plain misses/hits — today's single-process behavior."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=1, l2=True
        )
        try:
            path = _tile_paths(1)[0]
            async with ClientSession() as http:
                async with http.get(
                    replicas[0].url + path, headers=AUTH
                ) as r:
                    assert r.status == 200
                await resp.close()  # Redis dies
                for i in range(8):
                    async with http.get(
                        replicas[0].url + _tile_paths(8)[i],
                        headers=AUTH,
                    ) as r:
                        assert r.status == 200
                        assert r.headers["X-Cache"] in ("miss", "hit")
            board = BOARD.snapshot()
            assert board.get("cache:l2", {}).get("state") in (
                "open", "half_open", "closed",
            )
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_dead_peer_renders_locally(self, tmp_path):
        """A ring member nobody runs: tiles it owns are peer-fetch
        misses and render locally — no request fails, latency bounded
        by the peer timeout."""
        dead = f"http://127.0.0.1:{_free_port()}"
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=1, l2=False, dead_members=[dead],
            peer_timeout_ms=200,
        )
        try:
            paths = _tile_paths(8)
            async with ClientSession() as http:
                for path in paths:
                    async with http.get(
                        replicas[0].url + path, headers=AUTH
                    ) as r:
                        assert r.status == 200
                        assert r.headers["X-Cache"] == "miss"
            assert len(replicas[0].renders) == len(paths)
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_peer_fault_point_degrades(self, tmp_path):
        INJECTOR.install(
            "cache.peer", faultinject.always(ConnectionError("chaos"))
        )
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2, l2=False
        )
        try:
            paths = _tile_paths(6)
            async with ClientSession() as http:
                for i, path in enumerate(paths):
                    async with http.get(
                        replicas[i % 2].url + path, headers=AUTH
                    ) as r:
                        assert r.status == 200
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_torn_journal_still_boots_warm_prefix(self, tmp_path):
        """A journal torn mid-run degrades the RESTART to (at worst)
        a colder cache — the app boots and serves either way."""
        disk = str(tmp_path / "spill")
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=1, l2=False,
            cache_overrides={"disk-dir": disk, "memory-mb": 1},
        )
        try:
            async with ClientSession() as http:
                for path in _tile_paths(4):
                    async with http.get(
                        replicas[0].url + path, headers=AUTH
                    ) as r:
                        assert r.status == 200
        finally:
            await cleanup()
        with open(os.path.join(disk, JOURNAL_NAME), "ab") as fh:
            fh.write(b"xxxx torn")
        cache = TileResultCache(
            memory_bytes=1 << 20, disk_dir=disk, disk_bytes=1 << 30
        )
        try:
            assert cache.disk is not None  # boots despite the tear
        finally:
            cache.close()

    @pytest.mark.resilience
    async def test_purge_fan_out_and_dead_peer_never_blocks(
        self, tmp_path
    ):
        """The invalidation satellite: a purge clears the local tiers
        IMMEDIATELY and fans out to L2 + peers best-effort; a dead
        peer in the member list cannot delay or fail the local purge."""
        dead = f"http://127.0.0.1:{_free_port()}"
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=2, l2=True, dead_members=[dead],
            peer_timeout_ms=200,
        )
        try:
            path = _tile_paths(1)[0]
            async with ClientSession() as http:
                # warm both replicas + L2
                for r in replicas:
                    async with http.get(
                        r.url + path, headers=AUTH
                    ) as resp_:
                        assert resp_.status == 200
                await asyncio.sleep(0.05)  # let the L2 publish land
                assert any(
                    k.startswith(b"ompb:tile:img=1|")
                    for k in resp.live_keys()
                )
                # purge from replica 0 (the resolver-thread entry
                # point); the local purge must return promptly
                t0 = time.monotonic()
                replicas[0].app._invalidate_image(1)
                local_purge_s = time.monotonic() - t0
                assert local_purge_s < 0.15  # dead peer didn't block
                assert len(replicas[0].app.result_cache.memory) == 0
                # the fan-out drains in the background: L2 keys go and
                # the live peer's local cache empties
                for _ in range(40):
                    l2_clear = not any(
                        k.startswith(b"ompb:tile:img=1|")
                        for k in resp.live_keys()
                    )
                    peer_clear = (
                        len(replicas[1].app.result_cache.memory) == 0
                    )
                    if l2_clear and peer_clear:
                        break
                    await asyncio.sleep(0.05)
                assert l2_clear and peer_clear
                # and the tile re-renders fresh afterwards
                async with http.get(
                    replicas[0].url + path, headers=AUTH
                ) as r2:
                    assert r2.status == 200
        finally:
            await cleanup()

    async def test_internal_purge_requires_peer_header(self, tmp_path):
        replicas, resp, cleanup = await _make_cluster(tmp_path, n=1)
        try:
            async with ClientSession() as http:
                async with http.post(
                    replicas[0].url + "/internal/purge/1"
                ) as r:
                    assert r.status == 403
                async with http.post(
                    replicas[0].url + "/internal/purge/1",
                    headers={"X-OMPB-Peer": "tester"},
                ) as r:
                    assert r.status == 200
        finally:
            await cleanup()


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------

class TestClusterConfig:
    BASE = {"session-store": {"type": "memory"}}

    def test_self_must_be_member(self):
        with pytest.raises(ConfigError):
            Config.from_dict({
                **self.BASE,
                "cluster": {
                    "members": ["http://a:1"], "self": "http://b:2",
                },
            })

    def test_members_require_self(self):
        with pytest.raises(ConfigError):
            Config.from_dict({
                **self.BASE, "cluster": {"members": ["http://a:1"]},
            })

    def test_unknown_keys_rejected(self):
        with pytest.raises(ConfigError):
            Config.from_dict({
                **self.BASE, "cluster": {"membres": ["http://a:1"]},
            })
        with pytest.raises(ConfigError):
            Config.from_dict({
                **self.BASE,
                "cluster": {"l2": {"url": "redis://x"}},
            })
        with pytest.raises(ConfigError):
            Config.from_dict({
                **self.BASE, "cache": {"tinylfu": {"counter": 5}},
            })

    def test_l2_only_cluster_is_valid(self):
        config = Config.from_dict({
            **self.BASE,
            "cluster": {"l2": {"uri": "redis://localhost:6379/2"}},
        })
        assert config.cluster.plane_enabled
        assert config.cluster.members == ()

    def test_trailing_slashes_normalized(self):
        config = Config.from_dict({
            **self.BASE,
            "cluster": {
                "members": ["http://a:1/", "http://b:2"],
                "self": "http://a:1",
            },
        })
        assert config.cluster.members == ("http://a:1", "http://b:2")
        assert config.cluster.self_url == "http://a:1"

    def test_empty_block_disables_plane(self):
        config = Config.from_dict(self.BASE)
        assert not config.cluster.plane_enabled
