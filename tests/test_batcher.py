"""Batch pipelining: up to ``workers`` coalesced batches execute
concurrently on the executor (the reference's worker_pool_size knob,
PixelBufferMicroserviceVerticle.java:117-118,224-233), while ordering
of per-request results and failure isolation across batches hold."""

import asyncio
import threading

from omero_ms_pixel_buffer_tpu.auth.omero_session import AllowListValidator
from omero_ms_pixel_buffer_tpu.dispatch.batcher import BatchingTileWorker
from omero_ms_pixel_buffer_tpu.errors import TileError
from omero_ms_pixel_buffer_tpu.tile_ctx import RegionDef, TileCtx


def _ctx(image_id=1, z=0):
    return TileCtx(
        image_id=image_id, z=z, c=0, t=0,
        region=RegionDef(0, 0, 8, 8), format=None,
        omero_session_key="k",
    )


class GatedPipeline:
    """handle() blocks until ``release`` is set; records the maximum
    number of threads inside handle() at once."""

    def __init__(self):
        self.release = threading.Event()
        self._lock = threading.Lock()
        self.active = 0
        self.max_active = 0

    def _enter(self):
        with self._lock:
            self.active += 1
            self.max_active = max(self.max_active, self.active)

    def _exit(self):
        with self._lock:
            self.active -= 1

    def handle(self, ctx):
        self._enter()
        try:
            assert self.release.wait(10)
            return b"tile-%d-%d" % (ctx.image_id, ctx.z)
        finally:
            self._exit()

    def handle_batch(self, ctxs):
        return [self.handle(c) for c in ctxs]


async def _submit(worker, ctxs):
    await worker.start()
    return await asyncio.gather(
        *[worker.handle(c) for c in ctxs], return_exceptions=True
    )


def test_batches_overlap_with_two_workers(loop):
    """Two single-lane batches must be in the executor simultaneously
    when workers=2 (batch N+1 no longer serializes behind batch N)."""
    pipe = GatedPipeline()
    worker = BatchingTileWorker(
        pipe, AllowListValidator(), max_batch=1,
        coalesce_window_ms=0, workers=2,
    )

    async def run():
        task = asyncio.ensure_future(
            _submit(worker, [_ctx(z=0), _ctx(z=1)])
        )
        # wait (event-loop friendly) until both batches entered handle()
        for _ in range(200):
            if pipe.active >= 2:
                break
            await asyncio.sleep(0.02)
        assert pipe.active == 2, "second batch did not overlap the first"
        pipe.release.set()
        results = await asyncio.wait_for(task, 10)
        assert sorted(r[0] for r in results) == [b"tile-1-0", b"tile-1-1"]
        await worker.close()

    loop.run_until_complete(run())
    assert pipe.max_active == 2


def test_single_worker_serializes(loop):
    """workers=1 preserves the strict one-batch-at-a-time behavior."""
    pipe = GatedPipeline()
    pipe.release.set()  # no gating; just count concurrency
    worker = BatchingTileWorker(
        pipe, AllowListValidator(), max_batch=1,
        coalesce_window_ms=0, workers=1,
    )

    async def run():
        results = await _submit(worker, [_ctx(z=i) for i in range(8)])
        assert [r[0] for r in results] == [
            b"tile-1-%d" % i for i in range(8)
        ]
        await worker.close()

    loop.run_until_complete(run())
    assert pipe.max_active == 1


def test_failure_isolated_to_its_batch(loop):
    """A batch whose pipeline call raises fails only its own lanes;
    concurrent batches still serve."""

    class HalfBroken(GatedPipeline):
        def handle(self, ctx):
            if ctx.image_id == 666:
                raise RuntimeError("boom")
            return super().handle(ctx)

    pipe = HalfBroken()
    pipe.release.set()
    worker = BatchingTileWorker(
        pipe, AllowListValidator(), max_batch=1,
        coalesce_window_ms=0, workers=4,
    )

    async def run():
        results = await _submit(worker, [_ctx(1), _ctx(666), _ctx(3)])
        ok = [r for r in results if not isinstance(r, Exception)]
        bad = [r for r in results if isinstance(r, Exception)]
        assert len(ok) == 2 and len(bad) == 1
        assert isinstance(bad[0], TileError) and bad[0].code == 500
        await worker.close()

    loop.run_until_complete(run())


def test_close_fails_pending_cleanly(loop):
    """close() mid-flight resolves every outstanding future (executor
    batches finish; queued/coalescing lanes get 500s) — nothing hangs
    to the bus timeout."""
    pipe = GatedPipeline()
    worker = BatchingTileWorker(
        pipe, AllowListValidator(), max_batch=1,
        coalesce_window_ms=0, workers=1,
    )

    async def run():
        task = asyncio.ensure_future(
            _submit(worker, [_ctx(z=i) for i in range(4)])
        )
        for _ in range(200):
            if pipe.active >= 1:
                break
            await asyncio.sleep(0.02)
        pipe.release.set()
        await worker.close()
        results = await asyncio.wait_for(task, 10)
        assert all(
            isinstance(r, (tuple, TileError)) for r in results
        ), results

    loop.run_until_complete(run())


def test_default_workers_is_twice_cpus():
    import os

    w = BatchingTileWorker(GatedPipeline(), AllowListValidator())
    assert w.workers == max(1, 2 * (os.cpu_count() or 1))
