"""TileCtx parse/default semantics (reference: TileCtx.java:67-90) and
error taxonomy (PixelBufferVerticle.java:90-147,
PixelBufferMicroserviceVerticle.java:356-370)."""

import pytest

from omero_ms_pixel_buffer_tpu.errors import (
    BadRequestError,
    InternalError,
    NotFoundError,
    PermissionDeniedError,
    TileError,
    http_status_for_failure,
)
from omero_ms_pixel_buffer_tpu.tile_ctx import TileCtx


def params(**kw):
    base = {"imageId": "1", "z": "0", "c": "0", "t": "0"}
    base.update({k: str(v) for k, v in kw.items()})
    return base


class TestParse:
    def test_required_path_params(self):
        ctx = TileCtx.from_params(params(), "key")
        assert (ctx.image_id, ctx.z, ctx.c, ctx.t) == (1, 0, 0, 0)
        assert ctx.omero_session_key == "key"

    def test_region_defaults_to_zero(self):
        ctx = TileCtx.from_params(params(), None)
        r = ctx.region
        assert (r.x, r.y, r.width, r.height) == (0, 0, 0, 0)

    def test_region_parsed(self):
        ctx = TileCtx.from_params(params(x=10, y=20, w=512, h=256), None)
        r = ctx.region
        assert (r.x, r.y, r.width, r.height) == (10, 20, 512, 256)

    def test_resolution_defaults_none(self):
        assert TileCtx.from_params(params(), None).resolution is None
        assert TileCtx.from_params(params(resolution=2), None).resolution == 2

    def test_format_passthrough(self):
        assert TileCtx.from_params(params(), None).format is None
        assert TileCtx.from_params(params(format="png"), None).format == "png"
        # unknown formats parse fine; rejection happens in the pipeline
        assert TileCtx.from_params(params(format="bmp"), None).format == "bmp"

    @pytest.mark.parametrize("key", ["imageId", "z", "c", "t"])
    def test_missing_required_is_400(self, key):
        p = params()
        del p[key]
        with pytest.raises(BadRequestError) as ei:
            TileCtx.from_params(p, None)
        assert ei.value.code == 400

    @pytest.mark.parametrize(
        "bad", [{"imageId": "abc"}, {"z": "1.5"}, {"x": "NaNpx"}, {"resolution": ""}]
    )
    def test_unparseable_is_400(self, bad):
        with pytest.raises(BadRequestError):
            TileCtx.from_params(params(**bad), None)


class TestRoundTrip:
    def test_json_round_trip(self):
        ctx = TileCtx.from_params(
            params(x=1, y=2, w=3, h=4, resolution=1, format="tif"), "sk"
        )
        ctx.trace_context = {"traceId": "abc"}
        back = TileCtx.from_json(ctx.to_json())
        assert back == ctx

    def test_garbage_json_is_400_illegal_tile_context(self):
        with pytest.raises(BadRequestError) as ei:
            TileCtx.from_json({"imageId": "x"})
        assert ei.value.message == "Illegal tile context"


class TestFilename:
    def test_format_extension(self):
        ctx = TileCtx.from_params(params(x=5, y=6, w=7, h=8, format="png"), None)
        assert ctx.filename() == "image1_z0_c0_t0_x5_y6_w7_h8.png"

    def test_default_bin_extension(self):
        ctx = TileCtx.from_params(params(), None)
        assert ctx.filename() == "image1_z0_c0_t0_x0_y0_w0_h0.bin"


class TestErrorMapping:
    def test_codes(self):
        assert BadRequestError("x").code == 400
        assert PermissionDeniedError().code == 403
        assert PermissionDeniedError().message == "Permission denied"
        assert NotFoundError("Cannot find Image:5").code == 404
        assert InternalError().code == 500
        assert InternalError().message == "Exception while retrieving tile"

    def test_http_status_for_failure(self):
        assert http_status_for_failure(NotFoundError("x")) == 404
        assert http_status_for_failure(TileError(0, "bad")) == 500  # code < 1
        assert http_status_for_failure(RuntimeError("x")) == 404  # non-reply
