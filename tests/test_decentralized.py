"""Decentralized control plane (r20): gossip membership, sealed
coordination writes, end-to-end byte integrity, disk-tier
anti-entropy, and the shard-index TTL.

Unit lanes: seal/unseal (HMAC-sealed Redis values), GossipManager
merge semantics (heartbeat precedence, flag OR, tombstones, SWIM
self-refutation, direct-contact refutation, stall expiry, bounded
state, epoch + brain piggyback, rotation coverage, the Redis
join-bootstrap hint), body_matches + CorruptionLedger + the suspicion
corruption clause, L2 integrity verification against the RESP stub,
warm-set digests over the disk manifest, the Zarr v3 shard-index TTL
+ purge, and config validation for the new blocks.

Chaos lanes (``-m resilience``): a three-replica gossip fleet whose
Redis dies mid-traffic (ring stays converged, epoch bumps still
disseminate, zero 5xx) and a corrupt-peer drive (one replica serves
bit-flipped bodies with intact ETags; integrity verdicts feed the
suspicion quorum until it is demoted, and every client request still
receives correct bytes).
"""

import asyncio
import json
import os

import numpy as np
import pytest
from aiohttp import ClientSession

from omero_ms_pixel_buffer_tpu.cache.plane.l2 import RedisL2Tier
from omero_ms_pixel_buffer_tpu.cache.plane.resp_stub import (
    InMemoryRespServer,
)
from omero_ms_pixel_buffer_tpu.cache.result_cache import (
    CachedTile,
    TileResultCache,
    make_etag,
)
from omero_ms_pixel_buffer_tpu.cluster import (
    CorruptionLedger,
    EpochRegistry,
    GossipManager,
    SuspicionPolicy,
    body_matches,
    seal,
    unseal,
)
from omero_ms_pixel_buffer_tpu.cluster.gossip import _MAX_ENTRIES
from omero_ms_pixel_buffer_tpu.cluster.membership import MEMBER_PREFIX
from omero_ms_pixel_buffer_tpu.io import zarr as zarr_mod
from omero_ms_pixel_buffer_tpu.io.pixels_service import PixelsService
from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

from test_cluster import (
    _get,
    _key_for,
    _make_cluster,
    _tile_paths,
)

A, B, C = "http://a:1", "http://b:2", "http://c:3"


# ---------------------------------------------------------------------------
# sealed coordination values
# ---------------------------------------------------------------------------

class TestSealUnseal:
    def test_round_trip(self):
        raw = seal("s3cret", b'{"url":"http://a:1"}')
        assert unseal("s3cret", raw) == b'{"url":"http://a:1"}'

    def test_no_secret_passthrough(self):
        assert seal("", b"payload") == b"payload"
        assert unseal("", b"payload") == b"payload"

    def test_tampered_payload_rejected(self):
        raw = bytearray(seal("s3cret", b"payload"))
        raw[-1] ^= 0x01
        assert unseal("s3cret", bytes(raw)) is None

    def test_wrong_secret_rejected(self):
        assert unseal("other", seal("s3cret", b"p")) is None

    def test_unsealed_value_rejected_when_secret_set(self):
        # a bare (attacker-written) value never passes a sealed read
        assert unseal("s3cret", b'{"url":"http://evil:1"}') is None
        assert unseal("s3cret", b"") is None
        assert unseal("s3cret", None) is None

    def test_malformed_frames_rejected(self):
        assert unseal("s", b"s1:short:payload") is None
        assert unseal("s", b"s1:" + b"a" * 64) is None
        assert unseal("s", b"v9:" + b"a" * 64 + b":x") is None


# ---------------------------------------------------------------------------
# gossip membership units
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


class _StubPeers:
    """PeerClient.gossip stand-in: canned replies per target."""

    def __init__(self, replies=None):
        self.replies = replies or {}
        self.sent = []

    async def gossip(self, target, payload):
        self.sent.append((target, json.loads(payload)))
        reply = self.replies.get(target)
        return reply() if callable(reply) else reply


def _gm(self_url=A, seed=(A, B, C), clock=None, **kw):
    return GossipManager(
        _StubPeers(), self_url, seed,
        interval_s=0.1, fanout=2, fail_after_s=1.0,
        clock=clock or _Clock(), **kw,
    )


def _member(hb, draining=False, left=False):
    return {"hb": hb, "draining": draining, "left": left}


class TestGossipMerge:
    def test_seed_view_is_live(self):
        gm = _gm()
        assert gm.members == (A, B, C)
        assert gm.draining == frozenset()

    def test_higher_heartbeat_wins(self):
        gm = _gm()
        gm.merge({"members": {B: _member(5, draining=True)}})
        assert gm._entries[B]["hb"] == 5
        assert gm._entries[B]["draining"]
        # an older rumor never rolls state back
        gm.merge({"members": {B: _member(3)}})
        assert gm._entries[B]["hb"] == 5
        assert gm._entries[B]["draining"]

    def test_equal_heartbeat_ors_flags(self):
        gm = _gm()
        gm.merge({"members": {B: _member(2)}})
        gm.merge({"members": {B: _member(2, draining=True)}})
        assert gm._entries[B]["draining"]

    def test_stalled_member_expires(self):
        clock = _Clock()
        gm = _gm(clock=clock)
        clock.t += 2.0  # past fail_after_s
        gm._apply_view()
        assert gm.members == (A,)

    def test_advancing_heartbeat_is_liveness(self):
        clock = _Clock()
        gm = _gm(clock=clock)
        clock.t += 0.9
        gm.merge({"members": {B: _member(7)}})
        clock.t += 0.5  # B heard 0.5s ago, C stalled 1.4s
        gm._apply_view()
        assert gm.members == (A, B)

    def test_tombstone_removes_member(self):
        gm = _gm()
        gm.merge({"members": {B: _member(9, left=True)}})
        gm._apply_view()
        assert B not in gm.members

    def test_direct_contact_refutes_tombstone(self):
        gm = _gm()
        gm.merge({"members": {B: _member(9, left=True)}})
        gm._apply_view()
        assert B not in gm.members
        # B POSTs to us: direct evidence beats any rumor counter
        gm.receive({"from": B, "members": {B: _member(0)}})
        assert B in gm.members

    def test_self_refutation_outpaces_rumor(self):
        gm = _gm()
        gm.merge({"members": {A: _member(40, left=True)}})
        assert gm._entries[A]["hb"] == 41
        assert not gm._entries[A]["left"]
        assert A in gm.members

    def test_released_self_does_not_refute(self):
        gm = _gm()
        gm.released = True
        gm._entries[A]["left"] = True
        gm.merge({"members": {A: _member(40)}})
        assert gm._entries[A]["left"]

    def test_unknown_member_adopted_bounded(self):
        gm = _gm()
        gm.merge({"members": {
            f"http://m{i}:1": _member(1) for i in range(_MAX_ENTRIES * 2)
        }})
        assert len(gm._entries) <= _MAX_ENTRIES

    def test_malformed_digest_never_raises(self):
        gm = _gm()
        gm.merge(None)
        gm.merge([])
        gm.merge({"members": "nope", "epochs": 3, "brains": []})
        gm.merge({"members": {B: "nope", "": _member(1), C: {"hb": "x"}}})
        gm.merge({"brains": {B: "nope", C: [1, "not-a-dict"]}})
        assert gm.members == (A, B, C)

    def test_rotation_covers_all_candidates(self):
        gm = _gm()
        gm.fanout = 1
        seen = set()
        for _ in range(3):
            gm._round += 1
            seen.update(gm._pick_targets())
        assert seen == {B, C}


class TestGossipPiggyback:
    def test_epochs_disseminate(self):
        ea, eb = EpochRegistry(None), EpochRegistry(None)
        ga = _gm(epochs=ea)
        gb = _gm(self_url=B, epochs=eb)
        ea.note(7, 3)
        gb.merge(ga.digest())
        assert eb.known(7) == 3
        # high-water only: an older epoch never rolls back
        gb.epochs.note(7, 5)
        gb.merge(ga.digest())
        assert eb.known(7) == 5

    def test_brains_ride_the_digest(self):
        ga, gb = _gm(), _gm(self_url=B)
        ga.set_local_brain({"url": A, "pressure": 0.5})
        digest = ga.digest()
        assert digest["brains"][A][1]["pressure"] == 0.5
        gb.merge(digest)
        assert gb.fleet_brains()[A]["pressure"] == 0.5

    def test_stale_brain_never_overwrites(self):
        gb = _gm(self_url=B)
        gb.merge({"members": {A: _member(5)},
                  "brains": {A: [5, {"pressure": 0.9}]}})
        gb.merge({"brains": {A: [3, {"pressure": 0.1}]}})
        assert gb.fleet_brains()[A]["pressure"] == 0.9

    def test_left_member_brain_excluded(self):
        gb = _gm(self_url=B)
        gb.merge({"members": {A: _member(5)},
                  "brains": {A: [5, {"pressure": 0.9}]}})
        gb.merge({"members": {A: _member(9, left=True)}})
        gb._apply_view()
        assert A not in gb.fleet_brains()
        assert A not in gb.digest().get("brains", {})

    async def test_release_pushes_tombstone(self):
        gm = _gm()
        assert await gm.release_lease()
        digest = gm.digest()
        assert digest["members"][A]["left"]
        # terminal: no further rounds
        assert not await gm.refresh_once()

    async def test_refresh_exchange_merges_reply(self):
        gm = _gm()
        gm.peers = _StubPeers(replies={
            B: {"from": B, "members": {B: _member(11)}},
            C: None,  # unreachable
        })
        ok = await gm.refresh_once()
        assert ok
        assert gm._entries[B]["hb"] == 11
        assert gm.exchanges == 1 and gm.exchange_failures == 1


class _FakeLink:
    """RedisLink stand-in for the join-bootstrap hint."""

    def __init__(self):
        self.store = {}

    async def command(self, *parts):
        if parts[0] == b"SET":
            self.store[parts[1]] = parts[2]
            return b"OK"
        if parts[0] == b"MGET":
            return [self.store.get(k) for k in parts[1:]]
        if parts[0] == b"DEL":
            return int(self.store.pop(parts[1], None) is not None)
        raise AssertionError(parts)

    async def scan_keys(self, pattern):
        return list(self.store)


class TestGossipHint:
    async def test_hint_adopts_unknown_member(self):
        link = _FakeLink()
        ga = _gm(self_url=A, seed=(A,), link=link, secret="s")
        # D published its sealed lease; A has never heard of it
        link.store[(MEMBER_PREFIX + "http://d:4").encode()] = seal(
            "s", b'{"url":"http://d:4"}'
        )
        await ga._hint_round()
        assert "http://d:4" in ga._entries
        assert (MEMBER_PREFIX + A).encode() in link.store

    async def test_hint_rejects_unsealed_lease(self):
        link = _FakeLink()
        ga = _gm(self_url=A, seed=(A,), link=link, secret="s")
        link.store[(MEMBER_PREFIX + "http://evil:1").encode()] = (
            b'{"url":"http://evil:1"}'
        )
        await ga._hint_round()
        assert "http://evil:1" not in ga._entries

    async def test_hint_failure_is_silent(self):
        class _DeadLink:
            async def command(self, *parts):
                raise ConnectionError("down")

            async def scan_keys(self, pattern):
                raise ConnectionError("down")

        ga = _gm(self_url=A, seed=(A, B), link=_DeadLink())
        await ga._hint_round()  # must not raise
        assert ga.hint_failures == 1


# ---------------------------------------------------------------------------
# byte integrity: the hash gate, the ledger, the suspicion clause
# ---------------------------------------------------------------------------

class TestBodyIntegrity:
    def test_body_matches(self):
        body = b"tile-bytes"
        assert body_matches(make_etag(body), body)
        assert not body_matches(make_etag(body), body + b"x")

    def test_missing_etag_fails(self):
        # a stripped validator must not bypass the gate
        assert not body_matches(None, b"tile-bytes")
        assert not body_matches("", b"tile-bytes")

    def test_ledger_counts_and_expiry(self):
        clock = _Clock()
        ledger = CorruptionLedger(ttl_s=10.0, clock=clock)
        ledger.note(B)
        ledger.note(B)
        ledger.note(C)
        assert ledger.counts() == {B: 2, C: 1}
        # counts are NOT consumed by reading (suspicion re-derives
        # verdicts every round)
        assert ledger.counts() == {B: 2, C: 1}
        clock.t += 11.0
        assert ledger.counts() == {}

    def test_ledger_bounded(self):
        ledger = CorruptionLedger(max_members=4)
        for i in range(10):
            ledger.note(f"http://m{i}:1")
        assert len(ledger.counts()) <= 4
        assert ledger.snapshot()["total"] == 10

    def test_ledger_ignores_anonymous(self):
        ledger = CorruptionLedger()
        ledger.note(None)
        ledger.note("")
        assert ledger.counts() == {}

    def test_corruption_verdict(self):
        policy = SuspicionPolicy(enabled=True, corruption_after=2)
        assert policy.verdicts({}, {}, {B: 1}) == []
        assert policy.verdicts({}, {}, {B: 2}) == [B]

    def test_corruption_feeds_quorum(self):
        policy = SuspicionPolicy(enabled=True)
        my = policy.verdicts({}, {}, {C: 1})
        assert my == [C]
        # two of three reporters (peer brain + local verdict) demote
        fleet = {B: {"bad": [C]}, C: {"bad": []}}
        assert policy.demoted(fleet, my, (A, B, C)) == [C]

    def test_disabled_policy_judges_nothing(self):
        policy = SuspicionPolicy(enabled=False)
        assert policy.verdicts({}, {}, {B: 99}) == []


class TestL2Integrity:
    async def test_corrupt_l2_value_is_miss_and_deleted(self):
        resp = InMemoryRespServer()
        await resp.start()
        tier = RedisL2Tier(resp.uri, ttl_s=60.0)
        try:
            entry = CachedTile(b"png-bytes", filename="t.png")
            assert await tier.put("img=1|k", entry)
            got = await tier.get("img=1|k")
            assert got is not None and got.body == b"png-bytes"
            # flip one body byte inside the stored frame (the ETag in
            # the header stays intact — silent Redis-side corruption)
            key = tier._key("img=1|k")
            raw, expires = resp.data[key]
            resp.data[key] = (raw[:-1] + bytes([raw[-1] ^ 0xFF]),
                              expires)
            fails_before = tier.integrity_fails
            got = await tier.get("img=1|k")
            assert got is None
            assert tier.integrity_fails == fails_before + 1
            # quarantined: the corrupt value is gone from Redis
            assert key not in resp.data
        finally:
            await tier.close()
            await resp.close()

    async def test_verification_can_be_disabled(self):
        resp = InMemoryRespServer()
        await resp.start()
        tier = RedisL2Tier(resp.uri, ttl_s=60.0, verify_bodies=False)
        try:
            entry = CachedTile(b"png-bytes", filename="t.png")
            await tier.put("img=1|k", entry)
            key = tier._key("img=1|k")
            raw, expires = resp.data[key]
            resp.data[key] = (raw[:-1] + bytes([raw[-1] ^ 0xFF]),
                              expires)
            got = await tier.get("img=1|k")  # escape hatch honored
            assert got is not None
        finally:
            await tier.close()
            await resp.close()


# ---------------------------------------------------------------------------
# warm-set digests over the disk manifest
# ---------------------------------------------------------------------------

class TestWarmKeys:
    async def test_warm_keys_spans_both_tiers(self, tmp_path):
        cache = TileResultCache(
            memory_bytes=1 << 20, disk_dir=str(tmp_path / "spill"),
            manifest=False,
        )
        await cache.put("img=1|ram", CachedTile(b"r" * 64),
                        generation=cache.generation())
        # a disk-only entry (spilled and evicted from RAM long ago)
        cache.disk.put("img=1|disk", CachedTile(b"d" * 64))
        keys = cache.warm_keys(limit=16)
        assert "img=1|ram" in keys
        assert "img=1|disk" in keys
        # RAM slice leads: the hottest entries head the digest
        assert keys.index("img=1|ram") < keys.index("img=1|disk")

    async def test_warm_keys_dedups_and_bounds(self, tmp_path):
        cache = TileResultCache(
            memory_bytes=1 << 20, disk_dir=str(tmp_path / "spill"),
            manifest=False,
        )
        for i in range(6):
            await cache.put(f"img=1|k{i}", CachedTile(b"x" * 32),
                            generation=cache.generation())
            cache.disk.put(f"img=1|k{i}", CachedTile(b"x" * 32))
        keys = cache.warm_keys(limit=4)
        assert len(keys) == 4
        assert len(set(keys)) == 4

    def test_disk_keys_snapshot_mru_first(self, tmp_path):
        cache = TileResultCache(
            memory_bytes=1 << 20, disk_dir=str(tmp_path / "spill"),
            manifest=False,
        )
        for i in range(3):
            cache.disk.put(f"img=1|k{i}", CachedTile(b"x" * 32))
        snap = cache.disk.keys_snapshot()
        assert snap[0] == "img=1|k2"
        assert cache.disk.keys_snapshot(limit=2) == snap[:2]


# ---------------------------------------------------------------------------
# the Zarr v3 shard-index TTL + purge
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _restore_shard_ttl():
    before = zarr_mod.shard_index_ttl_s()
    yield
    zarr_mod.set_shard_index_ttl(before)


class TestShardIndexTtl:
    def _arr(self, tmp_path):
        img = np.arange(64 * 64, dtype=np.uint16).reshape(
            1, 1, 1, 64, 64
        )
        root = str(tmp_path / "sharded.zarr")
        zarr_mod.write_ngff(
            root, img, chunks=(32, 32), levels=1, zarr_format=3,
            compressor=None, shards=(64, 64),
        )
        return zarr_mod.ZarrArray(os.path.join(root, "0"))

    def test_memo_expires_after_ttl(self, tmp_path):
        arr = self._arr(tmp_path)
        clock = _Clock()
        arr._shard_clock = clock
        zarr_mod.set_shard_index_ttl(300.0)
        arr.read_region((0, 0, 0, 0, 0), (1, 1, 1, 64, 64))
        assert len(arr._shard_indexes) == 1
        key = next(iter(arr._shard_indexes))
        assert arr._cached_shard_index(key) is not zarr_mod._MISSING
        clock.t += 301.0
        # expired: the memo is dropped and the next read refetches
        assert arr._cached_shard_index(key) is zarr_mod._MISSING
        assert key not in arr._shard_indexes

    def test_zero_ttl_never_expires(self, tmp_path):
        arr = self._arr(tmp_path)
        clock = _Clock()
        arr._shard_clock = clock
        zarr_mod.set_shard_index_ttl(0.0)
        arr.read_region((0, 0, 0, 0, 0), (1, 1, 1, 64, 64))
        key = next(iter(arr._shard_indexes))
        clock.t += 1e9
        assert arr._cached_shard_index(key) is not zarr_mod._MISSING

    def test_rewritten_shard_observed_after_ttl(self, tmp_path):
        img = np.full((1, 1, 1, 64, 64), 7, dtype=np.uint16)
        root = str(tmp_path / "rw.zarr")
        zarr_mod.write_ngff(
            root, img, chunks=(32, 32), levels=1, zarr_format=3,
            compressor=None, shards=(64, 64),
        )
        arr = zarr_mod.ZarrArray(os.path.join(root, "0"))
        clock = _Clock()
        arr._shard_clock = clock
        zarr_mod.set_shard_index_ttl(300.0)
        first = arr.read_region((0, 0, 0, 0, 0), (1, 1, 1, 64, 64))
        assert int(first[0, 0, 0, 0, 0]) == 7
        # rewrite the shard in place with different pixels
        zarr_mod.write_ngff(
            root, np.full_like(img, 9), chunks=(32, 32), levels=1,
            zarr_format=3, compressor=None, shards=(64, 64),
        )
        clock.t += 301.0
        second = arr.read_region((0, 0, 0, 0, 0), (1, 1, 1, 64, 64))
        assert int(second[0, 0, 0, 0, 0]) == 9

    def test_purge_drops_all_levels(self, tmp_path):
        arr = self._arr(tmp_path)
        arr.read_region((0, 0, 0, 0, 0), (1, 1, 1, 64, 64))
        assert arr.purge_shard_indexes() == 1
        assert len(arr._shard_indexes) == 0

    def test_pixels_service_invalidate_purges(self):
        class _Buf:
            cache_ns = 42
            purged = 0

            def purge_shard_indexes(self):
                _Buf.purged += 1
                return 3

            def close(self):
                pass

        service = PixelsService.__new__(PixelsService)
        import threading

        service._lock = threading.Lock()
        service._cache = {1: _Buf()}
        assert service.invalidate(1) == 42
        assert _Buf.purged == 1
        assert service.invalidate(1) is None  # already gone


# ---------------------------------------------------------------------------
# config validation for the r20 blocks
# ---------------------------------------------------------------------------

def _cfg(raw):
    return Config.from_dict({
        "session-store": {"type": "memory"}, **raw,
    })


class TestDecentralizedConfig:
    def test_gossip_and_integrity_parse(self):
        cfg = _cfg({"cluster": {
            "members": [A, B], "self": A,
            "gossip": {"enabled": True, "interval-s": 0.5,
                       "fanout": 3, "fail-after-s": 4.0},
            "integrity": {"verify-bodies": False, "verdict-after": 2},
        }})
        g, i = cfg.cluster.gossip, cfg.cluster.integrity
        assert g.enabled and g.interval_s == 0.5 and g.fanout == 3
        assert g.fail_after_s == 4.0
        assert not i.verify_bodies and i.verdict_after == 2

    def test_defaults(self):
        cfg = _cfg({})
        assert not cfg.cluster.gossip.enabled
        assert cfg.cluster.integrity.verify_bodies
        assert cfg.cluster.integrity.verdict_after == 1
        assert cfg.io.shard_index_ttl_s == 300.0

    def test_gossip_requires_members_and_self(self):
        with pytest.raises(ConfigError, match="gossip"):
            _cfg({"cluster": {"gossip": {"enabled": True}}})

    def test_fail_after_must_exceed_interval(self):
        with pytest.raises(ConfigError, match="fail-after-s"):
            _cfg({"cluster": {
                "members": [A], "self": A,
                "gossip": {"enabled": True, "interval-s": 5,
                           "fail-after-s": 2},
            }})

    def test_unknown_keys_fail(self):
        with pytest.raises(ConfigError, match="gossip"):
            _cfg({"cluster": {"gossip": {"typo": 1}}})
        with pytest.raises(ConfigError, match="integrity"):
            _cfg({"cluster": {"integrity": {"typo": 1}}})
        with pytest.raises(ConfigError, match="io"):
            _cfg({"io": {"shard-index-ttls": 1}})

    def test_suspect_rides_gossip_without_lease(self):
        cfg = _cfg({"cluster": {
            "members": [A, B], "self": A,
            "gossip": {"enabled": True},
            "suspect": {"enabled": True},
        }})
        assert cfg.cluster.suspect.enabled

    def test_suspect_still_needs_a_heartbeat(self):
        with pytest.raises(ConfigError, match="suspect"):
            _cfg({"cluster": {
                "members": [A], "self": A,
                "suspect": {"enabled": True},
            }})

    def test_shard_index_ttl_parses_and_applies(self):
        cfg = _cfg({"io": {"shard-index-ttl-s": 120}})
        assert cfg.io.shard_index_ttl_s == 120.0
        from omero_ms_pixel_buffer_tpu.io.fetch import configure

        before = zarr_mod.shard_index_ttl_s()
        try:
            configure(cfg.io)
            assert zarr_mod.shard_index_ttl_s() == 120.0
        finally:
            zarr_mod.set_shard_index_ttl(before)


# ---------------------------------------------------------------------------
# chaos: the gossip fleet vs a dead Redis
# ---------------------------------------------------------------------------

GOSSIP_EXTRA = {
    "gossip": {
        "enabled": True, "interval-s": 0.15, "fail-after-s": 1.2,
    },
}


def _converged(replicas, expected):
    return all(
        set(r.app.cache_plane.membership.members) == set(expected)
        for r in replicas if not r.dead
    )


class TestRedislessFleet:
    @pytest.mark.resilience
    async def test_redis_death_is_a_non_event(self, tmp_path):
        """The tentpole drive: Redis dies mid-traffic and the control
        plane shrugs — membership stays converged over gossip, epoch
        bumps still disseminate, and the fleet serves zero 5xx."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=3, cluster_extra=GOSSIP_EXTRA,
        )
        members = [r.url for r in replicas]
        try:
            await asyncio.sleep(0.6)  # a few gossip rounds
            assert _converged(replicas, members)
            statuses = []
            async with ClientSession() as http:
                for path in _tile_paths(8):
                    for r in replicas:
                        s, _, _ = await _get(http, r.url + path)
                        statuses.append(s)
                # the coordinator dies mid-traffic
                await resp.close()
                await asyncio.sleep(0.8)
                for path in _tile_paths(8):
                    for r in replicas:
                        s, _, _ = await _get(http, r.url + path)
                        statuses.append(s)
            assert all(s == 200 for s in statuses), statuses
            # membership kept converging with no Redis at all
            assert _converged(replicas, members)
            # epochs: a bump on one replica reaches the others over
            # the gossip digest (Redis INCR is impossible now)
            plane0 = replicas[0].app.cache_plane
            await plane0.epochs.bump(1)
            bumped = plane0.epochs.known(1)
            assert bumped >= 1

            async def _epochs_spread():
                while not all(
                    r.app.cache_plane.epochs.known(1) >= bumped
                    for r in replicas
                ):
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(_epochs_spread(), 5.0)
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_member_death_detected_without_redis(self, tmp_path):
        """With Redis already dead, a crashed replica still leaves the
        live view within the gossip failure window."""
        replicas, resp, cleanup = await _make_cluster(
            tmp_path, n=3, cluster_extra=GOSSIP_EXTRA,
        )
        members = [r.url for r in replicas]
        try:
            await asyncio.sleep(0.6)
            await resp.close()
            await replicas[2].kill()
            survivors = replicas[:2]

            async def _shrunk():
                while not _converged(survivors, members[:2]):
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(_shrunk(), 10.0)
            # survivors keep serving
            async with ClientSession() as http:
                for path in _tile_paths(4):
                    for r in survivors:
                        s, _, _ = await _get(http, r.url + path)
                        assert s == 200
        finally:
            await cleanup()


# ---------------------------------------------------------------------------
# chaos: the corrupt peer
# ---------------------------------------------------------------------------

def _corrupt_serving(replica):
    """Bad-RAM lever: every cache read on this replica returns
    bit-flipped bytes under the ORIGINAL ETag — wrong-but-200 output
    that status codes cannot see."""
    cache = replica.app.result_cache
    inner = cache.get

    async def bad_get(key):
        entry = await inner(key)
        if entry is None:
            return None
        flipped = bytes([entry.body[0] ^ 0xFF]) + entry.body[1:]
        return CachedTile(
            flipped, etag=entry.etag, filename=entry.filename,
            stored_at=entry.stored_at,
        )

    cache.get = bad_get


class TestCorruptPeer:
    @pytest.mark.resilience
    async def test_corrupt_replica_demoted_clients_unharmed(
        self, tmp_path
    ):
        """One replica serves bit-flipped bodies: every transfer is
        discarded at the hash gate (clients always receive correct
        bytes), the strikes feed the suspicion quorum on every healthy
        replica, and the corrupt replica is demoted off the ring."""
        replicas, _resp, cleanup = await _make_cluster(
            tmp_path, n=3, l2=False, cluster_extra={
                **GOSSIP_EXTRA,
                "suspect": {"enabled": True},
            },
        )
        victim, healthy = replicas[2], replicas[:2]
        paths = _tile_paths(16)
        try:
            await asyncio.sleep(0.6)
            plane0 = healthy[0].app.cache_plane
            victim_owned = [
                p for p in paths
                if plane0.ring.owner(
                    _key_for(healthy[0].app, p)
                ) == victim.url
            ]
            assert victim_owned  # 16 keys over 3 members: some here
            baseline = {}
            async with ClientSession() as http:
                # baseline through the HONEST victim: it caches its
                # owned keys (the poisoned RAM of the next phase)
                # while the healthy replicas cache only their own
                for path in paths:
                    s, body, _ = await _get(http, victim.url + path)
                    assert s == 200
                    baseline[path] = body
                _corrupt_serving(victim)
                # every victim-owned key now peer-fetches flipped
                # bytes under the original ETag; the gate discards
                # them, strikes the ledger, and renders locally
                for r in healthy:
                    for path in paths:
                        s, body, _ = await _get(http, r.url + path)
                        assert s == 200
                        assert body == baseline[path]
                for r in healthy:
                    ledger = r.app.cache_plane.corruption.counts()
                    assert ledger.get(victim.url, 0) >= 1

                async def _demoted():
                    while not all(
                        victim.url in r.app.cache_plane.brains.demoted
                        for r in healthy
                    ):
                        await asyncio.sleep(0.05)

                await asyncio.wait_for(_demoted(), 10.0)
                # the demoted ring re-homes the victim's keys; the
                # fleet keeps serving correct bytes
                for path in victim_owned:
                    for r in healthy:
                        s, body, _ = await _get(http, r.url + path)
                        assert s == 200
                        assert body == baseline[path]
        finally:
            await cleanup()

    @pytest.mark.resilience
    async def test_corrupt_replica_push_rejected(self, tmp_path):
        """The replication ingress: a push whose body fails the hash
        gate is refused with a 400 and never lands in the cache."""
        from omero_ms_pixel_buffer_tpu.cache.plane.l2 import (
            encode_entry,
        )
        from omero_ms_pixel_buffer_tpu.cache.plane.peer import (
            KEY_HEADER,
            PEER_HEADER,
        )

        replicas, _resp, cleanup = await _make_cluster(
            tmp_path, n=2, l2=False, cluster_extra=GOSSIP_EXTRA,
        )
        try:
            good = CachedTile(b"correct-bytes", filename="t.png")
            evil = CachedTile(
                b"corrupt-bytes!", etag=good.etag, filename="t.png",
            )
            async with ClientSession() as http:
                async with http.post(
                    replicas[0].url + "/internal/replica",
                    data=encode_entry(evil),
                    headers={
                        PEER_HEADER: replicas[1].url,
                        KEY_HEADER: "img=1|evil",
                    },
                ) as r:
                    assert r.status == 400
            assert await replicas[0].app.result_cache.get(
                "img=1|evil"
            ) is None
            ledger = replicas[0].app.cache_plane.corruption.counts()
            assert ledger.get(replicas[1].url, 0) >= 1
        finally:
            await cleanup()
