"""zstd-compressed TIFF tiles (compression 50000, the libtiff/
Bio-Formats registered code) — increasingly the default for new
OME-TIFF exports."""

import numpy as np
import pytest

from omero_ms_pixel_buffer_tpu.io.ometiff import (
    OmeTiffPixelBuffer,
    write_ome_tiff,
)

# Writing zstd TIFF fixtures (and the hostile-frame test) needs the
# real codec; skip cleanly where python-zstandard isn't installed.
pytest.importorskip("zstandard")

rng = np.random.default_rng(89)
IMG = rng.integers(0, 60000, (1, 1, 2, 120, 150), dtype=np.uint16)


@pytest.fixture(scope="module")
def fixture(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("zstdtiff") / "z.ome.tiff")
    write_ome_tiff(path, IMG, tile_size=(64, 64), compression="zstd",
                   predictor=2)
    return path


def test_sequential_reads_pixel_exact(fixture):
    buf = OmeTiffPixelBuffer(fixture)
    try:
        tile = buf.get_tile_at(0, 1, 0, 0, 32, 16, 100, 90)
        np.testing.assert_array_equal(
            tile, IMG[0, 0, 1, 16:106, 32:132]
        )
    finally:
        buf.close()


def test_batched_equals_sequential(fixture):
    buf = OmeTiffPixelBuffer(fixture)
    try:
        coords = [
            (0, 0, 0, 0, 0, 64, 64),
            (1, 0, 0, 64, 64, 80, 56),
            (0, 0, 0, 100, 100, 50, 20),
        ]
        for co, tile in zip(coords, buf.read_tiles(coords)):
            np.testing.assert_array_equal(tile, buf.get_tile_at(0, *co))
    finally:
        buf.close()


def test_hostile_declared_size_bounded():
    """A frame declaring far more than the block capacity must be
    rejected BEFORE allocation — python-zstandard's max_output_size is
    ignored for known-size frames (ops/codecs.bounded_zstd)."""
    import zstandard

    from omero_ms_pixel_buffer_tpu.ops import codecs

    big = zstandard.ZstdCompressor().compress(bytes(1_000_000))
    assert codecs.bounded_zstd(big, 1000) is None  # declared 1MB > cap
    small = zstandard.ZstdCompressor().compress(b"ok" * 10)
    assert codecs.bounded_zstd(small, 1000) == b"ok" * 10
    assert codecs.bounded_zstd(b"garbage!", 1000) is None


def test_corrupt_block_degrades(fixture, tmp_path):
    data = bytearray(open(fixture, "rb").read())
    # corrupt bytes mid-file (inside some tile payload)
    mid = len(data) // 2
    data[mid : mid + 64] = bytes(64)
    bad = str(tmp_path / "bad.ome.tiff")
    open(bad, "wb").write(bytes(data))
    buf = OmeTiffPixelBuffer(bad)
    try:
        errors = 0
        for z in range(2):
            try:
                buf.get_tile_at(0, z, 0, 0, 0, 0, 120, 100)
            except Exception:
                errors += 1
        assert errors >= 1  # the corrupt plane fails, never crashes
    finally:
        buf.close()
