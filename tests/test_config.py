"""Config schema and hard-failure contracts
(reference: PixelBufferMicroserviceVerticle.java:120-137,155-158,258-273;
src/dist/conf/config.yaml)."""

import pytest

from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError


def test_defaults_match_reference_shipped_config():
    cfg = Config.from_dict({"session-store": {"type": "memory"}})
    assert cfg.port == 8082
    assert cfg.event_bus_send_timeout_ms == 15000
    assert cfg.omero_port == 4064
    assert cfg.effective_worker_pool_size >= 2  # 2 x CPUs default


def test_missing_session_store_is_hard_error():
    with pytest.raises(ConfigError):
        Config.from_dict({"port": 9000})


def test_invalid_session_store_type_is_hard_error():
    with pytest.raises(ConfigError):
        Config.from_dict({"session-store": {"type": "dynamo"}})


def test_full_yaml_shape():
    cfg = Config.from_dict(
        {
            "port": 9090,
            "event-bus-send-timeout": 5000,
            "worker_pool_size": 4,
            "omero": {"host": "omero.example", "port": 4444},
            "session-store": {
                "type": "redis",
                "synchronicity": "async",
                "uri": "redis://h:6379/1",
            },
            "http-tracing": {"enabled": True, "zipkin-url": "http://z/api/v2"},
            "backend": {
                "engine": "jax",
                "batching": {"buckets": [128, 256], "max-batch": 8},
            },
        }
    )
    assert cfg.port == 9090
    assert cfg.event_bus_send_timeout_ms == 5000
    assert cfg.worker_pool_size == 4
    assert cfg.omero_host == "omero.example"
    assert cfg.session_store.uri == "redis://h:6379/1"
    assert cfg.http_tracing_enabled
    assert cfg.backend.batching.buckets == (128, 256)
    assert cfg.backend.batching.max_batch == 8


def test_invalid_backend_engine_is_hard_error():
    with pytest.raises(ConfigError):
        Config.from_dict({
            "session-store": {"type": "memory"},
            "backend": {"engine": "hots"},
        })


def test_png_block_parsed():
    cfg = Config.from_dict({
        "session-store": {"type": "memory"},
        "backend": {"png": {"filter": "sub", "level": 3,
                            "strategy": "default"}},
    })
    assert cfg.backend.png.filter == "sub"
    assert cfg.backend.png.level == 3
    assert cfg.backend.png.strategy == "default"
    # defaults: up/6/rle
    cfg2 = Config.from_dict({"session-store": {"type": "memory"}})
    assert (cfg2.backend.png.filter, cfg2.backend.png.level,
            cfg2.backend.png.strategy) == ("up", 6, "fast")


def test_png_queue_and_deflate_mode_parsed():
    cfg = Config.from_dict({
        "session-store": {"type": "memory"},
        "backend": {"png": {"queue-depth": 4,
                            "device-deflate-mode": "rle"}},
    })
    assert cfg.backend.png.queue_depth == 4
    assert cfg.backend.png.device_deflate_mode == "rle"
    # defaults: streaming double buffer + the dynamic-Huffman stream
    cfg2 = Config.from_dict({"session-store": {"type": "memory"}})
    assert cfg2.backend.png.queue_depth == 2
    assert cfg2.backend.png.device_deflate_mode == "dynamic"
    for bad in ({"queue-depth": 0}, {"queue-depth": "deep"},
                {"device-deflate-mode": "huffman"}):
        with pytest.raises(ConfigError):
            Config.from_dict({
                "session-store": {"type": "memory"},
                "backend": {"png": bad},
            })


def test_logging_block_and_shipped_config(tmp_path):
    # the shipped sample must load cleanly
    cfg = Config.load("conf/config.yaml")
    assert cfg.session_store.type == "redis"
    assert cfg.backend.png.strategy == "fast"
    assert cfg.logging.file is None

    cfg2 = Config.from_dict({
        "session-store": {"type": "memory"},
        "logging": {"file": str(tmp_path / "svc.log"), "level": "debug",
                    "retention-days": 3},
    })
    assert cfg2.logging.level == "debug"
    assert cfg2.logging.retention_days == 3

    from omero_ms_pixel_buffer_tpu.utils.logging_setup import (
        configure_logging,
    )
    import logging as _logging

    configure_logging(cfg2.logging)
    _logging.getLogger("t").info("hello rolling file")
    root = _logging.getLogger()
    handler = root.handlers[0]
    handler.flush()
    assert "hello rolling file" in (tmp_path / "svc.log").read_text()
    assert handler.backupCount == 3
    # restore stdout logging for the rest of the suite
    configure_logging(type(cfg2.logging)())


def test_invalid_synchronicity_is_hard_error():
    import pytest

    from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

    with pytest.raises(ConfigError, match="synchronicity"):
        Config.from_dict({
            "session-store": {"type": "memory", "synchronicity": "later"}
        })


def test_session_validation_ttl_parsed():
    from omero_ms_pixel_buffer_tpu.utils.config import Config

    cfg = Config.from_dict({
        "session-store": {"type": "memory"},
        "omero": {"session-validation-ttl": 0},
    })
    assert cfg.omero_session_validation_ttl_s == 0.0
    # default preserves the burst-friendly cache
    cfg2 = Config.from_dict({"session-store": {"type": "memory"}})
    assert cfg2.omero_session_validation_ttl_s == 30.0


def test_invalid_session_validation_ttl_is_hard_error():
    import pytest

    from omero_ms_pixel_buffer_tpu.utils.config import Config, ConfigError

    with pytest.raises(ConfigError, match="session-validation-ttl"):
        Config.from_dict({
            "session-store": {"type": "memory"},
            "omero": {"session-validation-ttl": "30s"},
        })
    with pytest.raises(ConfigError, match="session-validation-ttl"):
        Config.from_dict({
            "session-store": {"type": "memory"},
            "omero": {"session-validation-ttl": -1},
        })
