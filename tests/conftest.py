"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh so sharding
paths are exercised without TPU hardware (the driver separately dry-runs
the multi-chip path; bench.py runs on the real chip)."""

import asyncio
import inspect
import os

import pytest

# Force an 8-virtual-device CPU backend regardless of the ambient
# JAX_PLATFORMS (the axon TPU plugin ignores the env var; only the
# config knob reliably overrides it).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Shared zstd gate: the *encode* paths (fixture writing, blosc
# cname="zstd") need the real codec; suites import `needs_zstd` from
# here and skip those cases where python-zstandard isn't installed.
try:
    import zstandard  # noqa: F401

    HAVE_ZSTD = True
except ImportError:
    HAVE_ZSTD = False

needs_zstd = pytest.mark.skipif(
    not HAVE_ZSTD, reason="python-zstandard not installed"
)


# -- minimal async-test support (no pytest-asyncio in the image) -----------


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if not inspect.iscoroutinefunction(fn):
        return None
    kwargs = {
        name: pyfuncitem.funcargs[name]
        for name in pyfuncitem._fixtureinfo.argnames
        if name in pyfuncitem.funcargs
    }
    loop = pyfuncitem.funcargs.get("loop")
    if loop is not None:
        loop.run_until_complete(fn(**kwargs))
    else:
        # Leftover-task reaper: ``asyncio.run``'s own teardown
        # cancels leftovers and then waits WITHOUT a bound — a task
        # that survives cancellation (e.g. a cancel swallowed by
        # wait_for's completion race, bpo-42130) wedges the whole
        # suite silently. Reap here with a timeout instead, so a
        # stuck task is a NAMED failure with its stack, not a hung
        # CI job.
        async def _main():
            try:
                await fn(**kwargs)
            finally:
                cur = asyncio.current_task()
                pending = [
                    t for t in asyncio.all_tasks() if t is not cur
                ]
                for t in pending:
                    t.cancel()
                if pending:
                    _done, still = await asyncio.wait(
                        pending, timeout=20
                    )
                    if still:
                        import sys
                        for t in still:
                            print("STUCK TASK:", t, file=sys.stderr)
                            t.print_stack(file=sys.stderr)
                        raise RuntimeError(
                            f"{len(still)} task(s) survived "
                            "cancellation for 20s — see stderr"
                        )

        asyncio.run(_main())
    return True
