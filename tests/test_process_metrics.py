"""Process collector (the JMX/hotspot exports analog)."""

from omero_ms_pixel_buffer_tpu.utils.metrics import Registry
from omero_ms_pixel_buffer_tpu.utils.process_metrics import (
    ProcessCollector,
    install,
)


def test_collect_exposes_process_metrics():
    text = "\n".join(ProcessCollector("1.2.3").collect())
    for metric in (
        "process_cpu_seconds_total",
        "process_resident_memory_bytes",
        "process_open_fds",
        "process_max_fds",
        "process_threads",
        "python_gc_collections_total",
    ):
        assert metric in text, metric
    assert 'build_info{version="1.2.3"} 1' in text
    # numbers are sane
    rss = float(
        [l for l in text.splitlines()
         if l.startswith("process_resident_memory_bytes")][0].split()[-1]
    )
    assert rss > 1e6  # a real python process uses > 1 MB


def test_install_idempotent_and_scraped_via_registry():
    registry = Registry()
    a = install(registry)
    b = install(registry)
    assert a is b
    text = registry.exposition()
    assert "process_cpu_seconds_total" in text
