"""Golden encode tests: PNG/TIFF streams must decode (via PIL, an
independent decoder) to the exact source pixels — the decoded-pixel
correctness contract from SURVEY.md §7 (viewers accept any valid
stream; compare pixels, not bytes)."""

import io

import numpy as np
import pytest
from PIL import Image

from omero_ms_pixel_buffer_tpu.ops import png as png_ops
from omero_ms_pixel_buffer_tpu.ops import tiff as tiff_ops
from omero_ms_pixel_buffer_tpu.ops.convert import (
    bytes_per_pixel,
    dtype_for,
    to_big_endian_bytes,
    to_big_endian_bytes_np,
)

rng = np.random.default_rng(42)


def pil_decode(data: bytes) -> np.ndarray:
    return np.array(Image.open(io.BytesIO(data)))


class TestConvert:
    def test_bytes_per_pixel_matches_bitsize(self):
        assert bytes_per_pixel("uint8") == 1
        assert bytes_per_pixel("uint16") == 2
        assert bytes_per_pixel("float") == 4
        assert bytes_per_pixel("double") == 8

    @pytest.mark.parametrize(
        "dtype", ["uint8", "int8", "uint16", "int16", "uint32", "int32", "float"]
    )
    def test_device_big_endian_matches_numpy(self, dtype):
        dt = dtype_for(dtype)
        if dt.kind == "f":
            arr = rng.standard_normal((5, 7)).astype(dt)
        else:
            info = np.iinfo(dt)
            arr = rng.integers(info.min, info.max, (5, 7), dtype=dt)
        dev = np.asarray(to_big_endian_bytes(arr))
        host = to_big_endian_bytes_np(arr)
        np.testing.assert_array_equal(dev, host)
        # and against numpy's own big-endian serialization
        np.testing.assert_array_equal(
            host.reshape(-1),
            np.frombuffer(arr.astype(dt.newbyteorder(">")).tobytes(), np.uint8),
        )

    def test_double_routes_to_host_path(self):
        arr = rng.standard_normal((3, 3))
        with pytest.raises(ValueError):
            to_big_endian_bytes(arr)
        host = to_big_endian_bytes_np(arr)
        np.testing.assert_array_equal(
            host.reshape(-1),
            np.frombuffer(arr.astype(">f8").tobytes(), np.uint8),
        )


class TestPng:
    @pytest.mark.parametrize("mode", ["none", "sub", "up", "average", "paeth", "adaptive"])
    def test_uint8_roundtrip_all_filters(self, mode):
        tile = rng.integers(0, 256, (33, 47), dtype=np.uint8)
        data = png_ops.encode_png(tile, filter_mode=mode)
        np.testing.assert_array_equal(pil_decode(data), tile)

    @pytest.mark.parametrize("mode", ["none", "up", "paeth", "adaptive"])
    def test_uint16_roundtrip_big_endian(self, mode):
        tile = rng.integers(0, 65536, (16, 29), dtype=np.uint16)
        data = png_ops.encode_png(tile, filter_mode=mode)
        decoded = pil_decode(data)
        np.testing.assert_array_equal(decoded.astype(np.uint16), tile)

    def test_rgb_roundtrip(self):
        tile = rng.integers(0, 256, (20, 20, 3), dtype=np.uint8)
        data = png_ops.encode_png(tile, filter_mode="adaptive")
        np.testing.assert_array_equal(pil_decode(data), tile)

    def test_float_rejected(self):
        with pytest.raises(png_ops.PngEncodeError):
            png_ops.encode_png(np.zeros((4, 4), np.float32))

    def test_own_decoder_agrees(self):
        tile = rng.integers(0, 65536, (9, 11), dtype=np.uint16)
        data = png_ops.encode_png(tile, filter_mode="paeth")
        np.testing.assert_array_equal(png_ops.decode_png(data), tile)

    @pytest.mark.parametrize("mode", ["none", "sub", "up", "average", "paeth"])
    @pytest.mark.parametrize("dtype", [np.uint8, np.uint16])
    def test_device_filter_matches_host(self, mode, dtype):
        bpp = np.dtype(dtype).itemsize
        tiles = rng.integers(0, np.iinfo(dtype).max, (3, 8, 12), dtype=dtype)
        host = np.stack(
            [
                png_ops.filter_rows_np(
                    to_big_endian_bytes_np(t), bpp, mode
                )
                for t in tiles
            ]
        )
        rows_dev = to_big_endian_bytes(tiles)  # (3, 8, 12*bpp)
        dev = np.asarray(png_ops.filter_batch(rows_dev, bpp, mode))
        np.testing.assert_array_equal(dev, host)

    def test_device_filtered_scanlines_make_valid_png(self):
        tiles = rng.integers(0, 65536, (2, 10, 13), dtype=np.uint16)
        rows = to_big_endian_bytes(tiles)
        filtered = np.asarray(png_ops.filter_batch(rows, 2, "up"))
        for i, t in enumerate(tiles):
            data = png_ops.assemble_png(filtered[i].tobytes(), 13, 10, 16, 0)
            np.testing.assert_array_equal(
                pil_decode(data).astype(np.uint16), t
            )


class TestTiff:
    @pytest.mark.parametrize(
        "dtype", [np.uint8, np.uint16, np.int16, np.float32]
    )
    def test_roundtrip_pil(self, dtype):
        if np.dtype(dtype).kind == "f":
            tile = rng.standard_normal((15, 21)).astype(dtype)
        else:
            info = np.iinfo(dtype)
            tile = rng.integers(info.min, info.max, (15, 21), dtype=dtype)
        data = tiff_ops.encode_tiff(tile)
        decoded = pil_decode(data)
        np.testing.assert_array_equal(decoded.astype(dtype), tile)

    def test_rgb_roundtrip(self):
        tile = rng.integers(0, 256, (10, 12, 3), dtype=np.uint8)
        data = tiff_ops.encode_tiff(tile)
        np.testing.assert_array_equal(pil_decode(data), tile)

    def test_big_endian_and_ome_xml(self):
        tile = np.zeros((4, 6), np.uint16)
        data = tiff_ops.encode_tiff(tile)
        assert data[:2] == b"MM"  # BigEndian=true contract
        assert b'DimensionOrder="XYCZT"' in data
        assert b'BigEndian="true"' in data
        assert b'Type="uint16"' in data
        assert b'SizeX="6"' in data and b'SizeY="4"' in data

    def test_own_decoder_agrees(self):
        tile = rng.integers(-30000, 30000, (7, 9), dtype=np.int16)
        data = tiff_ops.encode_tiff(tile)
        np.testing.assert_array_equal(tiff_ops.decode_tiff(data), tile)

    def test_uint32_and_double_supported(self):
        t32 = rng.integers(0, 2**32, (5, 5), dtype=np.uint32)
        d = tiff_ops.encode_tiff(t32)
        np.testing.assert_array_equal(tiff_ops.decode_tiff(d), t32)
        tf64 = rng.standard_normal((5, 5))
        d = tiff_ops.encode_tiff(tf64)
        np.testing.assert_array_equal(tiff_ops.decode_tiff(d), tf64)
