"""Ice/Glacier2 session-join client against a fake router that speaks
the same wire format (protocol 1.0 framing, encoding 1.1
encapsulations)."""

import asyncio
import struct

import pytest

from omero_ms_pixel_buffer_tpu.auth.ice import (
    Glacier2Client,
    IceProtocolError,
    IceSessionValidator,
    build_request,
    marshal_two_strings,
)

HEADER = b"IceP" + bytes([1, 0, 1, 0])


def _msg(msg_type: int, body: bytes = b"") -> bytes:
    return (
        b"IceP" + bytes([1, 0, 1, 0, msg_type, 0])
        + struct.pack("<i", 14 + len(body)) + body
    )


def _read_size(buf, off):
    if buf[off] != 255:
        return buf[off], off + 1
    return struct.unpack("<i", buf[off + 1 : off + 5])[0], off + 5


def _read_string(buf, off):
    n, off = _read_size(buf, off)
    return buf[off : off + n].decode(), off + n


class FakeGlacier2:
    """Accepts one Ice connection: sends ValidateConnection, parses one
    createSession Request, replies per the configured session table."""

    def __init__(self, valid_keys=(), exception="PermissionDenied"):
        self.valid_keys = set(valid_keys)
        self.exception = exception
        self.requests = []
        self.server = None
        self.port = None

    async def __aenter__(self):
        self.server = await asyncio.start_server(
            self._handle, "127.0.0.1", 0
        )
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def __aexit__(self, *exc):
        self.server.close()
        await self.server.wait_closed()

    async def _handle(self, reader, writer):
        try:
            writer.write(_msg(3))  # ValidateConnection
            await writer.drain()
            header = await reader.readexactly(14)
            assert header[:4] == b"IceP"
            assert header[8] == 0  # Request
            (total,) = struct.unpack("<i", header[10:14])
            body = await reader.readexactly(total - 14)
            (request_id,) = struct.unpack("<i", body[:4])
            off = 4
            name, off = _read_string(body, off)
            category, off = _read_string(body, off)
            nfacet, off = _read_size(body, off)
            operation, off = _read_string(body, off)
            mode = body[off]
            off += 1
            nctx, off = _read_size(body, off)
            # params encapsulation: size(i32) major minor payload
            (esize,) = struct.unpack("<i", body[off : off + 4])
            payload = body[off + 6 : off + esize]
            user, poff = _read_string(payload, 0)
            password, _ = _read_string(payload, poff)
            self.requests.append(
                (request_id, category, name, operation, mode, user,
                 password)
            )
            if operation != "createSession":
                status_body = struct.pack("<i", request_id) + bytes([2])
                writer.write(_msg(2, status_body))
                await writer.drain()
                return
            if user in self.valid_keys:
                # success: status 0 + encapsulated (null proxy) result
                result = struct.pack("<iBB", 7, 1, 1) + b"\x00"
                reply = struct.pack("<i", request_id) + bytes([0]) + result
            else:
                exc_blob = (
                    b"\x2b::Glacier2::" + self.exception.encode()
                    + b"Exception\x00reason"
                )
                reply = (
                    struct.pack("<i", request_id) + bytes([1]) + exc_blob
                )
            writer.write(_msg(2, reply))
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()


class TestGlacier2Client:
    def test_join_success(self, loop):
        async def run():
            async with FakeGlacier2(valid_keys={"good-key"}) as g:
                client = Glacier2Client("127.0.0.1", g.port)
                joined, reason = await client.create_session(
                    "good-key", "good-key"
                )
                assert joined and reason is None
                rid, category, name, op, mode, user, pw = g.requests[0]
                assert (category, name) == ("Glacier2", "router")
                assert op == "createSession"
                assert mode == 0
                assert user == pw == "good-key"

        loop.run_until_complete(run())

    @pytest.mark.parametrize(
        "exc,reason",
        [("PermissionDenied", "Permission denied"),
         ("CannotCreateSession", "Cannot create session")],
    )
    def test_join_denied(self, loop, exc, reason):
        async def run():
            async with FakeGlacier2(exception=exc) as g:
                client = Glacier2Client("127.0.0.1", g.port)
                joined, why = await client.create_session("bad", "bad")
                assert not joined
                assert why == reason

        loop.run_until_complete(run())

    def test_validator_contract(self, loop):
        async def run():
            async with FakeGlacier2(valid_keys={"alive"}) as g:
                v = IceSessionValidator("127.0.0.1", g.port)
                assert await v.validate("alive")
            async with FakeGlacier2() as g2:
                v2 = IceSessionValidator("127.0.0.1", g2.port)
                assert not await v2.validate("dead")
                assert not await v2.validate(None)  # no join attempted

        loop.run_until_complete(run())

    def test_protocol_error_raises(self, loop):
        async def run():
            async def bad_server(reader, writer):
                writer.write(b"NOPE" + bytes(10))
                await writer.drain()
                writer.close()

            server = await asyncio.start_server(
                bad_server, "127.0.0.1", 0
            )
            port = server.sockets[0].getsockname()[1]
            client = Glacier2Client("127.0.0.1", port, timeout_s=2)
            with pytest.raises(IceProtocolError):
                await client.create_session("k", "k")
            server.close()
            await server.wait_closed()

        loop.run_until_complete(run())


def test_request_marshaling_shape():
    req = build_request(
        7, ("Glacier2", "router"), "createSession",
        marshal_two_strings("u", "p"),
    )
    assert req[:4] == b"IceP"
    assert req[8] == 0  # Request
    (total,) = struct.unpack("<i", req[10:14])
    assert total == len(req)
    (request_id,) = struct.unpack("<i", req[14:18])
    assert request_id == 7


def test_validator_caches_valid_keys(loop):
    async def run():
        async with FakeGlacier2(valid_keys={"k"}) as g:
            v = IceSessionValidator("127.0.0.1", g.port, cache_ttl_s=30)
            assert await v.validate("k")
            joins = len(g.requests)
            assert await v.validate("k")  # cache hit, no new join
            assert len(g.requests) == joins
            # denials are never cached
            assert not await v.validate("other")
            assert not await v.validate("other")
            assert len(g.requests) == joins + 2

    loop.run_until_complete(run())


def test_validator_is_a_session_validator():
    from omero_ms_pixel_buffer_tpu.auth.validator import SessionValidator

    assert issubclass(IceSessionValidator, SessionValidator)


def test_validator_single_flight(loop):
    """Concurrent cold-cache validations of one key perform ONE join."""

    async def run():
        async with FakeGlacier2(valid_keys={"k"}) as g:
            v = IceSessionValidator("127.0.0.1", g.port)
            results = await asyncio.gather(
                *[v.validate("k") for _ in range(16)]
            )
            assert all(results)
            assert len(g.requests) == 1

    loop.run_until_complete(run())


def test_single_flight_survives_waiter_cancellation(loop):
    """A waiter (or the first caller) being cancelled must not poison
    the shared join for the others."""

    async def run():
        async with FakeGlacier2(valid_keys={"k"}) as g:
            v = IceSessionValidator("127.0.0.1", g.port)
            first = asyncio.ensure_future(v.validate("k"))
            await asyncio.sleep(0)  # let the join task start
            first.cancel()
            try:
                await first
            except asyncio.CancelledError:
                pass
            # others still complete from the surviving join task
            assert await v.validate("k")
            assert len(g.requests) == 1

    loop.run_until_complete(run())


def test_validator_ttl_zero_joins_per_request(loop):
    """cache_ttl_s=0 restores the reference's per-request Glacier2 join
    (PixelBufferVerticle.java:106-110): no caching, no merging."""

    async def run():
        async with FakeGlacier2(valid_keys={"k"}) as g:
            v = IceSessionValidator("127.0.0.1", g.port, cache_ttl_s=0)
            assert await v.validate("k")
            assert await v.validate("k")
            assert len(g.requests) == 2

    loop.run_until_complete(run())
